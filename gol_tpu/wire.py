"""Wire protocol for the controller↔engine control plane.

The reference uses Go net/rpc over HTTP with gob encoding
(`Server/gol/distributor.go:229-245`); the TPU-native equivalent keeps the
same 5-method semantic surface (SURVEY §2d) over a deliberately thin
transport: 4-byte big-endian length prefix + JSON header, with board
payloads appended as raw bytes after the header.

Message: { "method"/"ok": ..., ...fields..., "world": {...}? } followed by
the board payload when "world" is present.

Codecs & capability negotiation (PR 5)
--------------------------------------
Board payloads are framed by a codec named in the world dict:

  world = {"h": H, "w": W, "codec": C, "nbytes": N[, "basis_turn": T]}

  codec          payload                                    size
  -------------  -----------------------------------------  --------------
  u8             raw {0,255} pixel bytes, row-major         H*W
  packed         32 cells/word, LSB-first little-endian     H*ceil(W/32)*4
                 word bytes (`ops/bitpack` layout)
  u8+zlib        zlib(level 1) of the u8 payload            < H*W
  packed+zlib    zlib(level 1) of the packed payload        < packed
  xrle           XOR-delta vs the receiver's previous       <= H*W
                 frame ("basis_turn" names it), run-length
                 tokens `<II`(skip, litlen) + litlen bytes
  f32            raw little-endian float32 state, row-major  H*W*4
                 (continuous boards — Lenia; PR 20)
  f32+zlib       zlib(level 1) of the f32 payload           < H*W*4

Raw u8 is the universal fallback: an uncompressed u8 frame's payload is
exactly H*W bytes and pre-PR-5 receivers ignore unknown world keys, so
new senders interoperate with old peers by construction. Every OTHER
codec is only ever sent to a peer that advertised the matching
capability flag — requests carry `"caps": [...]`, replies echo the
server's caps, and `negotiate()` intersects them with `local_caps()`
(the GOL_WIRE_CAPS env allowlist; unset = all of packed, zlib, xrle).
Decoding is unconditional: everyone understands every codec on receive.

Senders of multi-GB snapshots use band-chunked Frames (the engine
overlaps the device→host copy of band i+1 with the socket send of band
i) instead of materializing one contiguous payload; zlib is only
attempted when the payload is at most GOL_WIRE_ZLIB_MAX (default 64 MiB)
since level-1 deflate of a multi-GB board would stall the send loop.

Hostile-input posture: header length is bounded by MAX_HEADER, h*w by
GOL_MAX_BOARD_CELLS (read per message), and every codec has an exact or
upper payload-size bound checked BEFORE the allocation — violations
raise `WireProtocolError`, a ConnectionError subclass distinct from
ordinary transport failures.

Durability methods (PR 3): `Checkpoint` (no fields) asks the engine for
a synchronous gol-ckpt/1 manifest checkpoint into ITS configured
directory and replies {"turn", "manifest"}; `RestoreRun` {"path"?}
adopts a checkpoint from within that directory (empty path = newest
durable) and replies {"turn"}. Checkpoint payloads never cross the
wire — only names and turns — so the methods stay O(header) regardless
of board size, and the server refuses path components that escape its
checkpoint directory ("denied:" error prefix).

Profiling (PR 4): `Profile` {"turns"?} arms an on-demand jax.profiler
capture of the next N engine turns into the server's configured
--profile-dir and replies {"armed", "turns", "dir"}; turns<=0 replies
{"status": ...} (the controller's snapshot) without arming. The peer
only ever picks the turn count — artifact paths are fixed server-side,
same containment posture as Checkpoint/RestoreRun.

Fleet runs (PR 7): `CreateRun` {"h", "w", "rule"?, "run_id"?,
"turns"?, "ckpt_every"?, "queue"?} (+ optional seed board payload)
admits a new resident run on a fleet server and replies {"run_id",
"state", "bucket", "turn"}; `ListRuns` replies {"runs": [...],
"summary": {...}}; `AttachRun` {"run_id"} replies that run's
description; `DestroyRun` {"run_id"} (PR 8) retires a fleet run,
releasing its admission budget so a queued waiter can promote, and
replies the run's final record. Every run-scoped method (`GetWorld`, `GetView`,
`Alivecount`, `CFput`, `DrainFlags`, `Checkpoint`, `Stats`,
`RestoreRun`) accepts an optional `"run_id"` header key routing it to
one resident run; a missing run_id means the legacy default run, so
capability-less pre-fleet peers behave bit-identically on a fleet
server. Run ids are validated by `valid_run_id` BEFORE they reach any
filesystem path (per-run checkpoint directories are keyed by run_id).

Trace context: when the sending thread has an open span (obs/trace.py)
and the header carries no explicit "tc", send_msg stamps the span's
compact context — `"tc": {"t": <trace_id>, "s": <span_id>}` — into the
header. The receiving dispatcher parses it with `trace.parse_context`
and parents its handler span under the sender's, which is the whole
cross-process propagation mechanism: one optional ~40-byte field.

Byte metering happens in `finally`: a transfer that dies mid-flight
still counts what it moved, so the byte counters stay honest during
exactly the connection failures they exist to explain. Message counters
still only count complete messages.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import time
import zlib
from typing import Callable, Iterable, Optional, Tuple

import numpy as np

from gol_tpu.obs import catalog as obs
from gol_tpu.obs import trace
from gol_tpu.ops.bitpack import WORD_BITS, pack_np, unpack_np
from gol_tpu.utils.envcfg import env_int

_LEN = struct.Struct(">I")
_XRLE_TOKEN = struct.Struct("<II")
MAX_HEADER = 1 << 20


def _chaos_enabled() -> bool:
    """One env lookup on the hot path; the chaos module (and its RNG
    state) is only imported/built when GOL_CHAOS is actually set."""
    return bool(os.environ.get("GOL_CHAOS"))

# Upper bound on h*w accepted from a peer before allocating: 2^35 cells
# covers the largest board the framework demonstrates (131072² = 2^34)
# with one doubling of headroom — a hostile or garbage header must not
# be able to trigger an arbitrary-size allocation. The reference trusts
# gob inside a VPC; a hand-rolled TCP plane bounds its inputs. Settable
# at RUNTIME via GOL_MAX_BOARD_CELLS (read per message, not frozen at
# import, so server processes can be reconfigured the same way SER/CONT
# are).
DEFAULT_MAX_BOARD_CELLS = 1 << 35

# Capability flags a peer may advertise; raw u8 needs no flag.
CAP_PACKED = "packed"
CAP_ZLIB = "zlib"
CAP_XRLE = "xrle"
# A peer advertising CAP_F32 accepts lossless float32 state frames
# (continuous boards — Lenia, PR 20). Senders without the flag get the
# quantized u8 view instead, which every peer ever shipped decodes.
CAP_F32 = "f32"
SUPPORTED_CAPS = frozenset({CAP_PACKED, CAP_ZLIB, CAP_XRLE, CAP_F32})

CODEC_U8 = "u8"
CODEC_PACKED = "packed"
CODEC_U8_ZLIB = "u8+zlib"
CODEC_PACKED_ZLIB = "packed+zlib"
CODEC_XRLE = "xrle"
CODEC_F32 = "f32"
CODEC_F32_ZLIB = "f32+zlib"
CODECS = frozenset({CODEC_U8, CODEC_PACKED, CODEC_U8_ZLIB,
                    CODEC_PACKED_ZLIB, CODEC_XRLE,
                    CODEC_F32, CODEC_F32_ZLIB})

ZLIB_LEVEL = 1
DEFAULT_ZLIB_MAX_BYTES = 64 << 20
DEFAULT_BAND_BYTES = 32 << 20
# xrle merges XOR runs separated by gaps of up to this many identical
# bytes into one literal segment — an 8-byte token per isolated changed
# byte would be worse than just shipping the short gap inline.
_XRLE_GAP = 16


# Pre-resolved metric children (PR 6): `.labels(...)` costs a label-set
# validation, a tuple build, and a family-lock acquisition per call —
# fine per RPC, not fine per frame on the streaming path. The label
# spaces here are tiny and closed (2 directions, 7 codecs), so resolve
# every child once at import and index a plain dict afterwards.
_BYTES_SENT = obs.WIRE_BYTES.labels(direction="sent")
_BYTES_RECV = obs.WIRE_BYTES.labels(direction="received")
_MSGS_SENT = obs.WIRE_MESSAGES.labels(direction="sent")
_MSGS_RECV = obs.WIRE_MESSAGES.labels(direction="received")
_FRAMES = {c: obs.WIRE_FRAMES.labels(codec=c) for c in CODECS}
_FRAME_BYTES = {c: obs.WIRE_FRAME_BYTES.labels(codec=c) for c in CODECS}
_ENCODE_SECONDS = {c: obs.WIRE_ENCODE_SECONDS.labels(codec=c)
                   for c in CODECS}
_DECODE_SECONDS = {c: obs.WIRE_DECODE_SECONDS.labels(codec=c)
                   for c in CODECS}


def max_board_cells() -> int:
    return env_int("GOL_MAX_BOARD_CELLS", DEFAULT_MAX_BOARD_CELLS)


def zlib_max_bytes() -> int:
    return env_int("GOL_WIRE_ZLIB_MAX", DEFAULT_ZLIB_MAX_BYTES)


def band_bytes() -> int:
    return max(1, env_int("GOL_WIRE_BAND_BYTES", DEFAULT_BAND_BYTES))


def words(w: int) -> int:
    """Packed words per row for a board of width w."""
    return -(-w // WORD_BITS)


# Run ids ride wire headers AND name per-run checkpoint directories
# (ckpt base / "run-<id>"), so the alphabet is restricted to filename-
# safe characters with no separators — a hostile run_id must not be
# able to traverse paths or mint unbounded metric labels.
RUN_ID_MAX = 64
_RUN_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def valid_run_id(run_id) -> bool:
    """True iff `run_id` is a protocol-legal run identifier."""
    return (isinstance(run_id, str)
            and 0 < len(run_id) <= RUN_ID_MAX
            and not run_id.startswith((".", "-"))
            and all(c in _RUN_ID_OK for c in run_id))


class WireProtocolError(ConnectionError):
    """A peer sent a frame that violates the protocol (oversized header,
    unknown codec, payload-size bound, corrupt delta …) — distinct from
    an honest transport failure, but still a ConnectionError so every
    existing shed-the-connection handler treats it correctly."""


def local_caps() -> frozenset:
    """Capabilities this process advertises/accepts for SENDING codecs.

    GOL_WIRE_CAPS is a comma-separated allowlist ("" = none: raw-u8
    only, the old-peer posture); unset means all supported caps. Read
    per call so tests and operators can flip it at runtime."""
    raw = os.environ.get("GOL_WIRE_CAPS")
    if raw is None:
        return SUPPORTED_CAPS
    return frozenset(
        t.strip() for t in raw.split(",") if t.strip()) & SUPPORTED_CAPS


# Negotiation/advert memos (PR 6): the request path used to rebuild the
# peer∩local frozenset and re-read + re-sort GOL_WIRE_CAPS on every
# message. Both are pure functions of (peer caps tuple, env value), so
# one dict lookup replaces the set algebra. Keyed on the raw env string:
# flipping GOL_WIRE_CAPS at runtime still takes effect immediately.
_NEGOTIATE_CACHE: dict = {}
_ADVERT_CACHE: dict = {}


def advertised_caps() -> list:
    """Sorted caps list for reply/request headers — the `"caps"` advert.
    Memoized per GOL_WIRE_CAPS value; returns a fresh list so callers
    may embed it in mutable headers."""
    raw = os.environ.get("GOL_WIRE_CAPS")
    got = _ADVERT_CACHE.get(raw)
    if got is None:
        if len(_ADVERT_CACHE) > 64:
            _ADVERT_CACHE.clear()
        got = tuple(sorted(local_caps()))
        _ADVERT_CACHE[raw] = got
    return list(got)


def negotiate(header: dict) -> frozenset:
    """Caps usable for the REPLY to this request: the peer's advertised
    list ∩ ours. A peer that advertises nothing (every pre-PR-5 client)
    negotiates the empty set and gets raw u8."""
    peer = header.get("caps")
    if not isinstance(peer, (list, tuple)):
        return frozenset()
    try:
        key = (tuple(peer), os.environ.get("GOL_WIRE_CAPS"))
        cached = _NEGOTIATE_CACHE.get(key)
    except TypeError:
        # Unhashable junk in a hostile caps list — negotiate uncached.
        key = cached = None
    if cached is None:
        cached = frozenset(
            c for c in peer if isinstance(c, str)) & local_caps()
        if key is not None:
            if len(_NEGOTIATE_CACHE) > 256:
                _NEGOTIATE_CACHE.clear()
            _NEGOTIATE_CACHE[key] = cached
    return cached


class ConnectionEncoder:
    """Per-connection precomputed encode state (PR 6): the negotiated
    caps for frames TO this peer and the caps advert for headers, both
    resolved once at connection setup instead of per reply. The server
    builds one per accepted connection; anything that later streams
    frames on that socket (snapshot replies, live-view pushes) reuses
    `caps` without touching the environment or the peer header again.

    PR 19 rides the same per-request object for wire-byte attribution:
    `bytes_in` is the request's on-wire size recomputed from the header
    (length prefix + canonical header dump + the payload size the
    framing rules imply — deterministic, no tap on the recv path), and
    `bytes_out` accumulates send_msg return values so the server can
    charge the run named in the header once the reply is down."""

    __slots__ = ("caps", "advert", "bytes_in", "bytes_out")

    def __init__(self, header: Optional[dict] = None) -> None:
        self.caps = negotiate(header) if header is not None \
            else frozenset()
        self.advert = advertised_caps()
        self.bytes_out = 0
        if header is None:
            self.bytes_in = 0
        else:
            try:
                self.bytes_in = 4 + len(json.dumps(
                    header, separators=(",", ":"))) \
                    + payload_nbytes(header)
            except (WireProtocolError, TypeError, ValueError):
                self.bytes_in = 0

    def stamp(self, header: dict) -> dict:
        """Add this connection's caps advert to a reply header."""
        header.setdefault("caps", self.advert)
        return header


def enable_nodelay(sock: socket.socket) -> None:
    """TCP_NODELAY, best-effort: small control RPCs (flag/ping/alive)
    must not eat Nagle delays queued behind board payloads. A no-op on
    non-TCP sockets (AF_UNIX socketpairs in tests)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass


class _Tally:
    """Mutable byte count shared with a `finally` meter."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0


class Frame:
    """One encoded board payload: a codec, its header metadata, and an
    iterable of byte chunks summing to exactly `nbytes`. `chunks` may be
    a lazy generator — band-chunked senders encode while earlier chunks
    are already on the wire. `raw_nbytes` is the u8-pixel equivalent
    (h*w), the denominator of the bytes-saved/compression-ratio metrics.
    `encode_s` accrues encode time (lazy chunk producers add to it as
    they run)."""

    __slots__ = ("codec", "h", "w", "nbytes", "raw_nbytes", "chunks",
                 "extra", "encode_s")

    def __init__(self, codec: str, h: int, w: int, nbytes: int,
                 raw_nbytes: int, chunks, extra: Optional[dict] = None,
                 encode_s: float = 0.0) -> None:
        self.codec = codec
        self.h = h
        self.w = w
        self.nbytes = nbytes
        self.raw_nbytes = raw_nbytes
        self.chunks = chunks
        self.extra = extra
        self.encode_s = encode_s

    def meta(self) -> dict:
        m = {"h": self.h, "w": self.w, "codec": self.codec,
             "nbytes": self.nbytes}
        if self.extra:
            m.update(self.extra)
        return m


def _build_frame(codec: str, h: int, w: int, nbytes: int, raw_nbytes: int,
                 caps: frozenset,
                 band_iter_factory: Callable[["Frame"], Iterable],
                 extra: Optional[dict] = None) -> Frame:
    """Assemble a Frame from a base codec + chunk producer, layering zlib
    when negotiated and worthwhile. Compression drains the producer
    eagerly (bounded by zlib_max_bytes, checked by the caller passing a
    small-enough nbytes), and falls back to the uncompressed chunks when
    level-1 deflate does not actually shrink the payload — so a zlib
    codec on the wire always means nbytes < base size, which the
    receiver enforces as a bound."""
    # Every encode funnels through here (encode_board, the band framers,
    # and encode_view_frame via its plain-codec base), so this counter is
    # the "did ANY wire-encode work happen" witness the no-viewer
    # turn-path test pins to zero.
    obs.WIRE_ENCODE_CALLS.inc()
    frame = Frame(codec, h, w, nbytes, raw_nbytes, None, extra)
    if CAP_ZLIB in caps \
            and codec in (CODEC_U8, CODEC_PACKED, CODEC_F32) \
            and nbytes <= zlib_max_bytes():
        t0 = time.perf_counter()
        co = zlib.compressobj(ZLIB_LEVEL)
        comp, clen, raw_chunks = [], 0, []
        for buf in band_iter_factory(frame):
            raw_chunks.append(buf)
            d = co.compress(buf)
            if d:
                comp.append(d)
                clen += len(d)
        d = co.flush()
        if d:
            comp.append(d)
            clen += len(d)
        frame.encode_s += time.perf_counter() - t0
        if clen < nbytes:
            frame.codec = codec + "+zlib"
            frame.nbytes = clen
            frame.chunks = comp
        else:
            frame.chunks = raw_chunks
        return frame
    frame.chunks = band_iter_factory(frame)
    return frame


def is_binary_pixels(world: np.ndarray) -> bool:
    """True iff every value is 0 or 255 — the life-like pixels contract.
    Generations boards carry gray levels and must never be bit-packed."""
    return not bool(np.any((world != 0) & (world != 255)))


def encode_board(world: np.ndarray, caps: frozenset = frozenset(), *,
                 binary: Optional[bool] = None) -> Frame:
    """Encode one host-resident {0..255} pixel board for the wire under
    the negotiated caps. `binary` short-circuits the is-it-{0,255} probe
    when the sender already knows (engines do; pass None to probe)."""
    if world.dtype != np.uint8 or world.ndim != 2:
        raise ValueError("world must be 2-D uint8")
    h, w = world.shape
    wp = words(w)
    t0 = time.perf_counter()
    # Packing a narrow board can EXPAND it (wp*4 > w for w < 26 wide
    # remnants); only pack when it actually wins.
    use_packed = CAP_PACKED in caps and wp * 4 < w
    if use_packed:
        if binary is None:
            binary = is_binary_pixels(world)
        use_packed = binary
    if use_packed:
        payload = pack_np(world)
        codec, nbytes = CODEC_PACKED, h * wp * 4
    else:
        payload = np.ascontiguousarray(world)
        codec, nbytes = CODEC_U8, h * w
    mv = memoryview(payload).cast("B")
    enc = time.perf_counter() - t0
    frame = _build_frame(codec, h, w, nbytes, h * w, caps,
                         lambda f: iter([mv]))
    frame.encode_s += enc
    return frame


def encode_board_f32(state: np.ndarray,
                     caps: frozenset = frozenset()) -> Frame:
    """Encode one host-resident float32 state board (continuous CA —
    Lenia) losslessly for the wire: raw little-endian '<f4' bytes,
    row-major, exactly h*w*4 of them, zlib-layered when negotiated.

    Only sent to peers that advertised CAP_F32 — callers without it
    must quantize to a u8 view and use `encode_board` instead (the
    engine's get_world_frame does exactly that)."""
    if state.ndim != 2:
        raise ValueError("state must be 2-D float32")
    if CAP_F32 not in caps:
        raise ValueError(
            "peer did not negotiate the f32 capability; send a "
            "quantized u8 view via encode_board instead")
    h, w = state.shape
    t0 = time.perf_counter()
    payload = np.ascontiguousarray(state, dtype="<f4")
    mv = memoryview(payload).cast("B")
    enc = time.perf_counter() - t0
    frame = _build_frame(CODEC_F32, h, w, h * w * 4, h * w * 4, caps,
                         lambda f: iter([mv]))
    frame.encode_s += enc
    return frame


def packed_words_frame(h: int, w: int, word_bands: Iterable[np.ndarray],
                       caps: frozenset) -> Frame:
    """Frame a board already in packed-words form: `word_bands` yields
    (rows, ceil(w/32)) uint32 host arrays covering rows 0..h in order —
    the engine's banded device_get generator plugs in directly, so the
    board is never unpacked on device OR host. Peers that never
    negotiated CAP_PACKED get each band unpacked host-side into the
    universal raw-u8 codec instead. Lazy unless zlib drains it (only
    for payloads ≤ zlib_max_bytes)."""
    from gol_tpu.ops.bitpack import unpack_np, words_bytes_np

    if CAP_PACKED not in caps:
        def px_bands():
            for band in word_bands:
                yield unpack_np(words_bytes_np(band), band.shape[0], w)
        return u8_band_frame(h, w, px_bands(), caps, binary=True,
                             values01=True)

    nbytes = h * words(w) * 4

    def bands(frame: Frame):
        got_rows = 0
        for band in word_bands:
            t0 = time.perf_counter()
            mv = memoryview(words_bytes_np(band)).cast("B")
            frame.encode_s += time.perf_counter() - t0
            got_rows += band.shape[0]
            yield mv
        if got_rows != h:
            raise RuntimeError(
                f"packed bands covered {got_rows} rows, board has {h}")

    return _build_frame(CODEC_PACKED, h, w, nbytes, h * w, caps, bands)


def u8_band_frame(h: int, w: int, bands: Iterable[np.ndarray],
                  caps: frozenset, *, binary: bool,
                  values01: bool = False) -> Frame:
    """Frame a board streamed as uint8 row bands ((rows, w) host arrays
    covering rows 0..h in order). When the peer takes packed frames and
    the board is binary, each band is bit-packed host-side as it
    arrives (any nonzero counts as alive, so {0,1} cell bands need no
    ×255 materialization first); otherwise raw pixels, scaled from
    {0,1} per band when `values01`."""
    wp = words(w)
    use_packed = binary and CAP_PACKED in caps and wp * 4 < w

    if use_packed:
        def bands_iter(frame: Frame):
            for band in bands:
                t0 = time.perf_counter()
                mv = memoryview(pack_np(band)).cast("B")
                frame.encode_s += time.perf_counter() - t0
                yield mv
        return _build_frame(CODEC_PACKED, h, w, h * wp * 4, h * w, caps,
                            bands_iter)

    def bands_iter(frame: Frame):
        for band in bands:
            t0 = time.perf_counter()
            px = band * np.uint8(255) if values01 else band
            mv = memoryview(np.ascontiguousarray(px)).cast("B")
            frame.encode_s += time.perf_counter() - t0
            yield mv
    return _build_frame(CODEC_U8, h, w, h * w, h * w, caps, bands_iter)


def xrle_encode(cur: np.ndarray, basis: np.ndarray) -> Optional[bytes]:
    """XOR-delta + run-length encode `cur` against `basis` (same shape):
    tokens of `<II`(skip, litlen) each followed by litlen XOR bytes,
    over the row-major flattening. b"" means the frames are identical.
    Returns None when the delta would not beat shipping the raw board —
    the caller falls back to a plain codec."""
    a = np.ascontiguousarray(cur).reshape(-1)
    if a.size >= 1 << 32:  # token fields are u32
        return None
    x = a ^ np.ascontiguousarray(basis).reshape(-1)
    nz = np.flatnonzero(x)
    if nz.size == 0:
        return b""
    breaks = np.flatnonzero(np.diff(nz) > _XRLE_GAP)
    seg_starts = np.concatenate(([0], breaks + 1))
    seg_ends = np.concatenate((breaks, [nz.size - 1]))
    out = bytearray()
    pos = 0
    for s_i, e_i in zip(seg_starts, seg_ends):
        s = int(nz[s_i])
        e = int(nz[e_i]) + 1
        out += _XRLE_TOKEN.pack(s - pos, e - s)
        out += x[s:e].tobytes()
        pos = e
        if len(out) >= a.size:
            return None
    return bytes(out)


def xrle_decode(payload, h: int, w: int, basis: np.ndarray) -> np.ndarray:
    """Apply an xrle delta to the previous frame. Every token is bounds-
    checked against both the payload and the board before any write."""
    if basis.shape != (h, w) or basis.dtype != np.uint8:
        raise WireProtocolError("xrle frame without matching basis")
    n = h * w
    out = np.empty(n, dtype=np.uint8)
    out[:] = np.ascontiguousarray(basis).reshape(-1)
    buf = memoryview(payload)
    total = len(buf)
    pos = off = 0
    while off < total:
        if total - off < _XRLE_TOKEN.size:
            raise WireProtocolError("xrle: truncated token")
        skip, lit = _XRLE_TOKEN.unpack_from(buf, off)
        off += _XRLE_TOKEN.size
        pos += skip
        if lit == 0 or pos + lit > n or off + lit > total:
            raise WireProtocolError("xrle: segment out of bounds")
        out[pos:pos + lit] ^= np.frombuffer(buf, np.uint8, lit, off)
        pos += lit
        off += lit
    return out.reshape(h, w)


def encode_view_frame(view: np.ndarray, caps: frozenset, *,
                      basis: Optional[np.ndarray] = None,
                      basis_turn=None,
                      binary: Optional[bool] = None) -> Frame:
    """Encode a live-view frame, preferring an xrle delta against the
    receiver's previous frame when one is negotiated and available —
    consecutive GoL frames are nearly identical, so the SDL path usually
    ships a few hundred bytes instead of the board. Falls back to the
    best plain codec whenever the delta loses."""
    plain = encode_board(view, caps, binary=binary)
    if CAP_XRLE in caps and basis is not None \
            and basis.shape == view.shape:
        t0 = time.perf_counter()
        delta = xrle_encode(view, basis)
        dt = time.perf_counter() - t0
        if delta is not None and len(delta) < plain.nbytes:
            h, w = view.shape
            return Frame(CODEC_XRLE, h, w, len(delta), h * w, [delta],
                         extra={"basis_turn": basis_turn}, encode_s=dt)
    return plain


def _recv_exact(sock: socket.socket, n: int,
                tally: Optional[_Tally] = None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
        if tally is not None:
            tally.n += len(chunk)
    return bytes(buf)


def _recv_into(sock: socket.socket, mv: memoryview, tally: _Tally) -> None:
    got = 0
    total = len(mv)
    while got < total:
        n_read = sock.recv_into(mv[got:])
        if n_read == 0:
            raise ConnectionError("peer closed mid-message")
        got += n_read
        tally.n += n_read


def send_msg(
    sock: socket.socket, header: dict, world: Optional[np.ndarray] = None,
    frame: Optional[Frame] = None,
) -> int:
    """Send one message; returns the bytes put on the wire.

    `world` is the legacy raw-u8 path (payload = the board's own buffer,
    exactly h*w bytes, no codec key — understood by every peer ever
    shipped); `frame` is a codec-aware Frame from the encode_* builders."""
    if world is not None and frame is not None:
        raise ValueError("pass either world or frame, not both")
    header = dict(header)
    if "tc" not in header:
        tc = trace.context()
        if tc is not None:
            header["tc"] = tc
    payload = None
    if frame is not None:
        header["world"] = frame.meta()
    elif world is not None:
        if world.dtype != np.uint8 or world.ndim != 2:
            raise ValueError("world must be 2-D uint8")
        h, w = world.shape
        header["world"] = {"h": int(h), "w": int(w)}
        # Send the board's own buffer — tobytes() + concatenation would
        # transiently double a multi-GB snapshot.
        payload = memoryview(np.ascontiguousarray(world)).cast("B")
    raw = json.dumps(header).encode()
    head = memoryview(_LEN.pack(len(raw)) + raw)
    if _chaos_enabled():
        from gol_tpu import chaos
        head = memoryview(chaos.send_hook(sock, bytes(head)))
    sent = 0
    try:
        # send() loops instead of sendall() so a connection that dies
        # mid-payload still tells us how many bytes made it out.
        while sent < len(head):
            sent += sock.send(head[sent:])
        if payload is not None:
            off = 0
            while off < payload.nbytes:
                n = sock.send(payload[off:])
                off += n
                sent += n
        elif frame is not None:
            paid = 0
            for chunk in frame.chunks:
                mv = memoryview(chunk)
                if mv.ndim != 1 or mv.itemsize != 1:
                    mv = mv.cast("B")
                off = 0
                while off < mv.nbytes:
                    n = sock.send(mv[off:])
                    off += n
                    sent += n
                paid += mv.nbytes
            if paid != frame.nbytes:
                raise RuntimeError(
                    f"frame chunks produced {paid} bytes, header "
                    f"promised {frame.nbytes}")
    finally:
        if sent:
            _BYTES_SENT.inc(sent)
    _MSGS_SENT.inc()
    if frame is not None:
        _FRAMES[frame.codec].inc()
        _FRAME_BYTES[frame.codec].inc(frame.nbytes)
        if frame.raw_nbytes > frame.nbytes:
            obs.WIRE_BYTES_SAVED.inc(frame.raw_nbytes - frame.nbytes)
        if frame.nbytes:
            obs.WIRE_COMPRESSION_RATIO.set(frame.raw_nbytes / frame.nbytes)
        _ENCODE_SECONDS[frame.codec].observe(frame.encode_s)
    return sent


def _decode_packed(buf, h: int, w: int) -> np.ndarray:
    px = unpack_np(buf, h, w)
    px *= 255  # pixels contract: life-like boards materialize as {0,255}
    return px


def _recv_frame(sock: socket.socket, codec: str, meta: dict, h: int,
                w: int, tally: _Tally, xrle_basis) -> np.ndarray:
    if codec not in CODECS:
        raise WireProtocolError(f"unknown codec: {codec!r}")
    try:
        nbytes = int(meta["nbytes"])
    except (TypeError, KeyError, ValueError) as e:
        raise WireProtocolError(f"malformed frame size: {e}") from e
    wp = words(w)
    lo, hi = {
        CODEC_U8: (h * w, h * w),
        CODEC_PACKED: (h * wp * 4, h * wp * 4),
        # a conforming sender only ships zlib when it SHRANK the payload
        CODEC_U8_ZLIB: (1, h * w - 1),
        CODEC_PACKED_ZLIB: (1, h * wp * 4 - 1),
        CODEC_XRLE: (0, h * w - 1),
        CODEC_F32: (h * w * 4, h * w * 4),
        CODEC_F32_ZLIB: (1, h * w * 4 - 1),
    }[codec]
    if not lo <= nbytes <= hi:
        raise WireProtocolError(
            f"frame size out of bounds for {codec}: {nbytes} "
            f"(board {h}x{w})")
    buf = np.empty(nbytes, dtype=np.uint8)
    if nbytes:
        _recv_into(sock, memoryview(buf).cast("B"), tally)
    t0 = time.perf_counter()
    if codec == CODEC_U8:
        world = buf.reshape(h, w)
    elif codec == CODEC_PACKED:
        world = _decode_packed(buf, h, w)
    elif codec == CODEC_F32:
        world = buf.view(np.dtype("<f4")).reshape(h, w)
    elif codec in (CODEC_U8_ZLIB, CODEC_PACKED_ZLIB, CODEC_F32_ZLIB):
        base = {CODEC_U8_ZLIB: h * w,
                CODEC_PACKED_ZLIB: h * wp * 4,
                CODEC_F32_ZLIB: h * w * 4}[codec]
        de = zlib.decompressobj()
        try:
            raw = de.decompress(buf, base)
        except zlib.error as e:
            raise WireProtocolError(f"zlib payload corrupt: {e}") from e
        # max_length bounds the inflation; anything beyond the declared
        # base size (zlib bomb) or short of it (truncated) is protocol
        # garbage, not a transport error.
        if len(raw) != base or de.unconsumed_tail or not de.eof:
            raise WireProtocolError(
                f"zlib payload decodes to {len(raw)} bytes, want {base}")
        if codec == CODEC_U8_ZLIB:
            world = np.frombuffer(raw, np.uint8).reshape(h, w).copy()
        elif codec == CODEC_F32_ZLIB:
            world = np.frombuffer(raw, "<f4").reshape(h, w).copy()
        else:
            world = _decode_packed(np.frombuffer(raw, np.uint8), h, w)
    else:  # xrle
        if xrle_basis is None \
                or xrle_basis[0] != meta.get("basis_turn"):
            raise WireProtocolError("xrle frame without matching basis")
        world = xrle_decode(buf, h, w, xrle_basis[1])
    _DECODE_SECONDS[codec].observe(time.perf_counter() - t0)
    return world


def recv_msg(sock: socket.socket,
             xrle_basis=None) -> Tuple[dict, Optional[np.ndarray]]:
    """Receive one message → (header, decoded board or None).

    `xrle_basis` = (basis_turn, previous frame ndarray) authorizes xrle
    decoding — only the call sites that kept their previous frame (the
    live-view client) pass it; an unsolicited delta is a protocol error."""
    if _chaos_enabled():
        from gol_tpu import chaos
        chaos.recv_hook(sock)
    tally = _Tally()
    try:
        (n,) = _LEN.unpack(_recv_exact(sock, 4, tally))
        if n > MAX_HEADER:
            raise WireProtocolError(f"header too large: {n}")
        raw = _recv_exact(sock, n, tally)
        try:
            header = json.loads(raw)
        except ValueError as e:  # bad UTF-8 or bad JSON — peer is garbage
            raise WireProtocolError(f"malformed header: {e}") from e
        if not isinstance(header, dict):
            raise WireProtocolError(
                f"malformed header: expected object, "
                f"got {type(header).__name__}")
        world = None
        meta = header.get("world")
        if meta is not None:
            try:
                h = int(meta["h"])
                w = int(meta["w"])
            except (TypeError, KeyError, ValueError) as e:
                raise WireProtocolError(
                    f"malformed world dims: {e}") from e
            if h <= 0 or w <= 0 or h * w > max_board_cells():
                raise WireProtocolError(
                    f"board dims out of bounds: {h}x{w}")
            codec = meta.get("codec", CODEC_U8)
            if codec == CODEC_U8 and "nbytes" not in meta:
                # Legacy peer: exactly h*w raw bytes. Receive straight
                # into the final array — going through bytes would peak
                # at ~3x the payload for a multi-GB snapshot.
                world = np.empty((h, w), dtype=np.uint8)
                _recv_into(sock, memoryview(world).cast("B"), tally)
            else:
                world = _recv_frame(sock, codec, meta, h, w, tally,
                                    xrle_basis)
    finally:
        if tally.n:
            _BYTES_RECV.inc(tally.n)
    _MSGS_RECV.inc()
    return header, world


# -- raw relay (federation router) ------------------------------------
#
# The router tier proxies RPCs without re-encoding them: it parses the
# JSON header only to route (method, run_id, req_id) and to compute how
# many payload bytes follow, then forwards every byte verbatim. That is
# what keeps the PR-10 req_id dedupe semantics, the tc trace context,
# and the negotiated codecs (including per-viewer xrle bases) intact
# across the proxy — the member sees the client's exact bytes, and vice
# versa. None of this touches send_msg/recv_msg: a routerless
# single-server deployment stays byte-for-byte unchanged on the wire.
#
# Live migration (PR 15) rides the same framing: a Rescale transfer
# ships the board as an ordinary CAP_PACKED frame (ReceiveRun), and a
# source member answering after its copy retired replies a "moved:"
# error — which the client treats as a TAGGED transport failure and
# retries through the router, whose PinRun placement already points at
# the new owner. The router never records "moved:" replies in its
# dedupe window (they are routing artifacts, not commit outcomes).

def recv_head_raw(sock: socket.socket) -> Tuple[dict, bytes]:
    """Receive one message's framed header WITHOUT consuming its board
    payload: (parsed header, the raw framed bytes — 4-byte length
    prefix + JSON exactly as the peer sent them). Pair with
    `payload_nbytes` + `relay_payload` to stream the rest."""
    tally = _Tally()
    try:
        (n,) = _LEN.unpack(_recv_exact(sock, 4, tally))
        if n > MAX_HEADER:
            raise WireProtocolError(f"header too large: {n}")
        raw = _recv_exact(sock, n, tally)
        try:
            header = json.loads(raw)
        except ValueError as e:
            raise WireProtocolError(f"malformed header: {e}") from e
        if not isinstance(header, dict):
            raise WireProtocolError(
                f"malformed header: expected object, "
                f"got {type(header).__name__}")
    finally:
        if tally.n:
            _BYTES_RECV.inc(tally.n)
    _MSGS_RECV.inc()
    return header, _LEN.pack(n) + raw


def payload_nbytes(header: dict) -> int:
    """Board-payload byte count implied by a received header (0 when it
    carries no `world` meta). Mirrors recv_msg's framing rules — same
    dim bounds, same per-codec size envelope — without decoding, so a
    relay can refuse malformed framing exactly where recv_msg would."""
    meta = header.get("world")
    if meta is None:
        return 0
    try:
        h = int(meta["h"])
        w = int(meta["w"])
    except (TypeError, KeyError, ValueError) as e:
        raise WireProtocolError(f"malformed world dims: {e}") from e
    if h <= 0 or w <= 0 or h * w > max_board_cells():
        raise WireProtocolError(f"board dims out of bounds: {h}x{w}")
    codec = meta.get("codec", CODEC_U8)
    if codec == CODEC_U8 and "nbytes" not in meta:
        return h * w  # legacy raw-u8 peer
    if codec not in CODECS:
        raise WireProtocolError(f"unknown codec: {codec!r}")
    try:
        nbytes = int(meta["nbytes"])
    except (TypeError, KeyError, ValueError) as e:
        raise WireProtocolError(f"malformed frame size: {e}") from e
    wp = words(w)
    lo, hi = {
        CODEC_U8: (h * w, h * w),
        CODEC_PACKED: (h * wp * 4, h * wp * 4),
        CODEC_U8_ZLIB: (1, h * w - 1),
        CODEC_PACKED_ZLIB: (1, h * wp * 4 - 1),
        CODEC_XRLE: (0, h * w - 1),
        CODEC_F32: (h * w * 4, h * w * 4),
        CODEC_F32_ZLIB: (1, h * w * 4 - 1),
    }[codec]
    if not lo <= nbytes <= hi:
        raise WireProtocolError(
            f"frame size out of bounds for {codec}: {nbytes} "
            f"(board {h}x{w})")
    return nbytes


def send_raw(sock: socket.socket, raw: bytes) -> None:
    """Put already-framed message bytes on the wire verbatim."""
    sock.sendall(raw)
    _BYTES_SENT.inc(len(raw))
    _MSGS_SENT.inc()


def frame_header(header: dict) -> bytes:
    """Frame a header dict exactly as send_msg would (length prefix +
    JSON), for relays that must rewrite one field (e.g. the router
    stamping a generated run_id into CreateRun) before forwarding."""
    raw = json.dumps(header).encode()
    if len(raw) > MAX_HEADER:
        raise WireProtocolError(f"header too large: {len(raw)}")
    return _LEN.pack(len(raw)) + raw


def freeze_message(header: dict, frame: Optional[Frame] = None) -> bytes:
    """Materialize one complete message (framed header + the frame's
    payload) into a single ready-to-send bytes object.

    This is the broadcast tier's half of send_msg: the epoch stream
    freezes each published frame ONCE and the gateway then writes the
    same immutable buffer to every subscriber socket, so fan-out cost
    is sendmsg syscalls, not re-encoding. The encode-side frame
    families (gol_wire_frames_total, frame bytes/saved/ratio, encode
    seconds) are metered here exactly once per freeze; the per-send
    byte accounting is the sender's job (the gateway batches it into
    gol_wire_bytes_total / gol_bcast_sent_bytes_total)."""
    if frame is None:
        return frame_header(header)
    header = dict(header)
    header["world"] = frame.meta()
    head = frame_header(header)
    parts = [head]
    paid = 0
    for chunk in frame.chunks:
        mv = memoryview(chunk)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        parts.append(bytes(mv))
        paid += mv.nbytes
    if paid != frame.nbytes:
        raise RuntimeError(
            f"frame chunks produced {paid} bytes, header promised "
            f"{frame.nbytes}")
    _FRAMES[frame.codec].inc()
    _FRAME_BYTES[frame.codec].inc(frame.nbytes)
    if frame.raw_nbytes > frame.nbytes:
        obs.WIRE_BYTES_SAVED.inc(frame.raw_nbytes - frame.nbytes)
    if frame.nbytes:
        obs.WIRE_COMPRESSION_RATIO.set(frame.raw_nbytes / frame.nbytes)
    _ENCODE_SECONDS[frame.codec].observe(frame.encode_s)
    return b"".join(parts)


def relay_payload(src: socket.socket, dst: socket.socket,
                  nbytes: int, chunk: int = 1 << 20) -> None:
    """Stream exactly `nbytes` of payload from src to dst, verbatim."""
    left = int(nbytes)
    moved = 0
    try:
        while left:
            buf = src.recv(min(left, chunk))
            if not buf:
                raise ConnectionError("peer closed mid-payload")
            dst.sendall(buf)
            left -= len(buf)
            moved += len(buf)
    finally:
        if moved:
            _BYTES_RECV.inc(moved)
            _BYTES_SENT.inc(moved)
