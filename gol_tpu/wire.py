"""Wire protocol for the controller↔engine control plane.

The reference uses Go net/rpc over HTTP with gob encoding
(`Server/gol/distributor.go:229-245`); the TPU-native equivalent keeps the
same 5-method semantic surface (SURVEY §2d) over a deliberately thin
transport: 4-byte big-endian length prefix + JSON header, with board
payloads appended as raw bytes after the header (a {0,255} board is already
its own densest trivial encoding — no base64, no gob).

Message: { "method"/"ok": ..., ...fields..., "world": {"h": H, "w": W}? }
followed by exactly H*W raw payload bytes when "world" is present.

Durability methods (PR 3): `Checkpoint` (no fields) asks the engine for
a synchronous gol-ckpt/1 manifest checkpoint into ITS configured
directory and replies {"turn", "manifest"}; `RestoreRun` {"path"?}
adopts a checkpoint from within that directory (empty path = newest
durable) and replies {"turn"}. Checkpoint payloads never cross the
wire — only names and turns — so the methods stay O(header) regardless
of board size, and the server refuses path components that escape its
checkpoint directory ("denied:" error prefix).

Profiling (PR 4): `Profile` {"turns"?} arms an on-demand jax.profiler
capture of the next N engine turns into the server's configured
--profile-dir and replies {"armed", "turns", "dir"}; turns<=0 replies
{"status": ...} (the controller's snapshot) without arming. The peer
only ever picks the turn count — artifact paths are fixed server-side,
same containment posture as Checkpoint/RestoreRun.

Trace context: when the sending thread has an open span (obs/trace.py)
and the header carries no explicit "tc", send_msg stamps the span's
compact context — `"tc": {"t": <trace_id>, "s": <span_id>}` — into the
header. The receiving dispatcher parses it with `trace.parse_context`
and parents its handler span under the sender's, which is the whole
cross-process propagation mechanism: one optional ~40-byte field.

Byte metering happens in `finally`: a transfer that dies mid-flight
still counts what it moved, so the byte counters stay honest during
exactly the connection failures they exist to explain. Message counters
still only count complete messages.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

import numpy as np

from gol_tpu.obs import catalog as obs
from gol_tpu.obs import trace
from gol_tpu.utils.envcfg import env_int

_LEN = struct.Struct(">I")
MAX_HEADER = 1 << 20
# Upper bound on h*w accepted from a peer before allocating: 2^35 cells
# covers the largest board the framework demonstrates (131072² = 2^34)
# with one doubling of headroom — a hostile or garbage header must not
# be able to trigger an arbitrary-size allocation. The reference trusts
# gob inside a VPC; a hand-rolled TCP plane bounds its inputs. Settable
# at RUNTIME via GOL_MAX_BOARD_CELLS (read per message, not frozen at
# import, so server processes can be reconfigured the same way SER/CONT
# are).
DEFAULT_MAX_BOARD_CELLS = 1 << 35


def max_board_cells() -> int:
    return env_int("GOL_MAX_BOARD_CELLS", DEFAULT_MAX_BOARD_CELLS)


class _Tally:
    """Mutable byte count shared with a `finally` meter."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0


def _recv_exact(sock: socket.socket, n: int,
                tally: Optional[_Tally] = None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
        if tally is not None:
            tally.n += len(chunk)
    return bytes(buf)


def send_msg(
    sock: socket.socket, header: dict, world: Optional[np.ndarray] = None
) -> int:
    """Send one message; returns the bytes put on the wire."""
    header = dict(header)
    if "tc" not in header:
        tc = trace.context()
        if tc is not None:
            header["tc"] = tc
    payload = None
    if world is not None:
        if world.dtype != np.uint8 or world.ndim != 2:
            raise ValueError("world must be 2-D uint8")
        h, w = world.shape
        header["world"] = {"h": int(h), "w": int(w)}
        # Send the board's own buffer — tobytes() + concatenation would
        # transiently double a multi-GB snapshot.
        payload = memoryview(np.ascontiguousarray(world)).cast("B")
    raw = json.dumps(header).encode()
    frame = memoryview(_LEN.pack(len(raw)) + raw)
    sent = 0
    try:
        # send() loops instead of sendall() so a connection that dies
        # mid-payload still tells us how many bytes made it out.
        while sent < len(frame):
            sent += sock.send(frame[sent:])
        if payload is not None:
            off = 0
            while off < payload.nbytes:
                n = sock.send(payload[off:])
                off += n
                sent += n
    finally:
        if sent:
            obs.WIRE_BYTES.labels(direction="sent").inc(sent)
    obs.WIRE_MESSAGES.labels(direction="sent").inc()
    return sent


def recv_msg(sock: socket.socket) -> Tuple[dict, Optional[np.ndarray]]:
    tally = _Tally()
    try:
        (n,) = _LEN.unpack(_recv_exact(sock, 4, tally))
        if n > MAX_HEADER:
            raise ConnectionError(f"header too large: {n}")
        raw = _recv_exact(sock, n, tally)
        try:
            header = json.loads(raw)
        except ValueError as e:  # bad UTF-8 or bad JSON — peer is garbage
            raise ConnectionError(f"malformed header: {e}") from e
        if not isinstance(header, dict):
            raise ConnectionError(
                f"malformed header: expected object, "
                f"got {type(header).__name__}")
        world = None
        if "world" in header and header["world"] is not None:
            try:
                h = int(header["world"]["h"])
                w = int(header["world"]["w"])
            except (TypeError, KeyError, ValueError) as e:
                raise ConnectionError(f"malformed world dims: {e}") from e
            if h <= 0 or w <= 0 or h * w > max_board_cells():
                raise ConnectionError(f"board dims out of bounds: {h}x{w}")
            # Receive straight into the final array — going through bytes
            # would peak at ~3x the payload for a multi-GB snapshot.
            world = np.empty((h, w), dtype=np.uint8)
            mv = memoryview(world).cast("B")
            got = 0
            while got < h * w:
                n_read = sock.recv_into(mv[got:])
                if n_read == 0:
                    raise ConnectionError("peer closed mid-message")
                got += n_read
                tally.n += n_read
    finally:
        if tally.n:
            obs.WIRE_BYTES.labels(direction="received").inc(tally.n)
    obs.WIRE_MESSAGES.labels(direction="received").inc()
    return header, world
