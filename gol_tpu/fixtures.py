"""Metadata about the seeded golden fixtures (`images/`, `check/`).

The 512² fixture board's ash is period-2 from well before turn 10⁴
(computed by the native u64 oracle; the analog of the reference board's
5565/5567 oscillation, `Local/count_test.go:43-49`). Consumers of the
oscillation gate — the telemetry contract test and the bench's engine
leg — share these constants so reseeding the fixture only needs one
update (regenerate via `tests/make_fixtures.py`, then re-derive with
`gol_tpu.native.step_torus`).
"""

# (even-turn alive count, odd-turn alive count) of the settled ash.
ASH_512_EVEN = 7527
ASH_512_ODD = 7525

# The ash is provably settled by here; gates keyed to ASH_512_* must not
# fire below this turn.
ASH_512_SETTLED_BY = 10_000


def ash_512_alive(turn: int) -> int:
    """Expected alive count of the settled 512² fixture at `turn`
    (valid for turn >= ASH_512_SETTLED_BY)."""
    return ASH_512_EVEN if turn % 2 == 0 else ASH_512_ODD
