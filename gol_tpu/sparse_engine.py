"""SparseEngine: the sparse-torus model behind the FULL control protocol.

r4 (VERDICT r3 weak #5/"next" #6): `models/sparse.SparseTorus` is the
kernel — episode-batched macro-steps over the live window of a 2^40-cell
torus — and this wraps it in the same 5-method-plus control surface as
the dense `Engine` (duck-typed: `server.EngineServer`, `client
.RemoteEngine` and the distributor drive either without caring which):

    server_distributor — blocking run; `world` is a SMALL seed board
                         whose live cells are stamped centred on the
                         torus, or None to resume the engine-held state
                         (the CONT=yes detach/reattach contract)
    alive_count        — (firing count, turn), published per chunk with
                         no device work on the poll path
    get_world          — live-window snapshot ({0,255} pixels)
    get_window         — (pixels, (ox, oy) torus origin, turn)
    cf_put/drain_flags — reference flag protocol (pause 0/quit 2/kill 5)
    abort_run/ping/stats/kill_prog, save/load_checkpoint

Chunking: the host wakes between adaptively-sized turn chunks (wall
target ~CHUNK_TARGET_SECONDS, same band discipline as the dense engine)
to honour flags and publish (window, origin, turn, alive) as one
coherent snapshot; inside a chunk `SparseTorus.run` batches macro-steps
into synchronization-free episodes.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from gol_tpu.engine import (
    CHUNK_TARGET_ENV,
    CHUNK_TARGET_SECONDS,
    CKPT_ENV,
    CKPT_EVERY_DEFAULT,
    CKPT_EVERY_ENV,
    MAX_CHUNK_ENV,
    ControlFlagProtocol,
    EngineBusy,
)
from gol_tpu.fleet.handles import SingleRunSurface
from gol_tpu.models.lifelike import CONWAY
from gol_tpu.models.sparse import SparseTorus
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import flight as obs_flight
from gol_tpu.ops.bitpack import WORD_BITS, unpack
from gol_tpu.utils.envcfg import env_float, env_int

SPARSE_CHUNK_MIN = 64
SPARSE_CHUNK_MAX = 1 << 16


class SparseEngine(SingleRunSurface, ControlFlagProtocol):
    def __init__(self, size: int, rule=CONWAY,
                 shards: Optional[int] = None) -> None:
        """`shards` (r5): row-shard the live window over this many
        devices (GOL_SPARSE_SHARDS from the environment when None;
        default 1) — raises the window's HBM ceiling by the device
        count via the same deep-halo ppermute ring the dense engine
        uses. Must divide 256 (the window row alignment) and the torus
        size."""
        from gol_tpu.models.lifelike import LifeLikeRule
        from gol_tpu.utils.envcfg import env_int as _env_int

        if not isinstance(rule, LifeLikeRule):
            # The live-window argument (sparse.py module doc) is a
            # 2-state property; a Generations rule would evolve wrongly.
            raise ValueError(
                f"sparse engine supports life-like rules only, "
                f"got {rule.rulestring!r}")
        if 0 in rule.born:
            # Mirror SparseTorus.__init__ (ADVICE r4): without this, a
            # B0 server starts cleanly and then fails every submit —
            # and a checkpoint restore would bypass the seed-time check
            # entirely and evolve silently wrongly.
            raise ValueError(
                f"rule {rule.rulestring} births on 0 neighbours; "
                "use the dense engine")
        if size % WORD_BITS != 0:
            raise ValueError(f"torus size {size} not a multiple of 32")
        self.size = size
        self._rule = rule
        if shards is None:
            shards = _env_int("GOL_SPARSE_SHARDS", 1)
        shards = max(1, min(shards, len(jax.devices())))
        if shards > 1:
            from gol_tpu.models.sparse import check_sparse_mesh
            from gol_tpu.parallel.mesh import make_mesh

            # Fail a bad shard count AT STARTUP (server banner time),
            # not as a per-submission error.
            check_sparse_mesh(shards, size)
            self._mesh = make_mesh(shards)
            self._devices = list(self._mesh.devices.flat)
        else:
            # Single-device fast path (the live window is one shard);
            # the attribute exists for surfaces that introspect any
            # engine's devices (server main's banner).
            self._mesh = None
            self._devices = [jax.devices()[0]]
        self._state_lock = threading.Lock()
        self._torus: Optional[SparseTorus] = None
        self._turn = 0
        # Published-per-chunk coherent snapshot: (packed handle, ox, oy,
        # turn, alive). Poll paths (ticker, get_window, checkpoint) read
        # this, never the mutating torus internals.
        self._pub: Optional[tuple] = None
        self._flags: "queue.Queue[int]" = queue.Queue()
        self._killed = False
        self._running = False
        self._run_token: Optional[str] = None
        self._abort = threading.Event()
        self._last_chunk = 0
        self._turns_per_s = 0.0
        self._chunk_overhead_us = 0.0

    # ------------------------------------------------------------------ RPC

    def server_distributor(
        self,
        params,
        world: Optional[np.ndarray],
        sub_workers: Sequence[str] = (),
        start_turn: int = 0,
        token: Optional[str] = None,
    ) -> Tuple[np.ndarray, int]:
        """Blocking sparse run. `world`: small seed board (its live cells
        are stamped centred on the torus) or None to resume the held
        state. Returns ({0,255} live-window pixels, completed turn)."""
        self._check_alive()
        if self._running:
            raise EngineBusy("engine already running a board")
        if world is not None:
            h0, w0 = world.shape
            ys, xs = np.nonzero(world)
            if len(xs) == 0:
                raise ValueError("seed board has no live cells")
            offx = (self.size - w0) // 2
            offy = (self.size - h0) // 2
            cells = [(int(x) + offx, int(y) + offy)
                     for x, y in zip(xs, ys)]
            torus = SparseTorus(self.size, cells, self._rule,
                                mesh=self._mesh)
        else:
            torus = None
        with self._state_lock:
            if self._running:
                raise EngineBusy("engine already running a board")
            if torus is not None:
                self._torus = torus
                self._turn = start_turn
            elif self._torus is None:
                raise RuntimeError("no sparse state to resume")
            self._running = True
            self._run_token = token
            self._abort.clear()
            self._publish_locked()

        target = start_turn + params.turns
        chunk_target = (env_float(CHUNK_TARGET_ENV, CHUNK_TARGET_SECONDS)
                        or CHUNK_TARGET_SECONDS)
        # GOL_MAX_CHUNK bounds flag/pause latency exactly as on the
        # dense engine (and is the tests' throttle).
        max_chunk = min(env_int(MAX_CHUNK_ENV, SPARSE_CHUNK_MAX),
                        SPARSE_CHUNK_MAX)
        chunk = min(SPARSE_CHUNK_MIN, max_chunk)
        quit_run = False
        ckpt_dir = os.environ.get(CKPT_ENV, "")
        ckpt_every = env_float(CKPT_EVERY_ENV, CKPT_EVERY_DEFAULT)
        ckpt_path = ""
        if ckpt_dir:
            os.makedirs(ckpt_dir, exist_ok=True)
            ckpt_path = os.path.join(
                ckpt_dir, f"sparse{self.size}x{self.size}.npz")
        last_ckpt = time.monotonic()
        # Turn-based manifest checkpointing, same contract as the dense
        # engine: chunk boundaries clamped onto checkpoint turns so the
        # cadence is timing-independent and resume-comparable.
        ckpt_writer = None
        next_ckpt_turn = None
        ckpt_every_turns = 0
        if ckpt_dir:
            from gol_tpu import ckpt as ckpt_mod

            ckpt_every_turns = env_int(
                ckpt_mod.CKPT_EVERY_TURNS_ENV, 0, minimum=0)
            if ckpt_every_turns > 0:
                ckpt_writer = ckpt_mod.CheckpointWriter(
                    ckpt_dir, run_id=obs_flight.RUN_ID,
                    keep_last=env_int(ckpt_mod.CKPT_KEEP_ENV,
                                      ckpt_mod.CKPT_KEEP_DEFAULT),
                    keep_every=env_int(ckpt_mod.CKPT_KEEP_EVERY_ENV, 0,
                                       minimum=0))
                next_ckpt_turn = (
                    start_turn // ckpt_every_turns + 1) * ckpt_every_turns

        def _ckpt_submit(trigger: str) -> None:
            ckpt_writer.submit(self._ckpt_snapshot(trigger))

        # Host-overhead accounting, same definition as the dense engine:
        # per-iteration wall time minus the device span (`elapsed`
        # already brackets run + the syncing alive_count) minus excluded
        # stalls (sync checkpoint saves, slow flag service).
        host_overhead = 0.0
        overhead_iters = 0
        try:
            while self._turn < target and not quit_run:
                if self._killed or self._abort.is_set():
                    break
                t_iter = time.monotonic()
                stall_excl = 0.0
                k = min(chunk, target - self._turn)
                if next_ckpt_turn is not None:
                    k = min(k, next_ckpt_turn - self._turn)
                t0 = time.monotonic()
                self._torus.run(k)
                # One poll-free (alive, turn) pair per chunk; fetching the
                # count also syncs the episode chain, making `elapsed` a
                # real wall measurement for the chunk adapter.
                alive = self._torus.alive_count()
                elapsed = time.monotonic() - t0
                with self._state_lock:
                    self._turn += k
                    self._last_chunk = k
                    if elapsed > 0:
                        self._turns_per_s = k / elapsed
                    self._publish_locked(alive)
                if elapsed < chunk_target and chunk * 2 <= max_chunk:
                    chunk *= 2
                elif elapsed > chunk_target * 2 and chunk > 1:
                    chunk //= 2
                if (next_ckpt_turn is not None
                        and self._turn >= next_ckpt_turn):
                    _ckpt_submit("periodic")
                    next_ckpt_turn = (
                        self._turn // ckpt_every_turns + 1
                    ) * ckpt_every_turns
                if ckpt_path and \
                        time.monotonic() - last_ckpt >= ckpt_every:
                    t_sync = time.monotonic()
                    self.save_checkpoint(ckpt_path)
                    last_ckpt = time.monotonic()
                    stall_excl += last_ckpt - t_sync
                if self._turn < target:
                    # Fast path: no pending flag, nothing to service —
                    # one deque truthiness check instead of a call into
                    # the queue machinery per chunk.
                    if (self._flags.queue or self._killed
                            or self._abort.is_set()):
                        t_flags = time.monotonic()
                        quit_run = self._handle_flags()
                        flag_cost = time.monotonic() - t_flags
                        if flag_cost > 0.01:
                            stall_excl += flag_cost
                host_overhead += max(
                    0.0,
                    time.monotonic() - t_iter - elapsed - stall_excl)
                overhead_iters += 1
            if ckpt_writer is not None and self._turn > start_turn:
                _ckpt_submit("final")
        except Exception:
            if ckpt_writer is not None:
                try:
                    ckpt_writer.write_sync(
                        self._ckpt_snapshot("emergency"))
                except Exception:
                    pass
            raise
        finally:
            if ckpt_writer is not None:
                ckpt_writer.close(timeout=60.0)
            if overhead_iters:
                self._chunk_overhead_us = (
                    host_overhead / overhead_iters * 1e6)
                obs.ENGINE_CHUNK_OVERHEAD_US.set(self._chunk_overhead_us)
            with self._state_lock:
                final_pub = self._pub
                final_turn = self._turn
                self._running = False
                self._run_token = None
                self._abort.clear()
        return self._window_pixels(final_pub), final_turn

    def alive_count(self) -> Tuple[int, int]:
        """(firing count, turn) from the last published chunk boundary —
        exact-at-turn, no device work on the poll path."""
        self._check_alive()
        with self._state_lock:
            if self._pub is None:
                return 0, self._turn
            _, _, _, turn, alive = self._pub
            return alive, turn

    def get_world(self) -> Tuple[np.ndarray, int]:
        """Live-window snapshot pixels (the sparse analog of the full
        board — the torus itself is up to 2^40 cells)."""
        pixels, _, turn = self.get_window()
        return pixels, turn

    def get_window(self) -> Tuple[np.ndarray, Tuple[int, int], int]:
        """(window pixels, (ox, oy) torus origin of window cell (0,0),
        completed turn)."""
        self._check_alive()
        with self._state_lock:
            pub = self._pub
        if pub is None:
            raise RuntimeError("no board loaded")
        return self._window_pixels(pub), (pub[1], pub[2]), pub[3]

    # Sparse frames are WINDOW-anchored and the origin moves as the
    # pattern grows — the wire layer must never delta-encode them (see
    # the get_view docstring); snapshots are always strict {0,255}.
    frames_diffable = False
    binary_pixels = True

    def get_world_frame(self, caps) -> Tuple[object, int]:
        """(wire.Frame, turn) for the live-window snapshot: the window
        is already a packed device array, so a packed-capable peer gets
        its words straight off the device — no unpack dispatch, no ×255
        pixel materialization — and everyone else gets the usual pixel
        encode."""
        from gol_tpu import wire

        self._check_alive()
        with self._state_lock:
            pub = self._pub
        if pub is None:
            raise RuntimeError("no board loaded")
        caps = frozenset(caps)
        packed, _, _, turn, _ = pub
        h, w = packed.shape[0], packed.shape[1] * WORD_BITS
        if wire.CAP_PACKED in caps:
            words = np.asarray(jax.device_get(packed))
            return wire.packed_words_frame(h, w, iter([words]), caps), turn
        return wire.encode_board(self._window_pixels(pub), caps,
                                 binary=True), turn

    def get_view(
        self, max_cells: int
    ) -> Tuple[np.ndarray, int, Tuple[int, int]]:
        """(window view, turn, (fy, fx)): the live window when it fits
        `max_cells`, else an on-device block-any-alive reduction — the
        same O(viewport) contract as the dense engine's GetView (a
        grown window is budget-bounded, not small: it can be GBs).

        Frames are WINDOW-anchored, and the window's torus origin moves
        as the pattern grows — consecutive frames of equal shape are
        NOT diffable against each other (unlike the dense engine's
        board-anchored frames). Incremental consumers must anchor on
        `get_window()`'s origin; the distributor's diffing live view
        stays disabled for sparse runs for exactly this reason
        (`distributor.py` sparse live_view guard)."""
        self._check_alive()
        with self._state_lock:
            pub = self._pub
        if pub is None:
            raise RuntimeError("no board loaded")
        packed, _, _, turn, _ = pub
        h, w = packed.shape[0], packed.shape[1] * WORD_BITS
        if max_cells <= 0 or h * w <= max_cells:
            return self._window_pixels(pub), turn, (1, 1)
        from gol_tpu.engine import _view_program, view_factor

        f = view_factor(h, w, max_cells)
        view = np.asarray(jax.device_get(
            _view_program("packed", 0, f, self._rule)(packed)))
        return view, turn, (f, f)

    def stats(self) -> dict:
        self._check_alive()
        with self._state_lock:
            window = origin = None
            alive = alive_turn = None
            if self._pub is not None:
                packed, ox, oy, alive_turn, alive = self._pub
                h, wp = packed.shape
                window, origin = [h, wp * WORD_BITS], [ox, oy]
            return {
                "turn": self._turn,
                "running": self._running,
                "board": [self.size, self.size],
                "window": window,
                "origin": origin,
                "alive": alive,
                "alive_turn": alive_turn,
                "packed": True,
                "sparse": True,
                "chunk": self._last_chunk,
                "turns_per_s": round(self._turns_per_s, 1),
                "chunk_overhead_us": round(self._chunk_overhead_us, 2),
                "rule": self._rule.rulestring,
                "devices": len(self._devices),
            }

    # -------------------------------------------------------- checkpointing

    def _ckpt_snapshot(self, trigger: str = "manual"):
        """Current published (window, origin, turn) as a ckpt.Snapshot
        (repr "sparse"; the writer serializes the window words plus the
        origin/size scalars into the legacy sparse npz format)."""
        from gol_tpu import ckpt as ckpt_mod

        with self._state_lock:
            pub = self._pub
        if pub is None:
            raise RuntimeError("no board loaded")
        packed, ox, oy, turn, _ = pub
        return ckpt_mod.Snapshot(
            packed, "sparse", 0, turn, (self.size, self.size),
            self._rule.rulestring, trigger=trigger,
            extra={"size": self.size, "ox": ox, "oy": oy},
            mesh={"devices": len(self._devices)})

    def checkpoint_now(self, directory: Optional[str] = None,
                       trigger: str = "manual") -> Tuple[str, int]:
        """Synchronous durable manifest checkpoint — same contract as
        `Engine.checkpoint_now` (Checkpoint wire method, SIGTERM)."""
        from gol_tpu import ckpt as ckpt_mod

        d = directory or os.environ.get(CKPT_ENV, "")
        if not d:
            raise RuntimeError(
                "checkpointing not configured: set GOL_CKPT or pass "
                "--checkpoint DIR")
        self._check_alive()
        snap = self._ckpt_snapshot(trigger)
        writer = ckpt_mod.CheckpointWriter(
            d, run_id=obs_flight.RUN_ID,
            keep_last=env_int(ckpt_mod.CKPT_KEEP_ENV,
                              ckpt_mod.CKPT_KEEP_DEFAULT),
            keep_every=env_int(ckpt_mod.CKPT_KEEP_EVERY_ENV, 0,
                               minimum=0))
        return writer.write_sync(snap), snap.turn

    def geometry(self) -> dict:
        """Placement geometry for the reshard-at-restore contract
        (ckpt/reshard.py): the torus size is fixed at construction, so
        a mismatched checkpoint is refused unless resharded (and even
        then must decode to exactly this torus)."""
        return {"kind": "sparse", "devices": len(self._devices),
                "size": int(self.size)}

    def restore_run(self, path: str, reshard: bool = False) -> int:
        """Verified manifest/legacy restore; returns the restored turn.
        `reshard=True` routes a geometry-mismatched checkpoint through
        the host-side canonical repack."""
        from gol_tpu import ckpt as ckpt_mod

        return ckpt_mod.restore_engine(self, path, reshard=reshard)

    def save_checkpoint(self, path: str) -> None:
        """Atomic .npz of (window words, origin, torus size, turn,
        rule) — the whole sparse state, 8 cells/byte."""
        with self._state_lock:
            pub = self._pub
        if pub is None:
            raise RuntimeError("no board loaded")
        packed, ox, oy, turn, _ = pub
        words = np.asarray(jax.device_get(packed))
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez_compressed(
                    f, sparse_words=words, ox=ox, oy=oy,
                    size=self.size, turn=turn,
                    rulestring=self._rule.rulestring)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load_checkpoint(self, path: str) -> int:
        self._check_alive()
        with np.load(path) as z:
            if "sparse_words" not in z.files:
                raise ValueError(f"{path}: not a sparse checkpoint")
            if str(z["rulestring"]) != self._rule.rulestring:
                raise ValueError(
                    f"checkpoint rule {z['rulestring']!r} != engine rule "
                    f"{self._rule.rulestring!r}")
            if int(z["size"]) != self.size:
                raise ValueError(
                    f"checkpoint torus {int(z['size'])} != engine torus "
                    f"{self.size}")
            words = z["sparse_words"]
            if words.dtype != np.uint32 or words.ndim != 2:
                raise ValueError(f"{path}: bad words {words.dtype} "
                                 f"{words.shape}")
            # Window-geometry invariants (ADVICE r4): SparseTorus
            # establishes window ≤ torus and a word-aligned origin, and
            # the repositioning machinery assumes both — a forged or
            # foreign checkpoint must not smuggle a violation past the
            # dtype checks.
            ox, oy = int(z["ox"]), int(z["oy"])
            if (words.shape[0] > self.size
                    or words.shape[1] * WORD_BITS > self.size):
                raise ValueError(
                    f"{path}: window {words.shape[1] * WORD_BITS}x"
                    f"{words.shape[0]} exceeds torus {self.size}")
            if ox % WORD_BITS != 0:
                raise ValueError(
                    f"{path}: window origin x={ox} is not word-aligned")
            torus = SparseTorus._from_state(
                self.size, words, ox, oy, self._rule, mesh=self._mesh)
            turn = int(z["turn"])
        with self._state_lock:
            if self._running:
                raise RuntimeError("cannot restore while running")
            self._torus = torus
            self._turn = turn
            self._publish_locked()
        return turn

    # ------------------------------------------------------------- internals

    def _publish_locked(self, alive: Optional[int] = None) -> None:
        """Refresh the coherent poll snapshot; caller holds the lock."""
        t = self._torus
        if t is None:
            self._pub = None
            return
        if alive is None:
            alive = t.alive_count()
        self._pub = (t._packed, t._ox, t._oy, self._turn, alive)

    @staticmethod
    def _window_pixels(pub) -> np.ndarray:
        if pub is None:
            raise RuntimeError("no board loaded")
        return (np.asarray(jax.device_get(unpack(pub[0])))
                * np.uint8(255))
