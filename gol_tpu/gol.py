"""Top-level entry: `run(params, events, key_presses)`.

Counterpart of reference `gol.Run` (`Local/gol/gol.go:12-40`), which wires
the distributor and io goroutines to the caller's channels and returns.
Here io is synchronous inside the distributor (the Go one-byte-per-send io
goroutine is an artifact of its channel design), so `run` starts a single
distributor thread and returns it; callers (tests, CLI) consume `events`
until the CLOSE sentinel, exactly like ranging over the Go events channel.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from gol_tpu.distributor import distributor
from gol_tpu.params import Params


def run(
    p: Params,
    events: "queue.Queue",
    key_presses: Optional["queue.Queue"] = None,
    engine=None,
    images_dir: Optional[str] = None,
    out_dir: Optional[str] = None,
    live_view: bool = False,
    rule=None,
    sparse: bool = False,
) -> threading.Thread:
    def _target() -> None:
        try:
            distributor(p, events, key_presses, engine, images_dir,
                        out_dir, live_view, rule, sparse)
        except BaseException as e:
            # Record for callers that need an exit status (the CLI):
            # a thread's own failure alone doesn't reach main()'s
            # return code. Reported through the structured logger
            # (GOL_LOG=json|text) instead of re-raising into the
            # default threading excepthook, whose raw traceback a log
            # pipeline can't parse.
            t.exception = e
            from gol_tpu.obs.log import exception as log_exception

            log_exception("distributor.failed", e)

    t = threading.Thread(
        target=_target, daemon=True, name="gol-distributor")
    t.exception = None
    t.start()
    return t
