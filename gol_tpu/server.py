"""Engine server: the broker-host process of the reference, TPU-native.

Wraps an `Engine` behind the 5-method control protocol
(`Server/gol/distributor.go:54-83` — ServerDistributor / Alivecount /
GetWorld / CFput / KillProg) on a TCP socket (default :8080, the reference
broker port, `Server:235`). Long-running: survives controller detach and
serves `GetWorld` for `CONT=yes` reattach, exactly like the Go broker
holding `world`/`turn` in globals.

Run:  python -m gol_tpu.server [--port 8080]
"""

from __future__ import annotations

import argparse
import collections
import os
import socket
import threading
import time
from typing import Optional

import numpy as np

from gol_tpu.engine import Engine, EngineBusy, EngineKilled
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import flight as obs_flight
from gol_tpu.obs.log import exception as obs_exception
from gol_tpu.obs.log import log as obs_log
from gol_tpu.obs import slo as obs_slo
from gol_tpu.obs import trace
from gol_tpu.obs.metrics import REGISTRY
from gol_tpu.params import Params
from gol_tpu.utils.envcfg import env_float, env_int
from gol_tpu import wire
from gol_tpu.wire import recv_msg, send_msg

DEFAULT_PORT = 8080  # reference broker port (`Server/gol/distributor.go:235`)

# Accept-loop hardening (VERDICT r3 weak #6): a client that connects and
# sends nothing (or trickles the request forever) must be shed, and the
# per-connection thread pool must be bounded — hostile PACING, not just
# hostile payloads. The timeout applies per socket op during request
# receipt, so a steadily-uploading legitimate client (multi-GB board)
# never trips it; only an IDLE link does. Cleared before dispatch: the
# blocking run call legitimately computes for hours between request and
# reply. The reference broker has neither guard (`Server:226-247` —
# http.Serve with default zero timeouts).
HEADER_TIMEOUT_ENV = "GOL_HDR_TIMEOUT"    # seconds; 0 disables
HEADER_TIMEOUT_DEFAULT = 30.0
MAX_CONNS_ENV = "GOL_MAX_CONNS"           # concurrent connections; 0 = off
MAX_CONNS_DEFAULT = 64

# Graceful drain (SIGTERM): stop accepting, wait up to this many seconds
# for in-flight handlers, checkpoint, then exit 0.
DRAIN_DEADLINE_ENV = "GOL_DRAIN_DEADLINE"
DRAIN_DEADLINE_DEFAULT = 5.0


class EngineServer:
    def __init__(
        self,
        port: int = DEFAULT_PORT,
        host: str = "0.0.0.0",
        engine: Optional[Engine] = None,
    ) -> None:
        self.engine = engine if engine is not None else Engine()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._shutdown = threading.Event()
        self._header_timeout = env_float(
            HEADER_TIMEOUT_ENV, HEADER_TIMEOUT_DEFAULT)
        max_conns = env_int(MAX_CONNS_ENV, MAX_CONNS_DEFAULT, minimum=0)
        self._conn_slots = (
            threading.BoundedSemaphore(max_conns) if max_conns else None)
        # Per-viewer last-served live-view frames, keyed by the client's
        # "vkey": the xrle codec deltas the next GetView reply against
        # the frame that viewer already holds. Bounded LRU — a handful
        # of concurrent viewers is the design point, and an eviction
        # only costs one full-frame resend.
        self._view_cache: dict = {}
        self._view_cache_lock = threading.Lock()
        # req_id dedupe window: the last DEDUPE_MAX mutating replies,
        # keyed by "<method>|<req_id>", so a client retry whose first
        # attempt already committed replays the recorded reply instead
        # of re-executing (idempotent retries). Raw-u8 legacy peers
        # never send req_id and keep today's at-most-once semantics.
        self._dedupe: dict = {}
        self._dedupe_lock = threading.Lock()
        self._dedupe_ctx = threading.local()
        # In-flight handler census for graceful drain.
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # Broadcast tier (hub + selectors gateway): created lazily on
        # the first Subscribe so servers that never see one spawn no
        # extra threads. (hub, gateway) once armed; guarded by _bcast
        # being written last.
        self._bcast = None
        self._bcast_lock = threading.Lock()
        # Per-thread flag a Subscribe handler sets after handing its
        # socket to the gateway: _serve_conn's finally must then NOT
        # close the fd the event loop now owns.
        self._adopted_conn = threading.local()
        # Per-thread ConnectionEncoder of the request being served:
        # _reply adds its send_msg byte counts there so the dispatch
        # tail can charge the run named in the header (PR 19).
        self._usage_ctx = threading.local()
        # Live migration forwarding map (PR 15): run_id -> the member
        # address it migrated to. A straggler whose request was relayed
        # here before the router's pin flipped gets a RETRYABLE
        # "moved:" answer instead of "unknown run". Bounded LRU.
        self._moved: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._moved_lock = threading.Lock()
        # Federation identity, stamped by main() when --federate is on:
        # the router address (PinRun destination) and the address this
        # member advertised (its member_id in the registry).
        self._fed_router = ""
        self._self_addr = ""

    VIEW_CACHE_MAX = 4
    DEDUPE_MAX = 512
    # How long a duplicate waits for the original attempt to record its
    # reply before giving up (the original may be a long Checkpoint).
    DEDUPE_WAIT_S = 60.0
    # Mirror of the client's MUTATING_METHODS: the set whose replies are
    # recorded for replay. Read-only methods are naturally idempotent.
    MUTATING_METHODS = frozenset({
        "CreateRun", "DestroyRun", "SetRule", "Checkpoint", "CFput",
        "DrainFlags", "RestoreRun", "AbortRun", "Profile", "KillProg",
        "AdoptRun", "Rescale", "ReceiveRun", "CommitRun", "PinRun",
    })

    MOVED_MAX = 256

    def note_moved(self, run_id: str, target: str) -> None:
        """Record that `run_id` now lives at member `target` so late
        requests answer "moved:" (retryable) instead of "unknown run"."""
        with self._moved_lock:
            self._moved[str(run_id)] = str(target)
            self._moved.move_to_end(str(run_id))
            while len(self._moved) > self.MOVED_MAX:
                self._moved.popitem(last=False)

    def moved_to(self, run_id: str) -> Optional[str]:
        with self._moved_lock:
            return self._moved.get(str(run_id))

    def drop_run_viewers(self, run_id: str, sentinel: str) -> None:
        """Purge every per-viewer xrle basis for a run that left this
        server and end its broadcast streams with `sentinel` — each
        subscriber reconnects and re-keys (first frame after any
        reconnect is a keyframe by protocol)."""
        self._drop_run_views(run_id)
        bc = self._bcast
        if bc is not None:
            bc[0].drop_run(run_id, sentinel)

    def serve_forever(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            # Accept timestamp: the start of the request's queue/accept
            # wait. Everything between here and dispatch start — conn-
            # slot acquisition, thread spawn/scheduling, header receipt
            # — is time the CLIENT experiences but no handler explains;
            # the SLO layer reports it as the kind="wait" split.
            t_acc = time.monotonic()
            wire.enable_nodelay(conn)
            try:
                # A peer that vanishes mid-call (power loss, NAT evict)
                # must eventually surface as a reset on long blocking
                # handlers, not hold the conn slot forever.
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            except OSError:
                pass
            if (self._conn_slots is not None
                    and not self._conn_slots.acquire(blocking=False)):
                # At the cap: refuse with a diagnosable error rather than
                # letting the accept backlog absorb the overflow silently.
                # "overloaded:" (NOT "busy:") — the client maps "busy:"
                # to EngineBusy, which a first-submission distributor
                # treats as a fatal foreign-run conflict; a transient
                # connection-limit spike must instead surface as a
                # ConnectionError and ride the reconnect/recovery path.
                try:
                    conn.settimeout(1.0)
                    send_msg(conn, {"ok": False,
                                    "error": "overloaded: connection limit"})
                except OSError:
                    pass
                finally:
                    conn.close()
                continue
            threading.Thread(
                target=self._serve_slot, args=(conn, t_acc), daemon=True
            ).start()

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._shutdown.set()
        bc = self._bcast
        if bc is not None:
            hub, gateway = bc
            try:
                hub.stop()
                gateway.stop()
            except Exception:  # noqa: BLE001 — shutdown must not raise
                pass
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------------

    def _serve_slot(self, conn: socket.socket,
                    t_acc: Optional[float] = None) -> None:
        with self._inflight_lock:
            self._inflight += 1
        try:
            self._serve_conn(conn, t_acc)
        finally:
            with self._inflight_lock:
                self._inflight -= 1
            if self._conn_slots is not None:
                self._conn_slots.release()

    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def wait_drained(self, deadline_s: float) -> int:
        """Block until every in-flight handler finished or the deadline
        passed; returns the handlers still running (0 = fully drained)."""
        t_end = time.monotonic() + max(0.0, deadline_s)
        while time.monotonic() < t_end:
            if self.inflight() == 0:
                return 0
            time.sleep(0.05)
        return self.inflight()

    def _serve_conn(self, conn: socket.socket,
                    t_acc: Optional[float] = None) -> None:
        # The fd closes on EVERY exit path (the final close below is the
        # guarantee) and transport failures are attributed by method and
        # kind — a bare `pass` would leave resets indistinguishable from
        # idle-client timeouts in a post-mortem.
        label = "unknown"
        try:
            try:
                if self._header_timeout > 0:
                    conn.settimeout(self._header_timeout)
                header, world = recv_msg(conn)
                label = obs.method_label(str(header.get("method")))
                conn.settimeout(None)  # dispatch may compute for hours
                self._dispatch(conn, header, world, t_acc)
            except wire.WireProtocolError:
                obs.RPC_ERRORS.labels(method=label, kind="protocol").inc()
            except (socket.timeout, TimeoutError):
                obs.RPC_ERRORS.labels(method=label, kind="timeout").inc()
            except (ConnectionError, OSError):
                obs.RPC_ERRORS.labels(method=label, kind="reset").inc()
            except ValueError:
                obs.RPC_ERRORS.labels(method=label, kind="protocol").inc()
            except Exception as e:
                # A handler bug must not leak the fd or die silently.
                obs_exception("server.handler_crashed", e, method=label)
        finally:
            if getattr(self._adopted_conn, "flag", False):
                # Subscribe upgrade: the gateway event loop owns this
                # fd now — closing it here would hang up the viewer the
                # handler just ACKed.
                self._adopted_conn.flag = False
            else:
                try:
                    conn.close()
                except OSError:
                    pass

    def _dispatch(
        self, conn: socket.socket, header: dict, world,
        t_acc: Optional[float] = None,
    ) -> None:
        method = header.get("method")
        # Request accounting brackets the whole dispatch, reply
        # included — for ServerDistributor the latency histogram
        # deliberately records the full blocking run (that IS the
        # request's service time on this protocol).
        label = obs.method_label(str(method))
        obs.SERVER_REQUESTS.labels(method=label).inc()
        t0 = time.monotonic()
        if t_acc is not None:
            # Queue/accept-wait split: accept() -> dispatch start. The
            # complement of the handler time below — together they tile
            # the server side of the client's observed round trip.
            obs_slo.observe_rpc("wait", label, t0 - t_acc, now=t0)
        # The handler span joins the caller's trace via the propagated
        # "tc" header (absent/garbage → a fresh root). It sits on this
        # connection thread's context stack for the whole dispatch, so
        # engine spans opened inside (chunk loop, flag service) parent
        # under it without engine.py knowing about the wire.
        with trace.span(f"serve.{label}", parent=header.get("tc")):
            try:
                self._dispatch_inner(conn, method, label, header, world)
            finally:
                t1 = time.monotonic()
                obs.SERVER_REQUEST_SECONDS.labels(method=label).observe(
                    t1 - t0)
                obs_slo.observe_rpc("handler", label, t1 - t0, now=t1)

    def _reply(self, conn: socket.socket, header: dict, world=None,
               frame=None) -> None:
        """Every reply advertises this server's wire caps, so ANY
        successful RPC (the distributor's attach ping, a flag ack)
        teaches the client which codecs the next board transfer may
        use. Old clients ignore the extra key. The advert is memoized
        in the wire layer (PR 6) — no env read or sort per reply."""
        header.setdefault("caps", wire.advertised_caps())
        # Record BEFORE the send: once the handler produced a reply the
        # operation is committed, and a retry after a lost reply must
        # replay this outcome, not re-execute it. Mutating replies are
        # header-only (no board frames), so the record is complete.
        key = getattr(self._dedupe_ctx, "key", None)
        if key is not None:
            self._dedupe_ctx.key = None
            self._record_reply(key, dict(header))
        n = send_msg(conn, header, world, frame=frame)
        enc = getattr(self._usage_ctx, "enc", None)
        if enc is not None:
            enc.bytes_out += n

    def _record_reply(self, key: str, reply: dict) -> None:
        with self._dedupe_lock:
            ent = self._dedupe.get(key)
            if ent is not None:
                ent["reply"] = reply
                ent["done"].set()

    def _dedupe_check(self, conn, method, label: str, header: dict):
        """Returns True when this request was answered from the dedupe
        window (a retry of a request already executed or executing);
        False when the caller should execute it — in which case the
        thread-local ctx key is armed for _reply to record the outcome."""
        req_id = header.get("req_id")
        if (method not in self.MUTATING_METHODS
                or not isinstance(req_id, str)
                or not 0 < len(req_id) <= 64):
            return False
        key = f"{method}|{req_id}"
        with self._dedupe_lock:
            ent = self._dedupe.get(key)
            if ent is None:
                self._dedupe[key] = {"done": threading.Event(),
                                     "reply": None}
                while len(self._dedupe) > self.DEDUPE_MAX:
                    # Window, not ledger: oldest entries age out (a
                    # retry only needs to survive the client's bounded
                    # backoff, not forever). dicts iterate in insertion
                    # order.
                    del self._dedupe[next(iter(self._dedupe))]
        if ent is None:
            self._dedupe_ctx.key = key
            return False
        # Duplicate: the first attempt owns execution; wait for its
        # recorded reply (it may still be running a long Checkpoint).
        obs.SERVER_DEDUP_HITS.labels(method=label).inc()
        ent["done"].wait(self.DEDUPE_WAIT_S)
        reply = ent["reply"]
        if reply is None:
            self._reply(conn, {
                "ok": False,
                "error": "RuntimeError: duplicate request still "
                         "executing"})
        else:
            self._reply(conn, dict(reply))
        return True

    def _board_frame(self, out, caps, eng=None):
        """Codec-frame a host pixel board under the peer's negotiated
        caps, consulting the engine for the binary-pixels contract
        (saves the probe pass; Generations engines answer False and keep
        their gray levels out of the packed codec)."""
        eng = eng if eng is not None else self.engine
        return wire.encode_board(
            out, caps, binary=getattr(eng, "binary_pixels", None))

    def _encode_view(self, header: dict, caps, out, turn: int,
                     fy: int, fx: int, eng=None):
        """Frame a GetView reply, delta-encoding (xrle) against the
        frame this viewer already holds when the negotiation, the
        engine's diffability contract, and the client's declared basis
        all line up; then remember `out` as the viewer's new basis."""
        eng = eng if eng is not None else self.engine
        vkey = self._view_cache_key(header)
        use_cache = (wire.CAP_XRLE in caps
                     and getattr(eng, "frames_diffable", False)
                     and vkey is not None)
        basis = basis_turn = None
        if use_cache:
            want = header.get("basis_turn")
            with self._view_cache_lock:
                ent = self._view_cache.get(vkey)
            if ent is not None and ent[0] == want and ent[1] == (fy, fx):
                basis_turn, _, basis = ent
        frame = wire.encode_view_frame(
            out, caps, basis=basis, basis_turn=basis_turn,
            binary=getattr(self.engine, "binary_pixels", None))
        if use_cache:
            with self._view_cache_lock:
                self._view_cache.pop(vkey, None)
                self._view_cache[vkey] = (turn, (fy, fx), out)
                while len(self._view_cache) > self.VIEW_CACHE_MAX:
                    self._view_cache.pop(next(iter(self._view_cache)))
        return frame

    def _view_cache_key(self, header: dict):
        """The per-viewer basis-cache key for a GetView request (the
        client vkey, namespaced per fleet run: the same viewer key
        watching two runs must not delta one run's frame against the
        other's), or None when the request names no usable viewer."""
        vkey = header.get("vkey")
        if not (isinstance(vkey, str) and 0 < len(vkey) <= 64):
            return None
        if header.get("run_id"):
            return f"{header['run_id']}|{vkey}"
        return vkey

    def _drop_run_views(self, run_id: str) -> None:
        """DestroyRun eviction: every `run_id|vkey` basis entry of the
        destroyed run must go, not just one key — a recycled run_id
        would otherwise delta a NEW run's first frame against the DEAD
        run's last one, and the leaked entries pin dead boards in the
        LRU until unrelated viewers push them out."""
        if not run_id:
            return
        prefix = f"{run_id}|"
        with self._view_cache_lock:
            for k in [k for k in self._view_cache if k.startswith(prefix)]:
                del self._view_cache[k]

    def _ensure_broadcast(self):
        """The lazily-armed (hub, gateway) pair. First Subscribe starts
        the publisher + event-loop threads and installs the hub's poke
        as the engine's per-chunk publish hook; servers that never see
        a Subscribe never pay for either."""
        bc = self._bcast
        if bc is not None:
            return bc
        with self._bcast_lock:
            if self._bcast is None:
                from gol_tpu.broadcast import BroadcastHub
                from gol_tpu.gateway import ViewerGateway
                hub = BroadcastHub()
                gateway = ViewerGateway()
                hub.start(sink=gateway.notify)
                gateway.start()
                try:
                    # Duck-typed: engines carry _bcast_notify = None;
                    # anything else simply never pokes and streams ride
                    # the hub's GOL_BCAST_HZ pacing tick alone.
                    self.engine._bcast_notify = hub.poke
                except Exception:  # noqa: BLE001
                    pass
                self._bcast = (hub, gateway)
        return self._bcast

    def _drop_view_basis(self, header: dict) -> None:
        """Invalidate a viewer's basis-cache entry after a reply failed
        mid-send: the viewer never received the frame we just recorded
        as its basis, so the next poll must get a fresh keyframe — and
        a truncated frame must not poison the namespace for a
        reconnecting viewer."""
        vkey = self._view_cache_key(header)
        if vkey is not None:
            with self._view_cache_lock:
                self._view_cache.pop(vkey, None)

    # Methods that act on ONE run and therefore honour a `run_id`
    # header: the engine's resolve_run maps it to a per-run surface
    # (the engine itself for the legacy run / single-run engines, a
    # fleet RunView otherwise). Engine-wide methods (KillProg,
    # AbortRun, RestoreRun, Profile, GetMetrics, ListRuns, CreateRun)
    # stay on the engine.
    RUN_SCOPED = frozenset({
        "ServerDistributor", "Ping", "Stats", "Alivecount", "GetWorld",
        "GetView", "GetWindow", "CFput", "DrainFlags", "Checkpoint",
        "Subscribe",
    })

    def _resolve_target(self, method, header: dict):
        """The engine surface a request dispatches against. Peers that
        never send run_id (every pre-fleet client) resolve to the
        engine itself — the legacy single run — on ALL engine flavours;
        unknown ids raise KeyError (mapped to an "unknown run" error)."""
        rid = header.get("run_id")
        if (method in self.RUN_SCOPED and rid not in (None, "")
                and hasattr(self.engine, "resolve_run")):
            return self.engine.resolve_run(str(rid))
        return self.engine

    def _dispatch_inner(
        self, conn: socket.socket, method, label: str, header: dict, world
    ) -> None:
        # One encoder per connection (the protocol is one request per
        # connection): negotiation + advert resolve here, once, via the
        # wire-layer memos — every frame built below reuses `enc.caps`
        # without re-reading the environment or the peer header.
        enc = wire.ConnectionEncoder(header)
        caps = enc.caps
        self._usage_ctx.enc = enc
        if self._dedupe_check(conn, method, label, header):
            return
        try:
            eng = self._resolve_target(method, header)
            if method == "ServerDistributor":
                p = Params(**header["params"])
                out, turn = eng.server_distributor(
                    p,
                    world,
                    tuple(header.get("sub_workers", ())),
                    start_turn=int(header.get("start_turn", 0)),
                    token=header.get("token"),
                )
                self._reply(conn, {"ok": True, "turn": turn},
                            frame=self._board_frame(out, caps, eng))
            elif method == "AbortRun":
                aborted = self.engine.abort_run(header.get("token"))
                self._reply(conn, {"ok": True, "aborted": aborted})
            elif method == "Ping":
                self._reply(conn, {"ok": True, "turn": eng.ping()})
            elif method == "Stats":
                self._reply(conn,
                            {"ok": True, "stats": eng.stats()})
            elif method == "GetMetrics":
                # Full registry snapshot (engine, wire, server families)
                # — the wire-native face of the /metrics endpoint.
                self._reply(conn,
                            {"ok": True, "metrics": REGISTRY.snapshot()})
            elif method == "GetTelemetry":
                # A member answers with its OWN family values (the
                # router answers the same method with fleet rollups).
                from gol_tpu.obs import export as obs_export
                self._reply(conn, {"ok": True,
                                   "telemetry": obs_export.local_doc()})
            elif method == "GetAudit":
                # Member-local event ring; the durable gol-fleet-audit/1
                # log lives on the registry tier.
                from gol_tpu.obs import audit as obs_audit
                self._reply(conn, {
                    "ok": True,
                    "records": obs_audit.recent(
                        int(header.get("since_seq", 0) or 0),
                        int(header.get("limit", 100) or 100))})
            elif method == "GetJournal":
                # Hash-chained run journal tail (PR 17). Resolved by
                # run_id directly (not RUN_SCOPED — the journal registry
                # is process-global, keyed by run id, and outlives the
                # run handle so a retired run's black box stays
                # readable until process exit).
                from gol_tpu import journal as journal_mod
                rid = str(header.get("run_id") or "")
                jw = journal_mod.get(rid) if rid else None
                if jw is None:
                    raise KeyError(f"no journal for run {rid!r}")
                self._reply(conn, {
                    "ok": True,
                    "head": jw.head, "seq": jw.last_seq,
                    "path": journal_mod.journal_path(rid),
                    "records": jw.tail(
                        int(header.get("since_seq", -1)
                            if header.get("since_seq") is not None
                            else -1),
                        int(header.get("limit", 100) or 100))})
            elif method == "GetUsage":
                # Per-run usage meter + capacity headroom (PR 19).
                # Like GetJournal, not RUN_SCOPED: without a run_id
                # the member's whole usage doc (top-K talkers +
                # capacity rows) answers; with one, the named run's
                # live record rides along — and an unknown id takes
                # the standard moved-redirect path below.
                from gol_tpu.obs import usage as obs_usage
                rid = str(header.get("run_id") or "")
                resp = {"ok": True, "usage": obs_usage.usage_doc()}
                if rid:
                    resp["run"] = obs_usage.METER.run_doc(rid)
                self._reply(conn, resp)
            elif method == "Alivecount":
                alive, turn = eng.alive_count()
                self._reply(conn,
                            {"ok": True, "alive": alive, "turn": turn})
            elif method == "GetWorld":
                if hasattr(eng, "get_world_frame"):
                    # The engines' frame path: packed device words go
                    # straight to the socket, banded, with no device-
                    # side unpack — the PR-5 snapshot data plane.
                    frame, turn = eng.get_world_frame(caps)
                else:
                    out, turn = eng.get_world()
                    frame = self._board_frame(out, caps, eng)
                self._reply(conn, {"ok": True, "turn": turn}, frame=frame)
            elif method == "GetView":
                # O(max_cells) downsampled live-view frame of the board
                # (dense) or live window (sparse) — the remote analog
                # of the engines' get_view.
                vkey = header.get("vkey")
                if (hasattr(eng, "subscribe_view")
                        and isinstance(vkey, str) and 0 < len(vkey) <= 64):
                    eng.subscribe_view(vkey)
                if (header.get("basis_turn") is not None
                        and not getattr(eng, "frames_diffable", True)):
                    # Float (Lenia) boards quantize per frame, so an
                    # XOR delta against the peer's basis would decode
                    # to garbage. Refuse with a TAGGED error instead —
                    # the client drops its basis and re-polls for full
                    # frames. (_encode_view never delta-encodes these;
                    # this guards the peer's explicit delta request.)
                    err = RuntimeError(
                        "this board's frames are not delta-codable "
                        "(float state); re-poll without basis_turn for "
                        "full frames")
                    err.rpc_error_kind = "nodiff"
                    raise err
                out, turn, (fy, fx) = eng.get_view(
                    int(header.get("max_cells", 0)))
                try:
                    self._reply(conn, {"ok": True, "turn": turn,
                                       "fy": fy, "fx": fx},
                                frame=self._encode_view(header, caps, out,
                                                        turn, fy, fx, eng))
                except (ConnectionError, OSError):
                    self._drop_view_basis(header)
                    raise
            elif method == "Subscribe":
                # Broadcast upgrade: ACK on the threaded path, then
                # hand the socket to the selectors gateway — this
                # handler (and its conn slot) returns immediately while
                # the gateway pushes encode-once epoch-stream frames
                # until the peer hangs up. Requires the peer to have
                # negotiated every stream codec: all subscribers share
                # the same frozen bytes, so a partial-caps peer must
                # poll per-viewer GetView instead.
                if not hasattr(eng, "get_view"):
                    raise RuntimeError("engine has no live view surface")
                vkey = header.get("vkey")
                if (hasattr(eng, "subscribe_view")
                        and isinstance(vkey, str) and 0 < len(vkey) <= 64):
                    eng.subscribe_view(vkey)
                hub, gateway = self._ensure_broadcast()
                stream = hub.stream_for(
                    str(header.get("run_id") or ""), eng,
                    int(header.get("max_cells", 0)))
                if not stream.caps <= caps:
                    raise RuntimeError(
                        "subscribe requires caps "
                        f"{sorted(stream.caps)}; poll GetView instead")
                if not gateway.try_reserve():
                    raise RuntimeError(
                        "overloaded: gateway connection limit")
                try:
                    self._reply(conn, {
                        "ok": True, "run_id": stream.run_id or None,
                        "epoch": stream.epoch,
                        "keyframe_every": stream.keyframe_every,
                        "max_cells": stream.max_cells})
                except BaseException:
                    gateway.release_reservation()
                    raise
                gateway.adopt(conn, stream)
                self._adopted_conn.flag = True
            elif method == "GetWindow":
                # Sparse engines only: live-window pixels + torus origin.
                out, (ox, oy), turn = eng.get_window()
                self._reply(conn, {"ok": True, "turn": turn,
                                   "ox": ox, "oy": oy},
                            frame=self._board_frame(out, caps, eng))
            elif method == "CFput":
                eng.cf_put(int(header["flag"]))
                self._reply(conn, {"ok": True})
            elif method == "DrainFlags":
                eng.drain_flags(
                    pause_only=bool(header.get("pause_only", False)))
                self._reply(conn, {"ok": True})
            elif method == "Checkpoint":
                # Controller-triggered durable snapshot into the
                # server's CONFIGURED directory (GOL_CKPT) — the client
                # never chooses write paths on this host (fleet runs
                # land in contained per-run subdirectories).
                path, turn = eng.checkpoint_now(trigger="remote")
                self._reply(conn, {"ok": True, "turn": turn,
                                   "manifest": os.path.basename(path)})
            elif method == "CreateRun":
                # Fleet admission: single-run engines answer with a
                # FleetUnsupported error pointing at --fleet. The seed
                # board (optional) rides the request payload exactly
                # like a ServerDistributor world upload.
                tt = header.get("target_turn")
                rec = self.engine.create_run(
                    int(header["h"]), int(header["w"]),
                    board=world,
                    run_id=header.get("run_id"),
                    rule=header.get("rule"),
                    ckpt_every=int(header.get("ckpt_every", 0)),
                    target_turn=int(tt) if tt is not None else None,
                    queue=bool(header.get("queue", False)))
                self._reply(conn, {"ok": True, "run": rec})
            elif method == "ListRuns":
                self._reply(conn, {
                    "ok": True,
                    "runs": self.engine.list_runs(),
                    "summary": self.engine.runs_summary()})
            elif method == "AttachRun":
                surf = self.engine.resolve_run(
                    str(header.get("run_id") or ""))
                self._reply(conn, {"ok": True,
                                   "run": surf.describe_run()})
            elif method == "DestroyRun":
                # Explicit slot release (vs QUIT/KILL flags): removes
                # the run wherever it sits (resident, queued, parked),
                # frees its admission budget, and wakes the loop so a
                # queued run promotes immediately. Single-run engines
                # answer FleetUnsupported, same as CreateRun.
                rid = str(header.get("run_id") or "")
                rec = self.engine.destroy_run(rid)
                # The run is gone: purge every per-viewer xrle basis in
                # its namespace and end its broadcast streams (each
                # subscriber gets the end sentinel, then a hangup).
                self._drop_run_views(rid)
                bc = self._bcast
                if bc is not None:
                    bc[0].drop_run(rid, f"killed: run {rid} destroyed")
                self._reply(conn, {"ok": True, "run": rec})
            elif method == "SetRule":
                # Rule migration: evict -> re-home under the new rule's
                # bucket -> readmit through the placement queue, board
                # intact. The legacy run0 is refused (PermissionError,
                # like DestroyRun) — its rule is fixed at construction.
                rec = self.engine.set_rule(
                    str(header.get("run_id") or ""),
                    str(header.get("rule") or ""))
                self._reply(conn, {"ok": True, "run": rec})
            elif method == "AdoptRun":
                # Federation failover (PR 12): adopt a dead member's
                # run from its per-run manifests under the shared
                # GOL_CKPT root. Fleet engines register the run in
                # quarantine so the capped-backoff verified restore
                # machinery re-homes it; others answer FleetUnsupported.
                from gol_tpu.fleet.handles import FleetUnsupported

                adopt = getattr(self.engine, "adopt_run", None)
                if adopt is None:
                    raise FleetUnsupported(
                        f"{type(self.engine).__name__} serves a single "
                        "run; start the server with --fleet for "
                        "AdoptRun")
                tt = header.get("target_turn")
                rec = adopt(
                    str(header.get("run_id") or ""),
                    ckpt_every=int(header.get("ckpt_every", 0) or 0),
                    target_turn=int(tt) if tt is not None else None)
                self._reply(conn, {"ok": True, "run": rec})
            elif method == "RestoreRun":
                turn = self._restore_run(
                    str(header.get("path", "")),
                    reshard=bool(header.get("reshard", False)))
                self._reply(conn, {"ok": True, "turn": turn})
            elif method == "Rescale":
                # Live migration (PR 15): THIS member is the source;
                # gol_tpu/migrate.py coordinates the failure-atomic
                # quiesce -> checkpoint -> transfer -> resume ->
                # redirect cutover (rollback to here on any failure).
                from gol_tpu import migrate as migrate_mod

                rec = migrate_mod.rescale(
                    self, str(header.get("run_id") or ""),
                    str(header.get("target") or ""))
                self._reply(conn, {"ok": True, **rec})
            elif method == "ReceiveRun":
                # Target half of a migration transfer: stage the
                # incoming board hidden ("staged") until CommitRun.
                from gol_tpu.fleet.handles import FleetUnsupported

                imp = getattr(self.engine, "import_run", None)
                if imp is None:
                    raise FleetUnsupported(
                        f"{type(self.engine).__name__} serves a single "
                        "run; start the server with --fleet for "
                        "ReceiveRun")
                if world is None:
                    raise RuntimeError(
                        "ReceiveRun carries the board as its payload")
                tt = header.get("target_turn")
                rec = imp(
                    str(header.get("run_id") or ""), world,
                    int(header.get("turn", 0)),
                    rule=header.get("rule"),
                    ckpt_every=int(header.get("ckpt_every", 0) or 0),
                    target_turn=int(tt) if tt is not None else None,
                    activate=str(header.get("state", "resident"))
                    in ("resident", "queued"),
                    journal_head=header.get("journal_head"))
                self._reply(conn, {"ok": True, "run": rec})
            elif method == "CommitRun":
                act = getattr(self.engine, "activate_imported", None)
                if act is None:
                    raise RuntimeError(
                        f"{type(self.engine).__name__} cannot commit "
                        "migrated runs")
                rec = act(str(header.get("run_id") or ""))
                self._reply(conn, {"ok": True, "run": rec})
            elif method == "Profile":
                # Arm an on-demand jax.profiler capture of the next N
                # engine turns, into the server's CONFIGURED directory
                # (--profile-dir) — same posture as Checkpoint: remote
                # peers never choose filesystem paths on this host.
                # turns=0 requests status only.
                from gol_tpu.obs.prof import PROFILER, ProfileUnavailable

                turns = int(header.get("turns", 0))
                if turns > 0:
                    try:
                        armed = PROFILER.request(turns=turns,
                                                 source="wire")
                    except ProfileUnavailable as e:
                        raise RuntimeError(str(e)) from e
                    self._reply(conn, {"ok": True, **armed})
                else:
                    self._reply(conn, {"ok": True,
                                       "status": PROFILER.status()})
            elif method == "KillProg":
                self.engine.kill_prog()
                self._reply(conn, {"ok": True})
                # Reference broker/worker die on KillProg (os.Exit(0),
                # `SubServer/distributor.go:42-45`): bring the server down.
                self.shutdown()
                if os.environ.get("GOL_SERVER_EXIT_ON_KILL", "1") == "1":
                    threading.Timer(0.2, _exit_after_flush).start()
            else:
                self._reply(conn, {"ok": False,
                                   "error": f"unknown method {method!r}"})
        except KeyError as e:
            # resolve_run contract: unknown run ids raise KeyError with
            # a presentable message ("unknown run 'x'"). Any other
            # KeyError (a malformed request header) keeps the generic
            # "ExcName: detail" shape clients already parse.
            obs.SERVER_ERRORS.labels(method=label).inc()
            msg = e.args[0] if e.args else ""
            if isinstance(msg, str) and msg.startswith("unknown run"):
                # Unknown because it MIGRATED away? Answer with the
                # retryable redirect — by the time the client retries,
                # the router pin points at the new owner. Downtime is
                # latency, never a caller-visible error.
                moved = self.moved_to(str(header.get("run_id") or ""))
                if moved is not None:
                    self._reply(conn, {
                        "ok": False,
                        "error": f"moved: run "
                                 f"{header.get('run_id')} migrated "
                                 f"to {moved}"})
                else:
                    self._reply(conn, {"ok": False, "error": msg})
            else:
                self._reply(conn, {"ok": False,
                                   "error": f"KeyError: {e}"})
        except EngineKilled as e:
            obs.SERVER_ERRORS.labels(method=label).inc()
            self._reply(conn, {"ok": False, "error": f"killed: {e}"})
        except PermissionError as e:
            obs.SERVER_ERRORS.labels(method=label).inc()
            self._reply(conn, {"ok": False, "error": f"denied: {e}"})
        except EngineBusy as e:
            obs.SERVER_ERRORS.labels(method=label).inc()
            self._reply(conn, {"ok": False, "error": f"busy: {e}"})
        except Exception as e:  # surface engine errors to the client
            obs.SERVER_ERRORS.labels(method=label).inc()
            if getattr(e, "rpc_error_kind", None) == "geometry":
                # Mismatched-geometry restore refusal (ckpt/reshard.py):
                # a tagged, never-retried error the client surfaces as
                # GeometryRefused — resend with reshard=True to repack.
                self._reply(conn, {"ok": False,
                                   "error": f"geometry: {e}"})
            elif getattr(e, "rpc_error_kind", None) == "nodiff":
                # Delta-view request against a non-diffable (float)
                # board: tagged so the client clears its stale basis
                # and re-polls full frames instead of surfacing an
                # error to the viewer.
                self._reply(conn, {"ok": False,
                                   "error": f"nodiff: {e}"})
            else:
                self._reply(conn, {"ok": False,
                                   "error": f"{type(e).__name__}: {e}"})
        finally:
            self._usage_ctx.enc = None
            rid = header.get("run_id")
            if rid:
                # Only run-scoped traffic is tenant cost; unscoped
                # RPCs (Stats, GetMetrics, ...) are fleet overhead.
                try:
                    from gol_tpu.obs import usage as obs_usage
                    obs_usage.METER.charge_wire(
                        str(rid), enc.bytes_in, enc.bytes_out)
                except Exception:
                    pass

    def _restore_run(self, req: str, reshard: bool = False) -> int:
        """RestoreRun target resolution: the request names a checkpoint
        WITHIN the server's configured directory (relative name, or an
        absolute path that realpath-resolves inside it) — or nothing,
        meaning the newest durable checkpoint there. A remote peer must
        not be able to point the engine at arbitrary host files."""
        from gol_tpu.engine import CKPT_ENV

        base = os.environ.get(CKPT_ENV, "")
        if not base:
            raise RuntimeError(
                "checkpointing not configured: set GOL_CKPT or pass "
                "--checkpoint DIR")
        target = os.path.join(base, req) if req else base
        real_base = os.path.realpath(base)
        real_target = os.path.realpath(target)
        if (real_target != real_base
                and not real_target.startswith(real_base + os.sep)):
            raise PermissionError(
                f"restore path {req!r} escapes the checkpoint directory")
        return self.engine.restore_run(target, reshard=reshard)


def _final_flush(reason: str) -> None:
    """Last writes on paths that end in os._exit (which skips atexit):
    the flight-recorder dump and the span export. Both are no-ops unless
    their env vars are set, and neither can raise."""
    obs_flight.FLIGHT.dump(reason)
    trace.export_from_env()


def _exit_after_flush() -> None:
    _final_flush("manual")
    os._exit(0)


def main() -> None:
    ap = argparse.ArgumentParser(description="gol_tpu engine server")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("GOL_PORT", DEFAULT_PORT)))
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve Prometheus text on "
                         "http://127.0.0.1:PORT/metrics (0 = ephemeral "
                         "port; unset = no endpoint)")
    ap.add_argument("--trace-spans", metavar="PATH", default="",
                    help="export handler/engine spans as Chrome "
                         "trace-event JSON (Perfetto-loadable) to PATH "
                         "on shutdown (sets GOL_TRACE_SPANS; a "
                         "directory gets one file per pid)")
    ap.add_argument("--resume", metavar="DIR|MANIFEST|NPZ", default="",
                    help="restore (world, turn) before serving: a "
                         "checkpoint directory (newest durable manifest "
                         "wins), a ckpt-*.json manifest (payload "
                         "SHA-256 verified), or a legacy .npz autosave")
    ap.add_argument("--reshard", action="store_true",
                    help="allow --resume to adopt a checkpoint whose "
                         "recorded geometry (mesh device count, "
                         "representation family, sparse torus size) "
                         "differs from this engine: the payload is "
                         "repacked host-side, bit-identically; without "
                         "this flag a mismatched resume is refused")
    ap.add_argument("--checkpoint", metavar="DIR", default="",
                    help="checkpoint directory (sets GOL_CKPT): runs "
                         "write gol-ckpt/1 manifest checkpoints here "
                         "when --ckpt-every is set, plus the legacy "
                         "time-based autosave")
    ap.add_argument("--ckpt-every", metavar="TURNS", type=int, default=0,
                    help="manifest checkpoint cadence in TURNS (sets "
                         "GOL_CKPT_EVERY_TURNS; 0 = off; requires "
                         "--checkpoint)")
    ap.add_argument("--ckpt-keep", metavar="N", type=int, default=0,
                    help="retention: keep the newest N checkpoints "
                         "(sets GOL_CKPT_KEEP; default 3; "
                         "GOL_CKPT_KEEP_EVERY additionally pins every "
                         "K-th turn)")
    ap.add_argument("--journal", metavar="DIR", default="",
                    help="event-sourced run journal root (sets "
                         "GOL_JOURNAL): every state-mutating input per "
                         "run appends to a hash-chained gol-journal/1 "
                         "JSONL log replayable by tools/replay_audit.py")
    ap.add_argument("--journal-digest-every", metavar="TURNS", type=int,
                    default=0,
                    help="board-digest journal events every TURNS on "
                         "the single-run engine (sets "
                         "GOL_JOURNAL_DIGEST_EVERY; default 512; fleet "
                         "runs digest at their checkpoint cadence "
                         "instead, via the shared writer pool)")
    ap.add_argument("--profile-dir", metavar="DIR", default="",
                    help="directory for on-demand jax.profiler captures "
                         "(Profile wire method / POST /profile arm one; "
                         "the peer only picks the turn count — this "
                         "flag fixes where artifacts land)")
    ap.add_argument("--coordinator", metavar="HOST:PORT", default="",
                    help="multi-host engine: jax.distributed coordinator "
                         "address (falls back to GOL_COORDINATOR; unset = "
                         "single-host)")
    ap.add_argument("--rule", metavar="RULE",
                    default=os.environ.get("GOL_RULE") or "B3/S23",
                    help="rulestring this engine evolves: life-like "
                         "'B3/S23' or Generations 'survival/birth/states'"
                         " (e.g. '/2/3' = Brian's Brain; default Conway; "
                         "falls back to GOL_RULE)")
    ap.add_argument("--sparse", metavar="SIZE", type=int, default=0,
                    help="serve a sparse-torus engine of SIZE x SIZE "
                         "cells (SIZE %% 32 == 0, e.g. 1048576 = 2^20) "
                         "instead of the dense engine: runs are seeded "
                         "by a small pattern board, snapshots are the "
                         "live window (life-like rules only; "
                         "GOL_SPARSE_SHARDS row-shards the window over "
                         "that many devices)")
    ap.add_argument("--fleet", action="store_true",
                    help="serve the batched multi-run fleet engine: "
                         "thousands of resident runs stepped in shared "
                         "size-bucket dispatches, admitted against a "
                         "device-memory budget (CreateRun/ListRuns/"
                         "AttachRun + run_id routing on the existing "
                         "methods; peers that never send run_id get the "
                         "legacy single run, bit-identically; life-like "
                         "rules only; GOL_FLEET_BUCKETS/GOL_FLEET_CHUNK/"
                         "GOL_FLEET_MEM_BUDGET tune it)")
    ap.add_argument("--federate", metavar="ROUTER_ADDR", default="",
                    help="join a federation: register with the router "
                         "at host:port and heartbeat every "
                         "GOL_FED_HEARTBEAT seconds (the router "
                         "declares this member dead after "
                         "GOL_FED_DEAD_AFTER of silence and re-homes "
                         "its runs from the shared GOL_CKPT root)")
    ap.add_argument("--advertise", metavar="HOST", default="127.0.0.1",
                    help="hostname the router should dial this member "
                         "back on (default 127.0.0.1; the port is the "
                         "bound serving port)")
    args = ap.parse_args()
    if args.fleet and args.sparse:
        ap.error("--fleet and --sparse are mutually exclusive")
    if args.trace_spans:
        os.environ[trace.TRACE_SPANS_ENV] = args.trace_spans
    # Checkpoint knobs travel as env (the engine reads them at run
    # start, same pattern as every GOL_* knob).
    if args.checkpoint:
        os.environ["GOL_CKPT"] = args.checkpoint
    if args.ckpt_every:
        os.environ["GOL_CKPT_EVERY_TURNS"] = str(args.ckpt_every)
    if args.ckpt_keep:
        os.environ["GOL_CKPT_KEEP"] = str(args.ckpt_keep)
    if args.journal:
        os.environ["GOL_JOURNAL"] = args.journal
    if args.journal_digest_every:
        os.environ["GOL_JOURNAL_DIGEST_EVERY"] = str(
            args.journal_digest_every)
    if args.profile_dir:
        # configure() only: the server arms nothing at startup — a
        # Profile RPC or POST /profile picks the moment and turn count.
        from gol_tpu.obs.prof import PROFILER

        PROFILER.configure(args.profile_dir)
    trace.set_process_name("gol-server")
    # Join the multi-host engine cluster FIRST: jax.distributed must
    # initialize before ANYTHING touches the XLA backend (including the
    # compile-cache block below, whose jax.default_backend() call would
    # otherwise poison it). After this, meshes span the pod (SURVEY §2d).
    from gol_tpu.parallel import multihost

    if multihost.initialize(args.coordinator or None):
        import jax

        print(f"multi-host engine: process {jax.process_index()}/"
              f"{jax.process_count()}, {len(jax.devices())} device(s)")
    import gol_tpu

    gol_tpu.maybe_enable_default_compile_cache()
    from gol_tpu.models import parse_rule

    rule = parse_rule(args.rule)
    if args.sparse:
        from gol_tpu.sparse_engine import SparseEngine

        eng = SparseEngine(args.sparse, rule=rule)
    elif args.fleet:
        from gol_tpu.fleet import FleetEngine

        eng = FleetEngine(rule=rule)
    else:
        eng = Engine(rule=rule)
    srv = EngineServer(port=args.port, host=args.host, engine=eng)
    if args.resume:
        turn = srv.engine.restore_run(args.resume,
                                      reshard=args.reshard)
        print(f"restored checkpoint {args.resume} at turn {turn}"
              + (" (resharded)" if args.reshard else ""))

    # Graceful shutdown: with checkpointing configured (GOL_CKPT), a
    # SIGTERM writes one final checkpoint before exiting, so an orderly
    # stop (systemd, k8s preStop, operator) loses zero turns — a
    # replacement server --resume picks up exactly where this one ended.
    import signal
    from gol_tpu.engine import CKPT_ENV

    def _on_term(signo, frame):
        # Graceful drain: stop accepting first, give in-flight handlers
        # a bounded window to finish (their replies are the whole point
        # of draining), THEN checkpoint and exit 0 — so an orderly stop
        # (systemd, k8s preStop, operator) loses zero turns AND zero
        # in-flight replies that could have completed.
        t_drain = time.monotonic()
        n0 = srv.inflight()
        deadline = env_float(DRAIN_DEADLINE_ENV, DRAIN_DEADLINE_DEFAULT)
        obs.SERVER_DRAIN_INFLIGHT.set(n0)
        obs_log("server.drain_begin", level="warning", inflight=n0,
                deadline_s=deadline)
        srv.shutdown()
        left = srv.wait_drained(deadline)
        ckpt_dir = os.environ.get(CKPT_ENV, "")
        if ckpt_dir:
            # Durable manifest checkpoint first (verified, retained,
            # resumable by --resume DIR); the legacy single-file
            # autosave rides along for pre-manifest tooling.
            try:
                path, turn = srv.engine.checkpoint_now(trigger="sigterm")
                obs_log("server.sigterm_checkpoint", turn=turn, path=path)
            except Exception as e:
                obs_exception("server.sigterm_checkpoint_failed", e)
            # Fleet residents: every non-legacy run gets its own per-run
            # manifest so a replacement server can restore the whole
            # fleet, not just the legacy run.
            ckpt_fleet = getattr(srv.engine, "checkpoint_fleet", None)
            if ckpt_fleet is not None:
                try:
                    n = ckpt_fleet(trigger="sigterm")
                    obs_log("server.sigterm_fleet_checkpoint", runs=n)
                except Exception as e:
                    obs_exception("server.sigterm_checkpoint_failed", e)
            try:
                # stats() gives (board geometry, turn) without the full
                # board transfer get_world() would cost.
                s = srv.engine.stats()
                if s["board"] is not None:
                    h, w = s["board"]
                    os.makedirs(ckpt_dir, exist_ok=True)
                    path = os.path.join(ckpt_dir, f"{w}x{h}.npz")
                    srv.engine.save_checkpoint(path)
            except Exception as e:
                obs_exception("server.sigterm_checkpoint_failed", e)
        dur = time.monotonic() - t_drain
        obs.SERVER_DRAIN_SECONDS.set(dur)
        # The drain flight event lands BEFORE the dump below records it.
        obs_log("server.drain", level="warning", inflight_start=n0,
                inflight_left=left, duration_s=round(dur, 3))
        # After the checkpoint (the dump should record its log event,
        # and a slow checkpoint must not delay the black box by dying
        # first — dump is sub-ms either way).
        _final_flush("sigterm")
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)
    if args.metrics_port is not None:
        from gol_tpu.obs.http import start_metrics_server

        msrv = start_metrics_server(args.metrics_port)
        print(f"metrics on {msrv.url}", flush=True)
    agent = None
    if args.federate:
        from gol_tpu.federation.agent import FederationAgent

        devices = len(np.atleast_1d(srv.engine._devices))
        # Stamp the federation identity on the server: the Rescale
        # coordinator pins redirects at this router, as this member.
        srv._fed_router = args.federate
        srv._self_addr = f"{args.advertise}:{srv.port}"
        agent = FederationAgent(
            args.federate, srv._self_addr,
            capacity=devices, mesh={"devices": devices}).start()
    # This exact banner is the readiness contract: harnesses parse
    # "serving on :<port>" from stdout to learn the bound port.
    print(f"gol_tpu engine serving on :{srv.port} "
          f"({len(np.atleast_1d(srv.engine._devices))} device(s), "
          f"rule {srv.engine._rule.rulestring})")
    srv.serve_forever()
    if agent is not None:
        agent.stop()
    # Orderly stop (accept loop closed, e.g. KillProg without the exit
    # timer): still export whatever spans were recorded.
    trace.export_from_env()


if __name__ == "__main__":
    main()
