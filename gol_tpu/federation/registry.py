"""Federation member registry: who is alive, and since when.

Each fleet server process registers with the router over the existing
wire protocol (`RegisterMember`) carrying its advertised address,
capacity, mesh geometry, and a MONOTONICALLY increasing heartbeat
sequence number. The registry stamps every accepted beat with its own
monotonic clock — member clocks are never compared — and a sequence
that does not advance is ignored, so a delayed/reordered duplicate
can't resurrect a quieter member ("monotonically-stamped heartbeat").

Death is declared by the router's sweep: a live member whose last
stamp is older than `GOL_FED_DEAD_AFTER` seconds moves to state
"dead", increments `gol_fed_failovers_total`, and is returned to the
caller so the router can adopt its runs. A dead member that registers
again (process restarted) moves back to live with a fresh sequence
epoch.

Gauges: `gol_fed_members{state}` tracks the census on every change;
`gol_fed_heartbeat_age_ms{q}` publishes heartbeat-age quantiles across
live members at each sweep. `/healthz` serves `members_doc()` through
the module-level active-registry pointer (reference-swapped, so the
HTTP thread never takes the registry lock).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from gol_tpu.obs import catalog as obs
from gol_tpu.obs import slo as obs_slo
from gol_tpu.utils.envcfg import env_float

HEARTBEAT_ENV = "GOL_FED_HEARTBEAT"
HEARTBEAT_DEFAULT_S = 0.5
DEAD_AFTER_ENV = "GOL_FED_DEAD_AFTER"
DEAD_AFTER_DEFAULT_S = 2.0


def heartbeat_interval_s() -> float:
    return max(0.05, env_float(HEARTBEAT_ENV, HEARTBEAT_DEFAULT_S))


def dead_after_s() -> float:
    """Heartbeat-lapse death threshold; never below one heartbeat."""
    return max(heartbeat_interval_s(),
               env_float(DEAD_AFTER_ENV, DEAD_AFTER_DEFAULT_S))


class Member:
    """One registered fleet server, as the router sees it."""

    __slots__ = ("member_id", "address", "capacity", "mesh", "state",
                 "hb_seq", "hb_stamp_s", "registered_s", "died_s")

    def __init__(self, member_id: str, address: str,
                 capacity: int = 0, mesh: Optional[dict] = None) -> None:
        self.member_id = member_id
        self.address = address
        self.capacity = int(capacity)
        self.mesh = mesh
        self.state = "live"
        self.hb_seq = -1
        self.hb_stamp_s = time.monotonic()
        self.registered_s = time.time()
        self.died_s: Optional[float] = None

    def doc(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        return {
            "member_id": self.member_id,
            "address": self.address,
            "capacity": self.capacity,
            "mesh": self.mesh,
            "state": self.state,
            "hb_seq": self.hb_seq,
            "hb_age_ms": round((now - self.hb_stamp_s) * 1e3, 1),
        }


class MemberRegistry:
    """Thread-safe membership view + heartbeat sweep."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._members: Dict[str, Member] = {}
        # Reference-swapped /healthz document (readers never lock).
        self._doc: dict = {"members": [], "live": 0, "dead": 0}

    # -- registration / heartbeat (wire-facing) -----------------------

    def register(self, member_id: str, address: str, seq: int,
                 capacity: int = 0,
                 mesh: Optional[dict] = None) -> dict:
        """Register-or-heartbeat: the first beat creates the member,
        later ones advance its stamp iff `seq` advanced. Returns a
        small ack dict ({"live": N} rides the reply so members can log
        the census they joined)."""
        now = time.monotonic()
        with self._lock:
            m = self._members.get(member_id)
            joined = m is None
            if joined:
                m = Member(member_id, address, capacity=capacity,
                           mesh=mesh)
                self._members[member_id] = m
            was_dead = m.state == "dead"
            if was_dead:
                # Restarted process: fresh sequence epoch, back to live.
                m.state = "live"
                m.died_s = None
                m.hb_seq = -1
            m.address = address
            if capacity:
                m.capacity = int(capacity)
            if mesh is not None:
                m.mesh = mesh
            accepted = int(seq) > m.hb_seq
            if accepted:
                m.hb_seq = int(seq)
                m.hb_stamp_s = now
            live = sum(1 for x in self._members.values()
                       if x.state == "live")
        self._publish()
        return {"registered": True, "accepted": accepted,
                "joined": joined, "rejoined": was_dead, "live": live}

    # -- queries ------------------------------------------------------

    def live_members(self) -> List[Member]:
        with self._lock:
            return [m for m in self._members.values()
                    if m.state == "live"]

    def live_ids(self) -> List[str]:
        return [m.member_id for m in self.live_members()]

    def get(self, member_id: str) -> Optional[Member]:
        with self._lock:
            return self._members.get(member_id)

    def members_doc(self) -> dict:
        """The cached /healthz member table (lock-free read)."""
        return self._doc

    # -- sweep --------------------------------------------------------

    def sweep(self, now: Optional[float] = None) -> List[Member]:
        """Declare death for live members whose heartbeat lapsed;
        returns the NEWLY dead members (the router adopts their runs).
        Also publishes the census gauges and heartbeat-age quantiles."""
        now = time.monotonic() if now is None else now
        cutoff = dead_after_s()
        newly_dead: List[Member] = []
        ages: List[float] = []
        with self._lock:
            for m in self._members.values():
                if m.state != "live":
                    continue
                age = now - m.hb_stamp_s
                if age > cutoff:
                    m.state = "dead"
                    m.died_s = time.time()
                    newly_dead.append(m)
                else:
                    ages.append(age)
        for m in newly_dead:
            obs.FED_FAILOVERS.inc()
        if ages:
            for q, v in zip(obs.SLO_QUANTILES,
                            obs_slo.exact_percentiles(
                                ages, (0.50, 0.95, 0.99))):
                obs.FED_HEARTBEAT_AGE_MS.labels(q=q).set(
                    round(v * 1e3, 3))
        self._publish()
        return newly_dead

    def _publish(self) -> None:
        now = time.monotonic()
        with self._lock:
            docs = [m.doc(now) for m in self._members.values()]
        live = sum(1 for d in docs if d["state"] == "live")
        dead = len(docs) - live
        obs.FED_MEMBERS.labels(state="live").set(live)
        obs.FED_MEMBERS.labels(state="dead").set(dead)
        self._doc = {"members": sorted(docs,
                                       key=lambda d: d["member_id"]),
                     "live": live, "dead": dead}


# -- /healthz hook: the process's active registry ---------------------

_active: Optional[MemberRegistry] = None


def set_active(reg: Optional[MemberRegistry]) -> None:
    global _active
    _active = reg


def active_doc() -> Optional[dict]:
    """The active registry's member table, or None when this process
    runs no router (so /healthz adds no federation key)."""
    reg = _active
    return None if reg is None else reg.members_doc()
