"""Rendezvous (highest-random-weight) placement for the router tier.

Each (member, run_id) pair gets a deterministic 64-bit score from a
SHA-256 digest; a run lives on the live member with the highest score.
The HRW property the federation leans on: removing one member of N
moves ONLY the runs that member owned (every other run's argmax is
unchanged), and adding one moves only the ~1/(N+1) of runs whose new
score beats their old owner's — no mass reshuffle on membership churn,
which is what keeps failover adoption proportional to the dead
member's share of the fleet. `tests/test_federation.py` pins both
directions on a fixed run-id corpus.

Scores are keyed on member_id (the member's advertised address), so
every router instance — and a restarted router with an empty placement
map — computes identical placements from the same membership view.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional


def score(member_id: str, run_id: str) -> int:
    """Deterministic 64-bit rendezvous weight for one (member, run)."""
    digest = hashlib.sha256(
        f"{member_id}|{run_id}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


def rank(run_id: str, member_ids: Iterable[str]) -> List[str]:
    """Members ordered by descending rendezvous score for `run_id`.
    The head is the placement; the tail is the failover/exclusion
    order (ties broken by member_id so the order is total)."""
    return sorted(set(member_ids),
                  key=lambda m: (score(m, run_id), m), reverse=True)


def place(run_id: str, member_ids: Iterable[str]) -> Optional[str]:
    """The highest-scoring member for `run_id`, or None if empty."""
    best = None
    best_key = None
    for m in set(member_ids):
        key = (score(m, run_id), m)
        if best_key is None or key > best_key:
            best_key = key
            best = m
    return best
