"""The federation router tier: placement, proxying, failover.

A thin TCP front speaking the ordinary wire protocol. It does three
jobs and deliberately nothing else:

* **Membership** — `RegisterMember` feeds the `MemberRegistry`; a
  sweeper thread declares death when heartbeats lapse
  (`GOL_FED_HEARTBEAT` / `GOL_FED_DEAD_AFTER`).
* **Placement** — `CreateRun` is placed by rendezvous (HRW) hashing on
  `run_id` over the live members (`hrw.place`); `ListRuns` fans out to
  every live member and merges. Run-scoped RPCs follow the placement
  map, falling back to the HRW owner for runs the router never saw
  created (a restarted router re-derives identical placements).
  `PinRun` (the live-migration redirect, PR 15) atomically re-points
  one run's placement at its new owner.
* **Failover** — a dead member's placed runs are adopted by survivors
  (`AdoptRun` → `FleetEngine.adopt_run` → the PR-10 quarantine→restore
  machinery, reading the per-run `run-<id>/` manifests under the
  shared checkpoint root). Proxied calls that land in the failover
  window WAIT (bounded by `GOL_FED_REROUTE`) for the adoption to
  re-home the run instead of failing — that wait IS the failover
  downtime the federation bench gates.

Proxying is a transparent byte relay (`wire.recv_head_raw` /
`send_raw` / `relay_payload`): the member sees the client's exact
bytes — `req_id`, `tc` trace context, negotiated codecs — and vice
versa, so the PR-10 retry/dedupe semantics survive the extra hop
unchanged. The router additionally keeps its OWN req_id dedupe window
for mutating methods: a retried mutate whose first attempt already
committed on a member that has since died is answered from the
recorded reply instead of re-executing on the adopting member.

Viewer re-routing needs no special machinery: xrle live-view deltas
are encoded against a per-(run, viewer) basis the SERVER caches, and
an adopting member has no such basis — its first reply to a re-routed
viewer is necessarily a fresh keyframe, which the client's own basis
bookkeeping accepts. Basis invalidation + keyframe on reconnect comes
free from relaying bytes instead of re-encoding them.

Router overhead (client-facing wall time minus the member-facing round
trip) feeds a log-bucket estimator published as
`gol_fed_router_overhead_ms{q}` — the bench gates its p99.
"""

from __future__ import annotations

import argparse
import collections
import os
import socket
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from gol_tpu import wire
from gol_tpu.federation import hrw
from gol_tpu.federation import registry as registry_mod
from gol_tpu.federation.registry import Member, MemberRegistry
from gol_tpu.obs import audit as obs_audit
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import export as obs_export
from gol_tpu.obs import slo as obs_slo
from gol_tpu.obs.log import log as obs_log
from gol_tpu.utils.envcfg import env_float

AUDIT_DIR_ENV = "GOL_AUDIT_DIR"

REROUTE_ENV = "GOL_FED_REROUTE"
REROUTE_DEFAULT_S = 10.0
DIAL_TIMEOUT_ENV = "GOL_FED_DIAL_TIMEOUT"
DIAL_TIMEOUT_DEFAULT_S = 2.0
MEMBER_TIMEOUT_ENV = "GOL_FED_MEMBER_TIMEOUT"
MEMBER_TIMEOUT_DEFAULT_S = 30.0

# Same window geometry as the member servers (server.py): bounded
# entries, bounded wait for a duplicate racing its first attempt.
DEDUPE_MAX = 512
DEDUPE_WAIT_S = 60.0

# Mutating methods mirror server.MUTATING_METHODS (imported lazily to
# keep this module importable without jax — server.py pulls engines in).
MUTATING_METHODS = frozenset({
    "CreateRun", "DestroyRun", "SetRule", "Checkpoint", "CFput",
    "DrainFlags", "RestoreRun", "AbortRun", "Profile", "KillProg",
    "AdoptRun", "Rescale", "ReceiveRun", "CommitRun", "PinRun",
})


class _DedupeEntry:
    __slots__ = ("done", "raw")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.raw: Optional[bytes] = None


class FederationRouter:
    """One router process (or in-process instance, for tests/bench)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[MemberRegistry] = None,
                 audit_dir: Optional[str] = None) -> None:
        self.host = host
        self.registry = registry or MemberRegistry()
        # Fleet telemetry plane (PR 16): durable audit log (memory-only
        # unless GOL_AUDIT_DIR / audit_dir names a directory), bounded
        # tsdb + rollups + alerting fed by heartbeat snapshots.
        if audit_dir is None:
            audit_dir = os.environ.get(AUDIT_DIR_ENV) or None
        self.audit_log = obs_audit.AuditLog(path=audit_dir)
        self.telemetry = obs_export.FleetTelemetry(
            audit_log=self.audit_log)
        # run_id -> {"member", "ckpt_every", "target_turn"}
        self._placements: Dict[str, dict] = {}
        self._plock = threading.Lock()
        self._dedupe: "collections.OrderedDict[str, _DedupeEntry]" = \
            collections.OrderedDict()
        self._dlock = threading.Lock()
        self._overhead = obs_slo.LogBucketEstimator()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        registry_mod.set_active(self.registry)
        obs_export.set_active_telemetry(self.telemetry)

    # -- lifecycle ----------------------------------------------------

    def start_background(self) -> "FederationRouter":
        for name, fn in (("gol-fed-accept", self._accept_loop),
                         ("gol-fed-sweep", self._sweep_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2.0)
        registry_mod.set_active(None)
        obs_export.set_active_telemetry(None)
        self.audit_log.close()

    # -- accept / dispatch --------------------------------------------

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            wire.enable_nodelay(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="gol-fed-conn", daemon=True)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        t0 = time.perf_counter()
        try:
            conn.settimeout(30.0)
            header, head_raw = wire.recv_head_raw(conn)
            n = wire.payload_nbytes(header)
            # Requests are buffered whole so a dial failure can retry
            # against another member (client payloads are seed boards
            # and control frames — small by construction; multi-GB
            # boards only ever ride REPLIES, which stream).
            payload = wire._recv_exact(conn, n) if n else b""
            method = str(header.get("method", ""))
            if method == "RegisterMember":
                mid = str(header.get("member_id", ""))
                ack = self.registry.register(
                    mid,
                    str(header.get("address", "")),
                    int(header.get("seq", 0)),
                    capacity=int(header.get("capacity", 0)),
                    mesh=header.get("mesh"))
                if ack.get("joined"):
                    self.audit_log.append("member_join", member=mid)
                elif ack.get("rejoined"):
                    self.audit_log.append("member_rejoin", member=mid)
                # Heartbeat-borne telemetry: ingest may mark the ack
                # with snap_resync when a delta finds no base state.
                self.telemetry.ingest(mid, header.get("snap"), ack)
                wire.send_msg(conn, ack)
                return
            if method == "GetTelemetry":
                wire.send_msg(conn, self._get_telemetry(header))
                return
            if method == "GetAudit":
                wire.send_msg(conn, self._get_audit(header))
                return
            if method == "ListRuns":
                wire.send_msg(conn, self._list_runs(header))
                return
            if method == "PinRun":
                # Live migration redirect (PR 15): the migration
                # coordinator re-points a run's placement at its new
                # owner. Served locally — this IS the atomic authority
                # flip; every later proxied call follows the new pin.
                wire.send_msg(conn, self._pin_run(header))
                return
            self._proxy(conn, header, head_raw, payload, method, t0)
        except (ConnectionError, OSError, wire.WireProtocolError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- ListRuns fan-out ---------------------------------------------

    def _list_runs(self, header: dict) -> dict:
        merged: List[dict] = []
        errors = 0
        for m in self.registry.live_members():
            try:
                resp, _ = self._member_call(
                    m, {"method": "ListRuns",
                        "run_id": header.get("run_id")})
                if "error" in resp:
                    errors += 1
                else:
                    for rec in resp.get("runs", []):
                        rec = dict(rec)
                        rec["member"] = m.member_id
                        merged.append(rec)
            except (ConnectionError, OSError,
                    wire.WireProtocolError):
                errors += 1
        merged.sort(key=lambda r: str(r.get("run_id", "")))
        doc = {"ok": True, "runs": merged}
        if errors:
            doc["members_unreachable"] = errors
        return doc

    # -- proxying -----------------------------------------------------

    def _proxy(self, conn: socket.socket, header: dict,
               head_raw: bytes, payload: bytes, method: str,
               t0: float) -> None:
        rid = header.get("run_id")
        if method == "CreateRun" and not rid:
            # The router owns id generation when the client left it to
            # the server: HRW needs the id BEFORE the member sees the
            # request, so stamp one and rewrite the header (the only
            # rewrite the relay ever performs).
            rid = uuid.uuid4().hex
            header = dict(header)
            header["run_id"] = rid
            head_raw = wire.frame_header(header)
        req_id = header.get("req_id")
        key = None
        ent = None
        if method in MUTATING_METHODS and req_id:
            key = f"{method}|{req_id}"
            ent, replay = self._dedupe_check(key)
            if replay is not None:
                obs.SERVER_DEDUP_HITS.labels(
                    method=obs.method_label(method)).inc()
                wire.send_raw(conn, replay)
                return
        try:
            reply_raw = self._forward_with_failover(
                conn, header, head_raw, payload, method, rid, t0)
            if ent is not None:
                ent.raw = reply_raw
        finally:
            if ent is not None:
                ent.done.set()

    def _dedupe_check(self, key: str) -> Tuple[Optional[_DedupeEntry],
                                               Optional[bytes]]:
        """(owned entry, None) for a first-seen req_id; (None, raw
        reply) for a duplicate whose first attempt committed. A
        duplicate racing an in-flight first attempt waits on it."""
        with self._dlock:
            ent = self._dedupe.get(key)
            if ent is None:
                ent = _DedupeEntry()
                self._dedupe[key] = ent
                while len(self._dedupe) > DEDUPE_MAX:
                    old_key, old = next(iter(self._dedupe.items()))
                    if old is ent or not old.done.is_set():
                        break
                    self._dedupe.pop(old_key, None)
                return ent, None
        if ent.done.wait(DEDUPE_WAIT_S) and ent.raw is not None:
            return None, ent.raw
        # First attempt never produced a recordable reply: the retry
        # proceeds as its own attempt (the MEMBER's dedupe window still
        # protects a same-member replay).
        return ent, None

    def _forward_with_failover(self, conn: socket.socket, header: dict,
                               head_raw: bytes, payload: bytes,
                               method: str, rid, t0: float,
                               ) -> Optional[bytes]:
        deadline = t0 + env_float(REROUTE_ENV, REROUTE_DEFAULT_S)
        excluded: set = set()
        member_s = 0.0
        while True:
            target = self._pick_target(method, rid, excluded)
            if target is None:
                if time.perf_counter() >= deadline:
                    wire.send_msg(conn, {
                        "error": "overloaded: no live federation "
                                 "member for this request"})
                    return None
                if excluded and not set(
                        self.registry.live_ids()) - excluded:
                    excluded.clear()  # everyone failed once: retry all
                time.sleep(0.02)
                continue
            tm0 = time.perf_counter()
            try:
                reply_raw = self._relay_once(conn, target, head_raw,
                                             payload)
            except _MemberUnreachable:
                excluded.add(target.member_id)
                if time.perf_counter() >= deadline:
                    wire.send_msg(conn, {
                        "error": "overloaded: federation reroute "
                                 "deadline exceeded"})
                    return None
                continue
            member_s = time.perf_counter() - tm0
            total_s = time.perf_counter() - t0
            self._overhead.observe(max(0.0, total_s - member_s))
            if method == "CreateRun" and rid:
                self._record_placement(rid, header, target.member_id)
            return reply_raw

    def _pick_target(self, method: str, rid,
                     excluded: set) -> Optional[Member]:
        live = {m.member_id: m for m in self.registry.live_members()}
        candidates = [mid for mid in live if mid not in excluded]
        if not candidates:
            return None
        if rid:
            with self._plock:
                pl = self._placements.get(rid)
            if pl is not None:
                mid = pl["member"]
                if mid in live and mid not in excluded:
                    return live[mid]
                # Placed on a dead/unreachable member: wait for the
                # sweeper's adoption to re-home it rather than guessing
                # (the caller loops under the reroute deadline).
                return None
            if method != "CreateRun":
                # Never saw this run created (router restart): the HRW
                # owner is where CreateRun would have put it.
                owner = hrw.place(str(rid), candidates)
                return live[owner] if owner else None
            placed = hrw.place(str(rid), candidates)
            return live[placed] if placed else None
        # No run scope (Ping/Stats/GetMetrics/...): deterministic pick.
        return live[sorted(candidates)[0]]

    def _relay_once(self, conn: socket.socket, target: Member,
                    head_raw: bytes, payload: bytes) -> Optional[bytes]:
        """One member round trip, bytes verbatim both ways. Raises
        _MemberUnreachable while nothing has been relayed to the
        client (safe to retry elsewhere); once reply bytes flow the
        call is committed."""
        host, _, port = target.address.rpartition(":")
        try:
            msock = socket.create_connection(
                (host or "127.0.0.1", int(port)),
                timeout=env_float(DIAL_TIMEOUT_ENV,
                                  DIAL_TIMEOUT_DEFAULT_S))
        except (OSError, ConnectionError) as e:
            raise _MemberUnreachable(str(e)) from e
        try:
            msock.settimeout(env_float(MEMBER_TIMEOUT_ENV,
                                       MEMBER_TIMEOUT_DEFAULT_S))
            wire.enable_nodelay(msock)
            try:
                wire.send_raw(msock, head_raw)
                if payload:
                    msock.sendall(payload)
                reply_header, reply_raw = wire.recv_head_raw(msock)
                rn = wire.payload_nbytes(reply_header)
            except (ConnectionError, OSError,
                    wire.WireProtocolError) as e:
                # Nothing reached the client yet — retryable.
                raise _MemberUnreachable(str(e)) from e
            wire.send_raw(conn, reply_raw)
            if rn:
                wire.relay_payload(msock, conn, rn)
                return None  # framed replies aren't replayable
            if str(reply_header.get("error", "")).startswith("moved:"):
                # A migration-window straggler: the member's answer is
                # "retry via the new pin", not a commit outcome — it
                # must never be replayed from the dedupe window or the
                # client's retry would see "moved:" forever.
                return None
            return reply_raw
        finally:
            try:
                msock.close()
            except OSError:
                pass

    def _pin_run(self, header: dict) -> dict:
        """Atomically re-point (or insert) a run's placement at a named
        live member. The single authority flip of a live migration: one
        dict store under _plock — calls relayed before it land on the
        source (which answers post-commit stragglers with a retryable
        "moved:"), calls after it land on the target."""
        rid = str(header.get("run_id") or "")
        mid = str(header.get("member_id") or "")
        if not rid or not mid:
            return {"error": "PinRun requires run_id and member_id"}
        member = self.registry.get(mid)
        if member is None or member.state != "live":
            return {"error": f"overloaded: member {mid} is not a live "
                             "federation member"}
        tt = header.get("target_turn")
        with self._plock:
            pl = self._placements.get(rid)
            prev = pl["member"] if pl else None
            self._placements[rid] = {
                "member": mid,
                "ckpt_every": int(header.get("ckpt_every", 0) or 0),
                "target_turn": int(tt) if tt is not None else None,
            }
        obs_log("fed.pinned", run_id=rid, member=mid, prev=prev)
        return {"ok": True, "run_id": rid, "member": mid, "prev": prev}

    # -- telemetry / audit queries (served locally) --------------------

    def _get_telemetry(self, header: dict) -> dict:
        """The fleet telemetry doc; an optional `series` key adds one
        tsdb series' merged buckets (`tier`, `since`, `labels`)."""
        doc = dict(self.telemetry.doc())
        name = header.get("series")
        if name:
            doc["series"] = {
                "name": str(name),
                "tier": str(header.get("tier", "raw")),
                "points": self.telemetry.query(
                    str(name),
                    labels=header.get("labels") or (),
                    tier=str(header.get("tier", "raw")),
                    since=float(header.get("since", 0.0) or 0.0)),
            }
        return {"ok": True, "telemetry": doc}

    def _get_audit(self, header: dict) -> dict:
        try:
            since_seq = int(header.get("since_seq", 0) or 0)
            limit = int(header.get("limit", 100) or 100)
        except (TypeError, ValueError):
            return {"error": "GetAudit: since_seq/limit must be ints"}
        return {"ok": True, "seq": self.audit_log.seq,
                "records": self.audit_log.tail(since_seq, limit)}

    def _record_placement(self, rid: str, header: dict,
                          member_id: str) -> None:
        tt = header.get("target_turn")
        with self._plock:
            self._placements[str(rid)] = {
                "member": member_id,
                "ckpt_every": int(header.get("ckpt_every", 0) or 0),
                "target_turn": int(tt) if tt is not None else None,
            }

    # -- member-side RPC (registry fan-out, adoption) ------------------

    def _member_call(self, member: Member, header: dict,
                     timeout: Optional[float] = None) -> tuple:
        host, _, port = member.address.rpartition(":")
        with socket.create_connection(
                (host or "127.0.0.1", int(port)),
                timeout=env_float(DIAL_TIMEOUT_ENV,
                                  DIAL_TIMEOUT_DEFAULT_S)) as sock:
            sock.settimeout(timeout if timeout is not None
                            else env_float(MEMBER_TIMEOUT_ENV,
                                           MEMBER_TIMEOUT_DEFAULT_S))
            wire.enable_nodelay(sock)
            wire.send_msg(sock, header)
            return wire.recv_msg(sock)

    # -- sweep / failover ---------------------------------------------

    def _sweep_loop(self) -> None:
        interval = min(1.0, max(
            0.05, registry_mod.heartbeat_interval_s() / 2.0))
        while not self._shutdown.wait(interval):
            try:
                self._sweep_once()
            except Exception as e:  # noqa: BLE001 — sweeper must live
                obs_log("fed.sweep_failed", level="error",
                        error=f"{type(e).__name__}: {e}")
        self._flush_overhead()

    def _sweep_once(self) -> None:
        for member in self.registry.sweep():
            obs_log("fed.member_dead", level="error",
                    member=member.member_id)
            self.audit_log.append("member_death",
                                  member=member.member_id,
                                  address=member.address)
            self._adopt_runs_of(member)
        # Rollups + alert evaluation ride the same sweep cadence the
        # death verdicts do — alert detection latency is bounded by
        # GOL_FED_DEAD_AFTER plus half a heartbeat, nothing more.
        self.telemetry.sweep(self.registry.members_doc())
        self._flush_overhead()

    def _flush_overhead(self) -> None:
        if not self._overhead.count:
            return
        p50, p95, p99 = self._overhead.percentiles((0.50, 0.95, 0.99))
        for q, v in zip(obs.SLO_QUANTILES, (p50, p95, p99)):
            if v is not None:
                obs.FED_ROUTER_OVERHEAD_MS.labels(q=q).set(
                    round(v * 1e3, 3))

    def _adopt_runs_of(self, member: Member) -> None:
        with self._plock:
            orphans = [(rid, dict(pl))
                       for rid, pl in self._placements.items()
                       if pl["member"] == member.member_id]
        for rid, pl in orphans:
            self._adopt_one(rid, pl)

    def _adopt_one(self, rid: str, pl: dict) -> None:
        """Re-home one orphaned run on the HRW-ranked survivors. On
        success the placement map re-points and proxied calls waiting
        under the reroute deadline proceed; on total failure the
        placement is dropped so lazy HRW discovery can still find the
        run if a member restores it by other means."""
        survivors = self.registry.live_ids()
        header = {"method": "AdoptRun", "run_id": rid,
                  "req_id": uuid.uuid4().hex,
                  "ckpt_every": pl.get("ckpt_every", 0)}
        if pl.get("target_turn") is not None:
            header["target_turn"] = pl["target_turn"]
        for mid in hrw.rank(rid, survivors):
            member = self.registry.get(mid)
            if member is None or member.state != "live":
                continue
            try:
                resp, _ = self._member_call(member, header)
            except (ConnectionError, OSError,
                    wire.WireProtocolError) as e:
                obs_log("fed.adopt_rpc_failed", level="warning",
                        run_id=rid, member=mid,
                        error=f"{type(e).__name__}: {e}")
                continue
            if "error" in resp:
                obs_log("fed.adopt_refused", level="warning",
                        run_id=rid, member=mid,
                        error=resp["error"])
                continue
            with self._plock:
                self._placements[rid]["member"] = mid
            obs_log("fed.adopted", run_id=rid, member=mid,
                    state=resp.get("run", {}).get("state"))
            self.audit_log.append("adopt", run_id=rid, member=mid)
            return
        obs.FED_ADOPTED_RUNS.labels(status="error").inc()
        obs_log("fed.adopt_failed", level="error", run_id=rid)
        with self._plock:
            self._placements.pop(rid, None)


class _MemberUnreachable(ConnectionError):
    """Dial/round-trip failed before any reply byte reached the
    client — the forward loop may retry another member."""


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="gol_tpu federation router (membership + HRW "
                    "placement + transparent proxy + failover)")
    ap.add_argument("--port", type=int, default=8799)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics + /healthz (0 = ephemeral)")
    ap.add_argument("--audit-dir", default=None,
                    help="directory for the durable gol-fleet-audit/1 "
                         "JSONL log (default $GOL_AUDIT_DIR, else "
                         "memory-only)")
    args = ap.parse_args(argv)

    router = FederationRouter(port=args.port, host=args.host,
                              audit_dir=args.audit_dir)
    if args.metrics_port is not None:
        from gol_tpu.obs.http import start_metrics_server
        msrv = start_metrics_server(args.metrics_port)
        print(f"metrics on :{msrv.port}", flush=True)
    router.start_background()
    print(f"gol_tpu federation router serving on :{router.port}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        router.shutdown()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
