"""Fleet federation (PR 12): membership, router tier, member failover.

`registry` — the router's member registry (heartbeat-stamped census);
`hrw` — rendezvous placement on run_id; `router` — the proxy tier with
HRW CreateRun placement, ListRuns fan-out, transparent byte relay, a
router-side req_id dedupe window, and dead-member run adoption through
`FleetEngine.adopt_run`; `agent` — the member-side heartbeat thread
(`server.py --federate`). See docs/ARCHITECTURE.md §Federation &
failover.
"""

from gol_tpu.federation.agent import FederationAgent  # noqa: F401
from gol_tpu.federation.hrw import place, rank, score  # noqa: F401
from gol_tpu.federation.registry import (  # noqa: F401
    MemberRegistry,
    dead_after_s,
    heartbeat_interval_s,
)
from gol_tpu.federation.router import FederationRouter  # noqa: F401
