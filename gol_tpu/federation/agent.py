"""Member-side federation agent: register + heartbeat to the router.

A daemon thread each federated fleet server starts (`server.py
--federate ROUTER_ADDR`). Every `GOL_FED_HEARTBEAT` seconds it sends a
`RegisterMember` message over the ordinary wire protocol carrying the
member's advertised address, capacity, mesh geometry, and a strictly
increasing sequence number. The router's registry is the only party
that stamps time; the agent only promises the sequence is monotonic.

Failures are soft by design: a beat that cannot reach the router is
logged and dropped — the NEXT beat is the retry, and the member keeps
serving its runs either way (the router declares death only after
`GOL_FED_DEAD_AFTER` of silence). One request per connection, exactly
like every other wire RPC.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

from gol_tpu import wire
from gol_tpu.federation.registry import heartbeat_interval_s
from gol_tpu.obs.export import SnapshotExporter
from gol_tpu.obs.log import log as obs_log


class FederationAgent:
    """Heartbeat loop from one member to one router."""

    def __init__(self, router_addr: str, address: str,
                 capacity: int = 0, mesh: Optional[dict] = None,
                 timeout: float = 5.0) -> None:
        host, _, port = router_addr.rpartition(":")
        self._router = (host or "127.0.0.1", int(port))
        self.address = address          # advertised; doubles as member_id
        self.capacity = int(capacity)
        self.mesh = mesh
        self._timeout = float(timeout)
        self._seq = 0
        self._exporter = SnapshotExporter()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat_once(self) -> Optional[dict]:
        """One RegisterMember round trip; None if the router was
        unreachable (the caller's loop just waits for the next beat)."""
        self._seq += 1
        header = {
            "method": "RegisterMember",
            "member_id": self.address,
            "address": self.address,
            "capacity": self.capacity,
            "seq": self._seq,
        }
        if self.mesh is not None:
            header["mesh"] = self.mesh
        try:
            # Telemetry rides the beat we already pay for (obs/export);
            # a snapshot failure must never cost us the heartbeat.
            snap = self._exporter.build()
            if snap is not None:
                header["snap"] = snap
        except Exception as e:  # noqa: BLE001 — beat > snapshot
            obs_log("fed.snapshot_failed", level="warning",
                    member=self.address,
                    error=f"{type(e).__name__}: {e}")
        try:
            with socket.create_connection(
                    self._router, timeout=self._timeout) as sock:
                sock.settimeout(self._timeout)
                wire.enable_nodelay(sock)
                wire.send_msg(sock, header)
                resp, _ = wire.recv_msg(sock)
            try:
                self._exporter.commit(resp)
            except Exception:  # noqa: BLE001
                pass
            return resp
        except (OSError, ConnectionError, wire.WireProtocolError) as e:
            obs_log("fed.heartbeat_failed", level="warning",
                    member=self.address,
                    error=f"{type(e).__name__}: {e}")
            return None

    def _run(self) -> None:
        first = True
        while not self._stop.is_set():
            resp = self.beat_once()
            if first and resp is not None:
                obs_log("fed.registered", member=self.address,
                        live=resp.get("live"))
                first = False
            self._stop.wait(heartbeat_interval_s())

    def start(self) -> "FederationAgent":
        self._thread = threading.Thread(
            target=self._run, name="gol-fed-agent", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
