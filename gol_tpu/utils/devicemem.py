"""Device-memory budget probe, shared by every subsystem that sizes
itself against HBM (the engine's pipeline-depth clamp, the sparse
engine's window ceiling) so the platform heuristic lives in ONE place.
"""

from __future__ import annotations


def half_device_memory(default: int, device=None) -> int:
    """Half the device's reported memory limit — kernel temporaries and
    working sets need the other half — or `default` where the platform
    reports none (e.g. the axon tunnel reports bytes_limit 0)."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        cap = (device.memory_stats() or {}).get("bytes_limit", 0)
        if cap:
            return int(cap) // 2
    except Exception:
        pass
    return default
