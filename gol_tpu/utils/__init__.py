from gol_tpu.utils.cell import Cell, alive_cells_from_board, read_alive_cells
from gol_tpu.utils.check import check
from gol_tpu.utils.visualise import alive_cells_to_string, board_diff

__all__ = [
    "Cell",
    "alive_cells_from_board",
    "read_alive_cells",
    "check",
    "alive_cells_to_string",
    "board_diff",
]
