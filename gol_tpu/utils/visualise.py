"""ASCII board rendering for failed-test diagnostics and the headless view.

Counterpart of reference `Local/util/visualise.go:21-108`: renders an
alive-cell list as a boxed ASCII grid, and renders the got-vs-want
side-by-side diff printed when a small-board test fails
(`Local/gol_test.go:45-52`).
"""

from __future__ import annotations

from typing import Iterable, Sequence, Tuple

import numpy as np

_ALIVE = "#"
_DEAD = " "


def _to_grid(cells: Iterable[Tuple[int, int]], w: int, h: int) -> np.ndarray:
    grid = np.zeros((h, w), dtype=np.uint8)
    for x, y in cells:
        if not (0 <= x < w and 0 <= y < h):
            # Silently dropping a stray cell would make board_diff hide
            # exactly the boundary off-by-ones it exists to expose.
            raise ValueError(
                f"cell ({x}, {y}) outside {w}x{h} board")
        grid[y, x] = 1
    return grid


def alive_cells_to_string(
    cells: Iterable[Tuple[int, int]], w: int, h: int
) -> str:
    """Boxed ASCII rendering of an alive-cell list
    (reference `visualise.go:21-48`)."""
    grid = _to_grid(cells, w, h)
    top = "┌" + "─" * w + "┐"
    bottom = "└" + "─" * w + "┘"
    rows = [
        "│" + "".join(_ALIVE if v else _DEAD for v in row) + "│"
        for row in grid
    ]
    return "\n".join([top, *rows, bottom])


def board_to_string(board: np.ndarray) -> str:
    h, w = board.shape
    ys, xs = np.nonzero(board)
    return alive_cells_to_string(zip(xs.tolist(), ys.tolist()), w, h)


def board_diff(
    got: Sequence[Tuple[int, int]],
    want: Sequence[Tuple[int, int]],
    w: int,
    h: int,
) -> str:
    """Side-by-side got/want rendering with a mismatch mask, the small-board
    failure report of the reference (`visualise.go:50-108`)."""
    g = _to_grid(got, w, h)
    e = _to_grid(want, w, h)
    bad = g != e
    lines = [f"{'got':^{w + 2}} {'want':^{w + 2}} {'diff':^{w + 2}}"]
    lines.append(("┌" + "─" * w + "┐ ") * 3)
    for y in range(h):
        row_g = "".join(_ALIVE if v else _DEAD for v in g[y])
        row_e = "".join(_ALIVE if v else _DEAD for v in e[y])
        row_d = "".join("X" if v else _DEAD for v in bad[y])
        lines.append(f"│{row_g}│ │{row_e}│ │{row_d}│")
    lines.append(("└" + "─" * w + "┘ ") * 3)
    return "\n".join(lines)
