"""Error check helper — counterpart of reference `Local/util/check.go:3-7`
(panic-on-error); in Python we simply raise."""

from __future__ import annotations


def check(condition: bool, message: str = "check failed") -> None:
    if not condition:
        raise RuntimeError(message)
