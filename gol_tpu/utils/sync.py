"""Reliable device synchronization.

`jax.Array.block_until_ready()` is a no-op on some experimental platform
plugins (observed: the 'axon' TPU tunnel returns immediately even while the
producing computation is still running). Everything in the framework that
needs a real completion barrier — benchmark timing, the engine's adaptive
chunk sizing — must therefore go through `wait()`, which additionally
fetches one element of the array to the host: a host transfer cannot
complete before the producer computation has.
"""

from __future__ import annotations

import jax
import numpy as np


def wait(x: jax.Array) -> jax.Array:
    """Block until `x` is fully computed on every device; returns `x`.

    One element is fetched from each addressable shard — a fetch only
    barriers the device that owns it, so fetching from shard 0 alone would
    let shards 1..N-1 still be executing when this returns."""
    x.block_until_ready()
    for s in x.addressable_shards:
        d = s.data
        np.asarray(jax.device_get(d[(0,) * d.ndim]))
    return x
