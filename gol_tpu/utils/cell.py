"""Cell type and PGM→alive-cell-list parsing.

Test-support counterpart of reference `Local/util/cell.go:10-56`:
`Cell{X, Y}` with X = column, Y = row, and `ReadAliveCells` which parses a
P5 PGM into the unordered set of alive cells (value 255).
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np


class Cell(NamedTuple):
    x: int  # column
    y: int  # row

    def __str__(self) -> str:
        return f"({self.x}, {self.y})"


def alive_cells_from_board(board: np.ndarray) -> List[Cell]:
    """Alive cells of an (H, W) board of {0, 255} (or {0, 1}) uint8.

    Iteration order is row-major like the reference's y-then-x scan
    (`Local/gol/distributor.go:78-86`), though consumers treat the result
    as an unordered set (`Local/gol_test.go:54-82`).
    """
    ys, xs = np.nonzero(board)
    return [Cell(int(x), int(y)) for x, y in zip(xs, ys)]


def read_alive_cells(path: str, width: int, height: int) -> List[Cell]:
    """Parse a P5 PGM into its alive-cell list (reference `cell.go:14-56`).

    Validates the dimensions against the caller's expectation the same way
    the reference cross-checks the header fields.
    """
    from gol_tpu.io.pgm import read_pgm

    board = read_pgm(path)
    h, w = board.shape
    if (w, h) != (width, height):
        raise ValueError(
            f"{path}: header says {w}x{h}, expected {width}x{height}"
        )
    return alive_cells_from_board(board)
