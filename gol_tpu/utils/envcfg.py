"""Defensive environment-variable parsing shared by the control plane
(client heartbeat, distributor reconnect, engine chunk cap): garbage or
negative values degrade to the documented default instead of aborting a
run with ValueError."""

from __future__ import annotations

import os


def env_float(name: str, default: float) -> float:
    """Non-negative float env var, `default` on garbage or negatives."""
    try:
        v = float(os.environ.get(name, default))
    except ValueError:
        return default
    return v if v >= 0 else default


def env_int(name: str, default: int, minimum: int = 1) -> int:
    """Int env var clamped to `minimum`, `default` on garbage."""
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return max(v, minimum)
