"""The GoL engine: broker + workers of the reference collapsed into one
device-resident TPU service.

The reference splits evolution across a broker turn loop
(`Server/gol/distributor.go:104-165`) and RPC workers
(`SubServer/distributor.go:48-82`), moving the whole board over the network
twice per turn. Here the board is a row-sharded device array that never
leaves the chips during a run: turns advance in compiled multi-turn chunks
(`lax.scan` inside `shard_map`), and the host thread only wakes between
chunks to honour the control protocol.

Control protocol parity (`Server/gol/distributor.go:54-83`):

    server_distributor  — blocking run               (API.ServerDistributor)
    alive_count         — (alive, turn) poll         (API.Alivecount)
    get_world           — board snapshot + turn      (API.GetWorld)
    cf_put              — control flag               (API.CFput)
    kill_prog           — die                        (API.KillProg)

Flag values keep the reference wire encoding (`Cf.Flag`,
`Server/gol/distributor.go:136-164`): 0 pause-toggle, 2 quit-run,
5 kill-cluster. The broker-internal sentinel 4 ("no keypress this turn") is
an artifact of reusing one Go channel as both mailbox and default-case and
has no counterpart here — an empty thread-safe queue already means "no
flag".

Chunking policy (SURVEY §7 hard parts 1-2): chunk sizes are powers of two
(bounded set of compiled programs), adapted so one chunk costs roughly
CHUNK_TARGET_SECONDS of wall clock — large enough for near-asymptotic
throughput, small enough that pause/quit/snapshot and the 2 s telemetry
ticker stay responsive. (alive, turn) pairs are only ever published at chunk
boundaries, so every published count is exact for its turn, matching the
reference's mutex-coherent pair (`Server:131-134,173-183`).
"""

from __future__ import annotations

import functools
import os
import queue
import threading
import time
from collections import deque
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from gol_tpu import journal as journal_mod
from gol_tpu.fleet.handles import SingleRunSurface
from gol_tpu.models.generations import GenerationsRule
from gol_tpu.models.largerthanlife import LargerThanLifeRule
from gol_tpu.models.lenia import ALIVE_THRESHOLD, LeniaRule
from gol_tpu.models.lifelike import CONWAY
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import devstats as obs_devstats
from gol_tpu.obs import flight as obs_flight
from gol_tpu.obs import halostats as obs_halostats
from gol_tpu.obs import prof as obs_prof
from gol_tpu.obs import timeline as obs_timeline
from gol_tpu.obs import trace as obs_trace
from gol_tpu.ops.bitpack import pack, packed_alive_count, unpack
from gol_tpu.ops.stencil import alive_count_exact, from_pixels, to_pixels
from gol_tpu.params import Params
from gol_tpu.parallel.halo import select_representation, shard_board
from gol_tpu.parallel.mesh import make_mesh, mesh_geometry
from gol_tpu.utils.envcfg import env_float, env_int
from gol_tpu.utils.sync import wait

# Control-flag wire values (reference Cf.Flag).
FLAG_PAUSE = 0
FLAG_QUIT = 2
FLAG_KILL = 5

# Marginal compute per chunk: the windowed adapter holds chunk wall in
# [target, 2*target], so worst-case control/query latency is about
# pipeline depth x 2*target (r4 defaults: 3 x 0.5 = 1.5 s — inside the
# 2 s ticker cadence and the reference's 5 s first-event bound,
# `Local/count_test.go:29-35`; pinned by test_quit_latency_bound).
# r4 measured (512², real chip, token pops): target 0.15/cap 2^20 gave
# 4.8M turns/s vs 0.5/2^22 at 5.2M — 0.25/2^21 takes most of that
# headroom at half the latency. Latency-sensitive deployments lower
# GOL_CHUNK_TARGET / GOL_MAX_CHUNK; throughput-hungry ones raise them.
CHUNK_TARGET_SECONDS = 0.25
CHUNK_TARGET_ENV = "GOL_CHUNK_TARGET"  # seconds; overrides the default
MAX_CHUNK = 1 << 21
# GOL_MAX_CHUNK=<n>: cap the adaptive chunk size. Bounds worst-case
# pause/quit/snapshot latency (and checkpoint staleness) at the cost of
# throughput; also the fault-injection tests' way of keeping an engine
# slow enough to kill mid-run deterministically.
MAX_CHUNK_ENV = "GOL_MAX_CHUNK"

# Dispatch pipeline: consecutive compiled chunks are issued asynchronously,
# up to this many in flight, because the dominant per-dispatch cost is a
# fixed host↔device round trip that overlaps with device compute (measured
# r3 on the tunnel: 8 chained dispatches complete in ~1.1 round trips,
# 0.023 s vs 0.170 s per dispatch). Control semantics survive: flags are
# still drained between issues (a pause stops the turn counter at once —
# in-flight chunks merely finish in the background), and every query path
# (alive_count, get_world, snapshot, checkpoint) reads the newest handle
# and blocks until it is real, so (alive, turn) pairs stay exact-at-turn.
# The costs are worst-case control/query latency of ~depth × chunk wall
# and up to depth + 1 board generations live in HBM (the per-run depth is
# clamped so those generations fit a fixed byte budget).
# r5 interleaved A/B on the real chip (512², 30M-turn reps alternating
# depths within one session): depth 3 and depth 4 are statistically
# identical (5.18-5.21M turns/s each) — the pipeline is already deep
# enough to hide the pop round trip, and the residual engine-vs-kernel
# gap tracks tunnel-window drift, not depth. 3 keeps the lower
# worst-case control latency.
PIPELINE_DEPTH = 3
PIPELINE_DEPTH_ENV = "GOL_PIPELINE_DEPTH"  # 1 disables (sync per chunk)
PIPELINE_BUDGET_ENV = "GOL_PIPELINE_BUDGET"  # bytes; overrides device limit
PIPELINE_BOARD_BUDGET = 8 << 30

# GOL_TRACE=<dir>: dump one jax.profiler trace of a representative chunk
# per run — the counterpart of the reference's runtime/trace TestTrace
# artifact (`Local/trace_test.go:19-27`, SURVEY §5).
TRACE_ENV = "GOL_TRACE"

# GOL_RUN_REPORT=<path>: append a JSON-lines chunk-timeline run report
# (`gol_tpu/obs/timeline.py`, schema gol-run-report/1) — one record per
# retired chunk plus run_start/run_end bookends. Set by `--run-report`.
RUN_REPORT_ENV = obs_timeline.RUN_REPORT_ENV

# GOL_CKPT=<dir> [GOL_CKPT_EVERY=<seconds>]: periodic crash-safe
# checkpoints during a run. The reference has only in-memory state plus
# user-triggered PGM snapshots (SURVEY §5 "checkpoint/resume"); this adds
# the crash-safe variant: an atomic .npz of (world, turn) the engine can
# reload before a CONT=yes reattach.
CKPT_ENV = "GOL_CKPT"
CKPT_EVERY_ENV = "GOL_CKPT_EVERY"
CKPT_EVERY_DEFAULT = 30.0


class EngineKilled(RuntimeError):
    """Raised on any call after kill_prog — the in-process stand-in for the
    reference worker's os.Exit(0) (`SubServer/distributor.go:42-45`)."""


class EngineBusy(RuntimeError):
    """A run was submitted while the engine is already running a board.
    Typed (and wire-mapped with a 'busy:' prefix) so the controller's
    partition-recovery logic can recognise its own orphaned run without
    matching on message text."""


def _firing_row_counts(cells, repr_: str):
    """(H,) int32 per-row counts of the FIRING population — the ONE
    per-repr counting rule, shared by the chunk token, the pad-crop
    fallback, and the reconcile path so the counts can never
    desynchronize: popcounts for the packed reprs (gen3's alive plane
    leads), state==1 for gen8, sums for {0,1} u8. Traceable (used
    inside jit)."""
    import jax.numpy as jnp
    from jax import lax

    if repr_ == "packed":
        return jnp.sum(lax.population_count(cells), axis=-1,
                       dtype=jnp.int32)
    if repr_ == "gen3":
        return jnp.sum(lax.population_count(cells[0]), axis=-1,
                       dtype=jnp.int32)
    if repr_ == "gen8":
        return jnp.sum((cells == 1).astype(jnp.int32), axis=-1)
    if repr_ == "f32":
        # Continuous boards (Lenia): "firing" is mass above the
        # documented telemetry threshold (models/lenia.py).
        return jnp.sum((cells > ALIVE_THRESHOLD).astype(jnp.int32),
                       axis=-1)
    return jnp.sum(cells, axis=-1, dtype=jnp.int32)


def _board_width(cells, repr_: str) -> int:
    """Cell-count width of a board array in any representation (the
    packed reprs store 32 cells per word on the last axis)."""
    w = cells.shape[-1]
    return w * 32 if repr_ in ("packed", "gen3") else w


def _banded_device_rows(cells, h: int, row_nbytes: int):
    """Yield the first `h` rows of a device array as host numpy bands of
    roughly GOL_WIRE_BAND_BYTES each, with the device→host copy of band
    i+1 started (`copy_to_host_async`) before band i is yielded — while
    the consumer pushes band i into a socket, the next band is already
    in flight off the device, and no full-board host copy (the old
    `np.ascontiguousarray` transient) ever exists. Boards that fit one
    band degrade to a single device_get."""
    from gol_tpu import wire

    band_rows = max(1, wire.band_bytes() // max(1, row_nbytes))
    slices = [cells[r0:min(r0 + band_rows, h)]
              for r0 in range(0, h, band_rows)]

    def _stage(x) -> None:
        # Counted so a test can prove the no-viewer turn path never
        # starts a banded copy (snapshot work is strictly demand-driven).
        obs.ENGINE_BAND_COPIES.inc()
        try:
            x.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass  # plain numpy fallback arrays / backends without it

    if slices:
        _stage(slices[0])
    for i, s in enumerate(slices):
        if i + 1 < len(slices):
            _stage(slices[i + 1])
        yield np.asarray(jax.device_get(s))


@functools.lru_cache(maxsize=64)
def _padded_row_counts(repr_: str, pad: int):
    """Cached jit fusing extension-crop + per-row firing count into ONE
    dispatch — a separate eager slice would double the fallback path's
    round trips on the tunnel. All four reprs can carry a pad (r5: the
    Generations family rides wrap-extension too); the crop is on axis
    -2, the row axis of every representation (gen3's plane axis leads)."""

    @jax.jit
    def rows(cells):
        return _firing_row_counts(
            cells[..., : cells.shape[-2] - pad, :], repr_)

    return rows


def view_factor(h: int, w: int, max_cells: int) -> int:
    """Smallest integer downsample factor f with
    ceil(h/f) * ceil(w/f) <= max_cells — the ONE factor rule shared by
    the dense and sparse GetView implementations."""
    f = max(1, int(np.ceil(np.sqrt(h * w / max_cells))))
    while -(-h // f) * -(-w // f) > max_cells:
        f += 1
    return f


@functools.lru_cache(maxsize=32)
def _view_program(repr_: str, pad: int, f: int, rule):
    """Cached jit: board state -> (ceil(H/f), ceil(W/f)) uint8 pixel
    view, ONE program + one O(viewport) transfer (r5 — VERDICT r4 #3:
    the live view must never move the full board to the host). Each
    view pixel is the BRIGHTEST pixel of its f x f block — for
    life-like boards that is any-alive; for Generations the firing
    state dominates the dying grays. The packed reprs OR-reduce the
    word rows first, so nothing board-sized is ever materialised wider
    than one band of unpacked rows."""
    import jax.numpy as jnp
    from jax import lax

    def or_rows(words):
        """(H, Wp) uint32 -> (ceil(H/f), Wp): bitwise OR per f-row band
        (max would lose bits; OR keeps every column's any-alive)."""
        h, wp = words.shape
        hp = -(-h // f) * f
        words = jnp.pad(words, ((0, hp - h), (0, 0)))
        return lax.reduce(words.reshape(hp // f, f, wp),
                          jnp.uint32(0), lax.bitwise_or, (1,))

    def max_cols(px):
        """(vh, W) -> (vh, ceil(W/f)): per-f-column block max."""
        vh, w = px.shape
        wp = -(-w // f) * f
        px = jnp.pad(px, ((0, 0), (0, wp - w)))
        return px.reshape(vh, wp // f, f).max(axis=2)

    def block_max(px):
        """(H, W) -> (ceil(H/f), ceil(W/f)) block max, both axes."""
        h, w = px.shape
        hp, wp2 = -(-h // f) * f, -(-w // f) * f
        px = jnp.pad(px, ((0, hp - h), (0, wp2 - w)))
        return px.reshape(hp // f, f, wp2 // f, f).max(axis=(1, 3))

    @jax.jit
    def view(cells):
        core = (cells[..., : cells.shape[-2] - pad, :] if pad else cells)
        if repr_ == "packed":
            return (max_cols(unpack(or_rows(core)))
                    * jnp.uint8(255)).astype(jnp.uint8)
        if repr_ == "u8":
            return (block_max(core) * jnp.uint8(255)).astype(jnp.uint8)
        if repr_ == "f32":
            # Continuous state quantized to the brightest mass of each
            # block — the live view is presentation, so the lossy /255
            # quantization is fine here (snapshots stay float).
            px = jnp.clip(jnp.rint(block_max(core) * 255.0), 0.0, 255.0)
            return px.astype(jnp.uint8)
        if repr_ == "gen8":
            from gol_tpu.models.generations import gray_levels

            levels = jnp.asarray(gray_levels(rule))
            return block_max(levels[core]).astype(jnp.uint8)
        # gen3: firing blocks at 255, else dying blocks at the dying
        # gray, else 0 — the brightest state of the block.
        from gol_tpu.models.generations import gray_levels

        a = max_cols(unpack(or_rows(core[0])))
        d = max_cols(unpack(or_rows(core[1])))
        dying = jnp.uint8(gray_levels(rule)[2])
        return jnp.maximum(a * jnp.uint8(255), d * dying).astype(jnp.uint8)

    return view


@jax.jit
def _gen3_state(cells):
    """Stacked packed (2, H, Wp) planes -> uint8 state board (H, W),
    one program, one transfer."""
    import jax.numpy as jnp

    return (unpack(cells[0]) + 2 * unpack(cells[1])).astype(jnp.uint8)


@functools.lru_cache(maxsize=64)
def _tokened_run(run_fn, mesh, rule, repr_, pad):
    """Wrap a sharded run in one jitted program that ALSO returns a tiny
    completion token (a full-board reduction — it reads every shard on
    every device, 1-D or 2-D mesh alike, so its value existing implies
    every device finished the chunk; the extra board read is device-side
    bandwidth, microseconds against a multi-second chunk).

    Why a token at all: `block_until_ready` is a no-op on the axon
    plugin, and the fallback barrier (`utils/sync.wait`) fetches an
    element via `x[0,..]`, which dispatches a fresh slice PROGRAM through
    the tunnel before the transfer — two serialized ~0.17 s round trips
    per chunk pop, the dominant term in the r3 engine-vs-kernel gap.
    Emitting the token inside the chunk program makes the pop a pure
    small transfer: one round trip, no compile, no extra dispatch.

    r5 (VERDICT r4 weak #1): the token IS the alive count. The reduction
    the token already paid for is made useful: per-row counts of the
    FIRING population (popcounts for the packed reprs, state==1 for
    gen8, sums for u8), wrap-extension pad rows cropped, folded into at
    most a handful of int32 partial sums whose per-bin value provably
    fits int32 (rows-per-bin is capped at (2^31-1)/width; the host sums
    the bins in int64). Every chunk pop thus publishes an exact
    (alive, turn) pair for free, and `alive_count()` never dispatches —
    the same zero-device-work poll path the sparse engine has
    (`sparse_engine.py:185-193`), now on the dense engine."""
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("k",))
    def go(cells, k):
        out = run_fn(cells, k, mesh, rule)
        rows = _firing_row_counts(out, repr_)
        width = _board_width(out, repr_)
        if pad:
            rows = rows[: rows.shape[0] - pad]
        h = rows.shape[0]
        rows_per_bin = min(h, max(1, (2**31 - 1) // max(width, 1)))
        g = -(-h // rows_per_bin)  # ceil: bins of provably-int32 sums
        hp = g * rows_per_bin
        if hp != h:
            rows = jnp.concatenate(
                [rows, jnp.zeros(hp - h, jnp.int32)])
        token = jnp.sum(rows.reshape(g, rows_per_bin), axis=1,
                        dtype=jnp.int32)
        return out, token

    return go


def _next_chunk(chunk: int, remaining: int) -> int:
    """Largest power of two ≤ min(chunk, remaining). Keeping every
    compiled loop length a power of two bounds the set of distinct XLA
    programs per mesh at O(log MAX_CHUNK) — crucial for a long-lived
    engine (the detach/resume contract) serving runs of arbitrary turn
    counts, where exact-length remainder chunks would compile one fresh
    program per distinct count, unbounded over the engine's lifetime.
    The one-off compiles of the odd-power tail sizes the ×4 ramp skips
    happen once per process and are amortized by the persistent compile
    cache thereafter."""
    k = chunk
    while k > remaining:
        k //= 2
    return max(k, 1)


class ControlFlagProtocol:
    """The reference control-flag protocol + liveness surface, shared
    by every engine flavour (dense `Engine`,
    `sparse_engine.SparseEngine`). Subclasses provide: `_flags`
    (queue.Queue), `_killed` (bool), `_abort` (threading.Event),
    `_state_lock`, `_running`, `_run_token`, `_turn`. One
    implementation so the subtle semantics (drain inside the lock,
    pause_only, token-scoped abort) cannot drift between engines."""

    def cf_put(self, flag: int) -> None:
        """Post a control flag (ref `Server:54-60`)."""
        self._check_alive()
        if flag not in (FLAG_PAUSE, FLAG_QUIT, FLAG_KILL):
            raise ValueError(f"unknown control flag {flag}")
        self._flags.put(flag)

    def drain_flags(self, pause_only: bool = False) -> None:
        """Discard STALE control flags — those left by a previous
        (detached/dead) controller session on a PARKED engine. A no-op
        while a run is in flight: an attaching observer must not be able
        to wipe the running controller's pause/quit flags out of the
        queue (flags are not token-scoped the way abort_run is).
        Reference analog: the broker's flag channel is emptied by its
        per-turn sentinel cycle, `Server:136-150`.

        `pause_only` drops only FLAG_PAUSE entries (re-queuing the rest
        in order): the loss-recovery path uses it because a stranded
        pause toggle would invert controller-vs-engine pause state on
        the resubmitted run, while a stranded quit/kill is an idempotent
        order the resubmitted run SHOULD honour."""
        self._check_alive()
        with self._state_lock:
            if self._running:
                return
            # Drain INSIDE the lock: a run starting in the gap between
            # the check and the drain could have its controller's early
            # pause/quit flags wiped by this observer (server_distributor
            # flips _running under the same lock, so holding it here
            # excludes that window; cf_put itself is queue-safe and
            # lock-free).
            kept = []
            try:
                while True:
                    flag = self._flags.get_nowait()
                    if pause_only and flag != FLAG_PAUSE:
                        kept.append(flag)
            except queue.Empty:
                pass
            for flag in kept:
                self._flags.put(flag)

    def kill_prog(self) -> None:
        """Mark the engine dead (ref `Server:77-80`, worker os.Exit)."""
        self._killed = True

    def abort_run(self, token: Optional[str] = None) -> bool:
        """Stop the current run iff `token` matches the run owner's —
        the recovery takeover after a transient partition (the controller
        resubmits, finds its pre-partition orphan still computing, and
        reclaims the engine). No reference counterpart: the Go broker has
        no way to be reclaimed by a controller that lost it. No-op (False)
        when idle or when the run belongs to another controller; on abort
        the state is preserved at the stop point exactly like FLAG_QUIT.
        A tokenless run cannot be aborted at all (None never matches) —
        otherwise any peer sending AbortRun with no token could stop a
        legacy client's run."""
        self._check_alive()
        with self._state_lock:
            if (token is not None and self._running
                    and self._run_token == token):
                self._abort.set()
                return True
            return False

    def ping(self) -> int:
        """Liveness probe: the completed turn, with no device work — cheap
        enough for a sub-second heartbeat. Beyond-reference addition (the
        reference has no failure detection, SURVEY §5); a killed engine
        still answers (with EngineKilled), distinguishing 'deliberately
        down' from 'lost'."""
        self._check_alive()
        with self._state_lock:
            return self._turn

    def _check_alive(self) -> None:
        if self._killed:
            raise EngineKilled("engine has been killed")

    def _handle_flags(self) -> bool:
        """Drain flags; block while paused. Returns True to quit the run
        (reference handshake `Server/gol/distributor.go:136-164`)."""
        paused = False
        while True:
            if self._killed or self._abort.is_set():
                return True
            try:
                flag = self._flags.get_nowait() if not paused \
                    else self._flags.get(timeout=0.05)
            except queue.Empty:
                if not paused:
                    return False
                continue
            if flag == FLAG_PAUSE:
                paused = not paused
                if not paused:
                    return False
            elif flag in (FLAG_QUIT, FLAG_KILL):
                # Both break the run loop and still return the board to the
                # controller; on kill the reference broker first downs its
                # workers then returns, and only dies when the controller
                # calls KillProg afterwards (`Server:157-164`,
                # `Local/gol/distributor.go:213-216`). Our "workers" are the
                # compiled program — nothing to down until kill_prog().
                return True


class Engine(SingleRunSurface, ControlFlagProtocol):
    """Holds (world, turn) authoritatively across runs — the detach/resume
    contract (reference broker globals `world`/`turn`, `Server:29-30`, and
    the `CONT=yes` path, `Local/gol/distributor.go:171-178`)."""

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        rule=CONWAY,
        mesh_shape: Optional[Tuple[int, int]] = None,
    ) -> None:
        """`rule`: a `LifeLikeRule` or a `GenerationsRule` — both families
        ride the full control stack (r4). `mesh_shape=(rows, cols)`
        requests the 2-D mesh (perimeter deep
        halos, `parallel/mesh2d.py`) instead of 1-D row sharding; it also
        honours GOL_MESH="RxC" from the environment. The engine falls back
        to 1-D when the board or device count doesn't fit the request."""
        self._devices = list(devices if devices is not None else jax.devices())
        self._rule = rule
        # Compile observability: jax.monitoring listeners behind the
        # gol_compile_* families. Idempotent — safe across engines.
        obs_devstats.install_compile_hooks()
        if mesh_shape is None:
            spec = os.environ.get("GOL_MESH", "").lower()
            if "x" in spec:
                try:
                    r, c = spec.split("x", 1)
                    mesh_shape = (int(r), int(c))
                    if mesh_shape[0] <= 0 or mesh_shape[1] <= 0:
                        raise ValueError("non-positive mesh dims")
                except ValueError:
                    import warnings

                    warnings.warn(
                        f"GOL_MESH={spec!r} is not 'RxC'; "
                        "falling back to 1-D row sharding")
                    mesh_shape = None
        self._mesh_shape = mesh_shape
        self._state_lock = threading.Lock()
        # Row-sharded board state; representation tag `_repr`:
        #   "packed" — life-like, bit-packed uint32 (H, W/32) (32
        #              cells/lane, 1/8th the HBM traffic, `ops/bitpack`)
        #   "u8"     — life-like, {0,1} uint8 (H, W) (width not a whole
        #              number of words)
        #   "gen8"   — Generations, uint8 states (H, W)
        #   "gen3"   — 3-state Generations, packed planes (2, H, W/32)
        # `_packed` stays the life-like-packed boolean for the paths
        # (mesh2d, Stats) that predate the multi-family engine.
        self._cells: Optional[jax.Array] = None
        self._repr = "u8"
        self._packed = False
        # Wrap-extension rows appended for the exact-shard-count path
        # (`parallel/halo.exact_shard_ext`): every query/serialization
        # path crops them — they are representation, not board.
        self._pad_rows = 0
        self._turn = 0
        # Coherent (alive, turn) pair published at every chunk boundary
        # (the chunk token carries the count — `_tokened_run`) and at
        # submit/restore: the poll path (`alive_count`, the 2 s ticker)
        # reads this under the lock with ZERO device work, so telemetry
        # latency is immune to pipeline depth and chunk wall. Mirrors
        # the reference's mutex-coherent pair (`Server:131-134`) and the
        # sparse engine's publication discipline.
        self._alive_pub: Optional[Tuple[int, int]] = None
        # Monotonic floor on the published turn within a run: new runs
        # and checkpoint restores legitimately rewind it (reset_floor);
        # anything else moving it backwards is a publication-ordering
        # bug, counted rather than asserted so production keeps running
        # (gol_engine_published_turn_regressions_total).
        self._pub_floor = -1
        self._flags: "queue.Queue[int]" = queue.Queue()
        self._killed = False
        self._running = False
        # Owner token of the current run + its abort signal (abort_run).
        self._run_token: Optional[str] = None
        self._abort = threading.Event()
        # Dispatch-floor estimate for the chunk adapter (min elapsed ever
        # observed for a full chunk); engine-lifetime, it only sharpens.
        self._fixed_cost_est = float("inf")
        # Sliding (pop time, turns) window for the pipelined-regime
        # adapter, plus a count of post-reset pops to ignore; see
        # _adapt_chunk_windowed.
        self._pace_window: deque = deque(maxlen=8)
        self._pace_skip = 0
        self._max_chunk = MAX_CHUNK
        self._chunk_target = CHUNK_TARGET_SECONDS
        # Rolling throughput telemetry for the Stats RPC.
        self._last_chunk = 0
        self._turns_per_s = 0.0
        # Mean host-side µs per retired chunk spent outside the device
        # wait (dispatch, publish, flag poll, pipeline bookkeeping) —
        # the small-board scaling cost; refreshed at each metrics flush.
        self._chunk_overhead_us = 0.0
        # Converged chunk size per (board shape, repr, mesh, target):
        # later runs of the same configuration start there, skipping the
        # synchronous ramp's round trips.
        self._chunk_hints: dict = {}
        # Mesh geometry of the most recently submitted run
        # (parallel.mesh.mesh_geometry dict), for stats()/Stats.
        self._mesh_geom: Optional[dict] = None
        # Broadcast publish hook (gol_tpu/broadcast.py): invoked once
        # per retired chunk, no arguments, must be cheap and never
        # raise (the hub installs threading.Event.set). None = no
        # broadcast tier attached, and the attribute read is the only
        # per-chunk cost.
        self._bcast_notify = None

    def _publish_locked(self, alive: int, turn: int,
                        reset_floor: bool = False) -> None:
        """Publish a coherent (alive, turn) pair. Caller holds
        `_state_lock`. Every publication site routes through here so the
        published-turn gauge and its monotonicity accounting can't drift
        from `_alive_pub`: within a run the gauge only moves forward
        (an out-of-order publication increments the regressions counter
        and leaves the gauge at the floor); a new submit or checkpoint
        restore resets the floor (`reset_floor`)."""
        if reset_floor:
            self._pub_floor = -1
        self._alive_pub = (alive, turn)
        if turn < self._pub_floor:
            obs.ENGINE_PUBLISHED_TURN_REGRESSIONS.inc()
            return
        self._pub_floor = turn
        obs.ENGINE_PUBLISHED_TURN.set(turn)
        obs.ENGINE_PUBLISHED_ALIVE.set(alive)

    # ------------------------------------------------------------------ RPC

    def server_distributor(
        self,
        params: Params,
        world: np.ndarray,
        sub_workers: Sequence[str] = (),
        start_turn: int = 0,
        token: Optional[str] = None,
    ) -> Tuple[np.ndarray, int]:
        """Blocking run: evolve `world` for `params.turns` turns, honouring
        control flags between chunks. Returns ({0,255} board, completed turn).

        `sub_workers` mirrors the reference's worker-address list
        (`SUB`, `Local/gol/distributor.go:100-105`): its length is the
        requested shard count. `start_turn` carries the resume arithmetic
        explicitly (the reference keeps it in a broker global). `token`
        identifies the submitting controller so `abort_run` can stop an
        orphaned run of the SAME controller after a transient partition
        without being able to touch anyone else's.
        """
        self._check_alive()
        if self._running:
            raise EngineBusy("engine already running a board")

        height, width = world.shape
        pad_rows = 0  # wrap-extension rows (exact-shard-count path)
        # Shard-count request: worker-list length (reference SUB),
        # falling back to the `threads` hint (per-worker fan-out) —
        # one resolution shared by every family branch.
        requested = len(sub_workers) if sub_workers else params.threads
        requested = max(1, min(requested, len(self._devices)))
        # Temporal fusion (GOL_FUSE_K): resolve the pinned depth ONCE at
        # submit and select a stable-identity fused run fn, so the
        # jit/_tokened_run caches key on the depth via run-fn identity —
        # an env flip mid-process can never serve a stale compiled
        # program. fuse_eff records the depth this run actually applies
        # (1 where a branch has no fused tier: u8/gen8 boards, the
        # wrap-extension path, multi-shard gen3).
        from gol_tpu.ops.fused import configured_fuse_k

        fuse = configured_fuse_k()
        fuse_eff = 1
        if isinstance(self._rule, GenerationsRule):
            # Multi-state family on the SAME control stack (r4 — VERDICT
            # r3 weak #5): uint8 states row-sharded through the generic
            # halo machinery; 3-state rules on word-aligned widths ride
            # the bit-packed two-plane kernel, stacked as one
            # (2, H, W/32) array so every single-array state path
            # (publication, token, checkpoint) applies unchanged.
            # r5 (VERDICT r4 #2): non-divisible heights get the SAME
            # wrap-extension exact-N treatment as the life-like family —
            # no divisor fallback left anywhere in the engine.
            from gol_tpu.models.generations import from_pixels_gen
            from gol_tpu.parallel.halo import (
                exact_shard_ext,
                extend_rows,
                extended_run_fn,
                shard_board_gen3,
                sharded_gen3_run_turns,
                sharded_generations_run_turns,
            )
            from gol_tpu.ops.bitpack import WORD_BITS

            # Routes through the same loud-fallback path as every other
            # unsatisfiable 2-D mesh reason (ADVICE r4 + review): a
            # Generations request always resolves to None with a warn.
            self._resolve_mesh2d(height, width, False, generations=True)
            state = from_pixels_gen(world, self._rule)
            # Turn-0 firing count for the publication, from the
            # already-decoded state board (no second pixel scan).
            alive0 = int((state == 1).sum())
            pad_rows = exact_shard_ext(height, requested)
            mesh = make_mesh(requested, self._devices)
            if self._rule.states == 3 and width % WORD_BITS == 0:
                import jax.numpy as jnp

                repr_ = "gen3"
                a = pack((state == 1).astype(np.uint8))
                d = pack((state == 2).astype(np.uint8))
                if pad_rows:
                    # extend_rows is host-side; the round trip is the
                    # price of the exact-N path only — divisible
                    # heights keep the planes on device.
                    stacked = extend_rows(
                        np.stack([np.asarray(a), np.asarray(d)]),
                        pad_rows, axis=1)
                    run = extended_run_fn(height, pad_rows, "gen3")
                else:
                    stacked = jnp.stack([a, d])
                    run = sharded_gen3_run_turns
                    if fuse > 1 and requested == 1:
                        # Multi-shard gen3 stays per-turn (a k-deep gen3
                        # halo would ship both planes — a different
                        # traffic model, see sharded_gen3_run_turns).
                        from gol_tpu.parallel.halo import fused_gen3_run_fn

                        run = fused_gen3_run_fn(fuse)
                        fuse_eff = fuse
                cells = shard_board_gen3(stacked, mesh)
            else:
                repr_ = "gen8"
                if pad_rows:
                    state = extend_rows(state, pad_rows)
                    run = extended_run_fn(height, pad_rows, "gen8")
                else:
                    run = sharded_generations_run_turns
                cells = shard_board(state, mesh)
        elif isinstance(self._rule, (LargerThanLifeRule, LeniaRule)):
            # Conv/FFT kernel-tier families (PR 20): the large-radius
            # neighborhood sum has no bitplane form and no halo
            # machinery, so these boards run single-shard; the tier
            # policy (`ops/conv.select_tier`) picks direct-space conv
            # or FFT per (board, radius, dtype) and the choice rides
            # the run-fn identity through the jit caches.
            import warnings

            from gol_tpu.ops import conv as conv_ops

            if self._mesh_shape is not None:
                warnings.warn(
                    f"2-D mesh request {self._mesh_shape} ignored (the "
                    f"conv/FFT kernel tier runs single-shard)")
            if requested > 1:
                warnings.warn(
                    f"{requested} shards requested for rule "
                    f"{self._rule.rulestring}; the conv/FFT kernel tier "
                    f"has no halo machinery — running single-shard")
            mesh = make_mesh(1, self._devices)
            if isinstance(self._rule, LeniaRule):
                repr_ = "f32"
                if world.dtype == np.float32:
                    state = np.clip(np.ascontiguousarray(world),
                                    0.0, 1.0)
                else:
                    # u8 pixel ingest (the wire's universal codec):
                    # the state arrives quantized to /255 levels.
                    state = (np.asarray(world, dtype=np.float32)
                             / np.float32(255.0))
                alive0 = int((state > ALIVE_THRESHOLD).sum())
                tier = conv_ops.select_tier(
                    height, width, self._rule.radius, "float32",
                    allowed=("conv", "fft"))
                run = conv_ops.lenia_run_fn(tier)
                cells = shard_board(state, mesh)
            else:
                repr_ = "u8"
                alive0 = int(np.count_nonzero(np.asarray(world)))
                tier = conv_ops.select_tier(
                    height, width, self._rule.radius, "uint8",
                    allowed=("conv", "fft"))
                run = conv_ops.ltl_run_fn(tier)
                cells = shard_board(from_pixels(world), mesh)
            conv_ops.note_dispatch(tier)
        else:
            packed, run = select_representation(width)
            repr_ = "packed" if packed else "u8"
            # Turn-0 firing count (any nonzero pixel is alive, the
            # `from_pixels` predicate): one optimized host pass, no
            # boolean temporary.
            alive0 = int(np.count_nonzero(np.asarray(world)))
            cells01 = from_pixels(world)
            mesh2d = self._resolve_mesh2d(height, width, packed)
            if mesh2d is not None:
                from gol_tpu.parallel.mesh2d import (
                    shard_board2d,
                    sharded_packed_run_turns_2d,
                )

                mesh = mesh2d
                run = sharded_packed_run_turns_2d
                if fuse > 1:
                    from gol_tpu.parallel.mesh2d import fused_run_fn_2d

                    run = fused_run_fn_2d(fuse)
                    fuse_eff = fuse
                cells = shard_board2d(pack(cells01), mesh)
            else:
                from gol_tpu.parallel.halo import (
                    exact_shard_ext,
                    extend_rows,
                    extended_run_fn,
                )

                pad_rows = exact_shard_ext(height, requested)
                if pad_rows:
                    # Exact requested shard count on a non-divisible
                    # height (reference remainder-spread parity,
                    # `Server/gol/distributor.go:106-116`): wrap-extend
                    # the board so it splits evenly and let GSPMD place
                    # the cross-shard seam traffic.
                    mesh = make_mesh(requested, self._devices)
                    base = np.asarray(
                        pack(cells01) if packed else cells01)
                    cells = shard_board(
                        extend_rows(base, pad_rows), mesh)
                    run = extended_run_fn(height, pad_rows, packed)
                else:
                    # pad_rows == 0 means requested == 1 or it divides
                    # the height — equal shards, no downgrade possible.
                    mesh = make_mesh(requested, self._devices)
                    cells = shard_board(
                        pack(cells01) if packed else cells01, mesh)
                    if packed and fuse > 1:
                        from gol_tpu.parallel.halo import fused_run_fn

                        run = fused_run_fn(fuse)
                        fuse_eff = fuse
        with self._state_lock:
            if self._running:  # re-check under the lock (TOCTOU)
                raise EngineBusy("engine already running a board")
            self._cells = cells
            self._repr = repr_
            self._packed = repr_ == "packed"
            self._pad_rows = pad_rows
            self._turn = start_turn
            # Turn-0 publication, computed host-side above: the ticker
            # has an exact pair before the first chunk ever pops.
            self._publish_locked(alive0, start_turn, reset_floor=True)
            self._running = True
            self._run_token = token
            self._abort.clear()

        target = start_turn + params.turns
        self._max_chunk = env_int(MAX_CHUNK_ENV, MAX_CHUNK)
        # `or`: a zero/unset target would make both adapters halve
        # forever (chunk pinned at 1 ≈ one round trip per turn) — 0 is
        # not a meaningful band, so it falls back to the default.
        self._chunk_target = (
            env_float(CHUNK_TARGET_ENV, CHUNK_TARGET_SECONDS)
            or CHUNK_TARGET_SECONDS)
        # Start where the last run of this same (board geometry, repr,
        # shard count) converged instead of re-ramping from 1: resubmits
        # and reattaches skip ~7 synchronous round trips. The hint is
        # only a starting point — if the regime changed (env caps, a
        # slower link) the adapters re-correct within a few chunks.
        hint_key = (cells.shape, repr_, tuple(mesh.devices.shape),
                    self._chunk_target, fuse_eff)
        # Recompile-churn signal: a new (repr, shape, dtype, mesh, rule,
        # fuse) tuple means jit will trace + compile a fresh step
        # executable (the fuse depth is baked into the compiled macro).
        obs_devstats.note_signature(
            (repr_, tuple(cells.shape), str(cells.dtype),
             tuple(mesh.devices.shape), self._rule.rulestring, fuse_eff))
        # Floor to a power of two <= the cap: min() alone would hand a
        # non-power-of-two GOL_MAX_CHUNK straight to the dispatch loop,
        # breaking the bounded-compiled-program invariant (_next_chunk).
        chunk = 1
        hinted = min(self._chunk_hints.get(hint_key, 1), self._max_chunk)
        while chunk * 2 <= hinted:
            chunk *= 2
        quit_run = False
        # Chunk-timeline run report (GOL_RUN_REPORT / --run-report) and
        # the engine metric gauges: both update only at chunk and flag
        # boundaries on the host thread, never inside compiled code —
        # that's the whole overhead story (docs/OBSERVABILITY.md).
        reporter = obs_timeline.from_env()
        run_t0 = time.monotonic()
        board_cells = height * width
        # Mesh geometry stamp: the gol_mesh_* gauges, /healthz `mesh`,
        # checkpoint manifests, and Stats all read this one dict.
        mesh_geom = mesh_geometry(mesh)
        self._mesh_geom = mesh_geom
        obs_devstats.note_mesh(mesh_geom)
        # Fusion stamp: gol_fuse_k gauge now, checkpoint manifests later
        # (the writer reads devstats.fuse_field(), same pattern as mesh).
        obs_devstats.note_fuse(fuse_eff)
        if reporter is not None:
            reporter.emit(
                "run_start", w=width, h=height,
                model=self._rule.rulestring, repr=repr_,
                devices=int(mesh.size), shards=mesh_geom["shards"],
                mesh_shape=mesh_geom["shape"], mesh_axes=mesh_geom["axes"],
                turns_requested=params.turns,
                start_turn=start_turn, fuse_k=fuse_eff)
        obs.ENGINE_CHUNK_SIZE.set(chunk)
        # GOL_PROFILE_DIR: one-shot env contract (set by --profile-dir)
        # — arm an on-demand profiler capture of this run's first
        # GOL_PROFILE_TURNS turns. A Profile RPC / POST /profile can arm
        # later ones; the loop below is the sole consumer either way.
        obs_prof.arm_from_env()
        # Device memory baseline for this run; also refreshes the
        # healthz cache (graceful no-op on stat-less backends).
        obs_devstats.poll_device_memory()
        last_devpoll = time.monotonic()
        trace_dir = os.environ.get(TRACE_ENV, "")
        ckpt_dir = os.environ.get(CKPT_ENV, "")
        ckpt_every = env_float(CKPT_EVERY_ENV, CKPT_EVERY_DEFAULT)
        ckpt_path = ""
        if ckpt_dir:
            os.makedirs(ckpt_dir, exist_ok=True)
            ckpt_path = os.path.join(ckpt_dir, f"{width}x{height}.npz")
        last_ckpt = time.monotonic()
        # Manifest checkpointing (gol_tpu/ckpt): TURN-based cadence, so
        # checkpoint turns are deterministic across runs — the legacy
        # seconds-based autosave above keeps working independently.
        ckpt_writer = None
        next_ckpt_turn = None
        ckpt_every_turns = 0
        if ckpt_dir:
            from gol_tpu import ckpt as ckpt_mod

            ckpt_every_turns = env_int(
                ckpt_mod.CKPT_EVERY_TURNS_ENV, 0, minimum=0)
            if ckpt_every_turns > 0:
                ckpt_writer = ckpt_mod.CheckpointWriter(
                    ckpt_dir, run_id=obs_flight.RUN_ID,
                    keep_last=env_int(ckpt_mod.CKPT_KEEP_ENV,
                                      ckpt_mod.CKPT_KEEP_DEFAULT),
                    keep_every=env_int(ckpt_mod.CKPT_KEEP_EVERY_ENV, 0,
                                       minimum=0))
                next_ckpt_turn = (
                    start_turn // ckpt_every_turns + 1) * ckpt_every_turns
        # Event-sourced journal (GOL_JOURNAL): the run's hash-chained
        # black box. The create event pins the seed — inline for small
        # packed/u8 boards, digest-only otherwise — and digest events
        # land at exact turn boundaries below so a replay can assert
        # bit-identity mid-history, not just at the end.
        journal_writer = None
        next_digest_turn = None
        digest_every_turns = 0
        if journal_mod.enabled():
            journal_writer = journal_mod.for_run(obs_flight.RUN_ID)
        if journal_writer is not None:
            digest_every_turns = journal_mod.digest_every()
            if digest_every_turns > 0:
                next_digest_turn = (
                    start_turn // digest_every_turns + 1
                ) * digest_every_turns
            try:
                _jhost = np.asarray(jax.device_get(cells))
                if pad_rows:
                    _jhost = _jhost[..., : _jhost.shape[-2] - pad_rows, :]
                _jseed = None
                if height * width <= (1 << 22):
                    if repr_ == "u8":
                        _jseed = journal_mod.encode_board(_jhost)
                    elif repr_ == "packed":
                        from gol_tpu.ops.bitpack import (
                            unpack_np, words_bytes_np)
                        _jseed = journal_mod.encode_board(unpack_np(
                            words_bytes_np(_jhost), height, width))
                _jfields = dict(
                    turn=start_turn, h=height, w=width,
                    rule=self._rule.rulestring, repr=repr_,
                    fuse_k=fuse_eff,
                    board_sha256=journal_mod.board_digest(_jhost, repr_))
                if _jseed is not None:
                    _jfields["seed"] = _jseed
                journal_writer.append("create", **_jfields)
                del _jhost, _jseed
            except Exception:  # journaling must never sink a run
                journal_writer = None
                next_digest_turn = None

        def _ckpt_submit(snap_cells, trigger: str) -> None:
            """Queue a checkpoint of `snap_cells` at self._turn on the
            background writer: a pointer hand-off plus an async
            device→host copy kick — the turn loop never waits on disk
            or transfer."""
            from gol_tpu import ckpt as ckpt_mod

            snap = ckpt_mod.Snapshot(
                snap_cells, repr_, pad_rows, self._turn, (height, width),
                self._rule.rulestring, trigger=trigger,
                mesh={"devices": len(self._devices)})
            try:
                snap_cells.copy_to_host_async()
            except Exception:
                pass  # not fatal: the writer's device_get still works
            ckpt_writer.submit(snap)
        chunks_done = 0
        traced_chunks = 0
        # Flag-service seconds accrued since the last chunk record — the
        # record attributes control-plane stall to the chunk it delayed.
        flag_pending = 0.0
        # Hot-loop metric batching (r6): the registry's counters and
        # histograms take a lock per update, which at µs chunk walls is
        # real per-chunk overhead multiplied by millions of chunks. The
        # loop accumulates in plain locals and flushes on a coarse
        # interval + at run end, so scrapes lag by at most
        # METRICS_FLUSH_SECONDS while the hot path pays int adds and a
        # list append. The published (alive, turn) pair still moves
        # every pop — coherence of the poll path is not batched.
        pend_chunks = 0
        pend_turns = 0
        pend_elapsed: list = []
        pend_flags: list = []
        # Per-chunk halo accounting for sharded runs: the dispatch
        # wrappers in parallel/halo.py skip tracers (under the jitted
        # token wrapper they execute once per compilation), so the loop
        # buffers (turns, wall) per popped chunk here and drains them at
        # flush cadence through the analytic traffic model. The
        # wrap-extension path is excluded — its seam collectives are
        # GSPMD-inserted, not modelled by halo_traffic.
        pend_halo: list = []
        halo_traffic_fn = None
        if pad_rows == 0 and int(mesh.size) > 1:
            from gol_tpu.parallel.halo import halo_traffic

            halo_traffic_fn = functools.partial(
                halo_traffic, repr_, tuple(cells.shape), mesh,
                fuse=fuse_eff)
        last_cups = 0.0
        last_rate = 0.0
        last_done_turn = start_turn
        # Host-overhead accounting: per-iteration wall time minus the
        # device token wait and excluded stalls (compile, pause, sync
        # checkpoint) — the chunk_overhead_us gate metric.
        host_overhead = 0.0
        overhead_iters = 0
        wait_accum = 0.0
        last_flush = time.monotonic()
        METRICS_FLUSH_SECONDS = 0.5
        # Per-chunk spans only when someone consumes them (span export
        # or flight dump configured): an unconsumed span is two dict
        # builds, two lock acquisitions, and a ring append per chunk.
        hot_spans = obs_trace.hot_spans_enabled()

        def _flush_metrics(now: float) -> None:
            """Drain the batched hot-loop telemetry into the registry."""
            nonlocal pend_chunks, pend_turns, last_flush
            if pend_chunks:
                if fuse_eff > 1:
                    obs.FUSED_DISPATCHES.labels(tier="engine").inc(
                        pend_chunks)
                obs.ENGINE_CHUNKS_TOTAL.inc(pend_chunks)
                obs.ENGINE_TURNS_TOTAL.inc(pend_turns)
                obs.ENGINE_CHUNK_SECONDS.observe_batch(pend_elapsed)
                pend_elapsed.clear()
                pend_chunks = pend_turns = 0
            if pend_flags:
                obs.ENGINE_FLAG_SERVICE_SECONDS.observe_batch(pend_flags)
                pend_flags.clear()
            if pend_halo:
                obs_halostats.flush_chunk_walls(pend_halo, halo_traffic_fn)
                pend_halo.clear()
            obs.ENGINE_TURN.set(last_done_turn)
            obs.ENGINE_CHUNK_SIZE.set(chunk)
            if last_cups > 0:
                obs.ENGINE_CUPS.set(last_cups)
            if last_rate > 0:
                obs.ENGINE_TURNS_PER_S.set(last_rate)
            if overhead_iters:
                self._chunk_overhead_us = (
                    host_overhead / overhead_iters * 1e6)
                obs.ENGINE_CHUNK_OVERHEAD_US.set(self._chunk_overhead_us)
            last_flush = now
        # Per-run pipeline depth: clamp so depth + 1 board generations fit
        # the board byte budget (a 2 GB flagship board still pipelines at
        # 3; a board near device-memory capacity degrades to
        # sync-per-chunk rather than OOM). The budget comes from the
        # device's reported memory limit when available (half of it —
        # kernel temporaries and haloed windows need the rest), else a
        # conservative default; GOL_PIPELINE_BUDGET (bytes) overrides.
        budget = env_int(PIPELINE_BUDGET_ENV, 0, minimum=0)
        if budget <= 0:
            from gol_tpu.utils.devicemem import half_device_memory

            budget = half_device_memory(
                PIPELINE_BOARD_BUDGET, self._devices[0])
        # The budget is per device, so compare against this device's SHARD
        # of the board, not the global array size.
        shard_bytes = int(cells.nbytes) // max(mesh.size, 1)
        depth = max(1, min(
            env_int(PIPELINE_DEPTH_ENV, PIPELINE_DEPTH),
            8,  # the pace window must always out-span a drain cluster
            budget // max(shard_bytes, 1) - 1,
        ))
        inflight: deque = deque()
        last_pop = time.monotonic()
        # The pipeline stays at depth 1 (sync per chunk — the adapter
        # sees every measurement immediately) while the chunk is still
        # ramping; once the adapter stops growing it, the pipeline opens
        # to full depth. Pipelining the ramp instead would lag each
        # doubling by a full pipeline of stale pops, tripling the number
        # of round-trip-priced ramp chunks.
        ramping = True
        # Size the pace window so a drain cluster (up to `depth` queued
        # completions popped microseconds apart) can never fill it — the
        # rate must always span at least a few real completion intervals.
        self._pace_window = deque(maxlen=depth + 5)
        self._pace_skip = 0

        def _reset_pace(at: float) -> None:
            """Exclude a host-side stall (compile, checkpoint, pause,
            trace) from pace measurements: wall time spent there is not
            chunk compute, for either adapter regime. Chunks that
            completed during the stall drain as a burst right after it,
            so the next `depth` pops are suspect and skipped by the
            windowed adapter."""
            nonlocal last_pop
            last_pop = at
            self._pace_window.clear()
            self._pace_skip = depth

        tokened = _tokened_run(run, mesh, self._rule, repr_, pad_rows)

        def _pop_oldest() -> None:
            """Block until the oldest in-flight chunk is real (one small
            token transfer — see `_tokened_run`); publish its exact
            (alive, turn) pair and feed its completion to the
            regime-appropriate chunk adapter (floor-based for
            synchronous measurements — the ramp and depth-1 mode —
            windowed-rate once the pipeline is open)."""
            nonlocal chunk, last_pop, ramping, flag_pending, last_devpoll
            nonlocal pend_chunks, pend_turns, wait_accum
            nonlocal last_cups, last_rate, last_done_turn
            (done_cells, done_token, done_k, done_turn,
             done_issue, done_span) = inflight.popleft()
            t_wait = time.monotonic()
            done_alive = int(np.asarray(
                jax.device_get(done_token), dtype=np.int64).sum())
            now = time.monotonic()
            token_wait = now - t_wait
            wait_accum += token_wait
            elapsed = now - last_pop
            last_pop = now
            if ramping or depth == 1:
                # Synchronous measurements: elapsed = fixed cost + compute
                # for exactly this chunk, which is what the floor-based
                # adapter expects (the windowed est would re-include the
                # irreducible dispatch floor and shrink toward chunk=1 on
                # a slow link).
                new_chunk = self._adapt_chunk(chunk, done_k, elapsed)
                if ramping and done_k == chunk and new_chunk == chunk:
                    # Adapter satisfied (in band, or capped): open the
                    # pipeline. The ramp rule has no hysteresis, so an
                    # unchanged return is a genuine steady point.
                    ramping = False
                chunk = new_chunk
                rate = done_k / elapsed if elapsed > 0 else 0.0
            else:
                chunk = self._adapt_chunk_windowed(chunk, now, done_k)
                # Single pop-to-pop intervals collapse to ~0 when queued
                # completions drain together (e.g. right after a
                # checkpoint barrier) — report the windowed rate instead.
                rate = self._pace_rate() or 0.0
            with self._state_lock:
                self._last_chunk = done_k
                if rate > 0:
                    self._turns_per_s = rate
                self._publish_locked(done_alive, done_turn)
            cups = (done_k * board_cells / elapsed) if elapsed > 0 else 0.0
            # Batched telemetry: locals here, registry at the next flush.
            last_done_turn = done_turn
            pend_chunks += 1
            pend_turns += done_k
            pend_elapsed.append(elapsed)
            if halo_traffic_fn is not None:
                pend_halo.append((done_k, elapsed))
            if cups > 0:
                last_cups = cups
            if rate > 0:
                last_rate = rate
            if reporter is not None:
                reporter.emit(
                    "chunk", turn=done_turn, turns=done_k,
                    chunk_size=chunk, wall_s=round(elapsed, 6),
                    cups=cups, turns_per_s=rate,
                    token_wait_s=round(token_wait, 6),
                    dispatch_s=round(done_issue, 6),
                    flag_s=round(flag_pending, 6), alive=done_alive)
            flag_pending = 0.0
            # The chunk span opened at issue closes here: it covers
            # dispatch + device compute + token wait, i.e. the chunk's
            # life in the pipeline, not just the host-side blocking.
            # None when hot spans are gated off (no consumer configured).
            if done_span is not None:
                done_span.attrs.update(alive=done_alive,
                                       token_wait_s=round(token_wait, 6))
                obs_trace.finish(done_span)
            if (journal_writer is not None and digest_every_turns > 0
                    and done_turn > start_turn
                    and done_turn % digest_every_turns == 0):
                # Journal digest at an exact cadence boundary (every
                # such boundary is a chunk boundary — the issue-side
                # k_cap lands chunks on digest turns). This chunk is
                # already complete, so the device_get is a small copy,
                # not a pipeline drain, and the sha256 + append overlap
                # with the chunks still computing on the device.
                try:
                    _dhost = np.asarray(jax.device_get(done_cells))
                    if pad_rows:
                        _dhost = _dhost[
                            ..., : _dhost.shape[-2] - pad_rows, :]
                    journal_writer.digest(
                        done_turn,
                        journal_mod.board_digest(_dhost, repr_),
                        repr_=repr_)
                except Exception:
                    # A failed digest must never sink the run; the
                    # journal sink latches itself dead on OSError.
                    pass
            if now - last_devpoll >= 2.0:
                # Throttled gol_dev_* refresh: memory_stats() is a cheap
                # local counter read, but once per chunk at µs chunk
                # walls would still be noise.
                obs_devstats.poll_device_memory()
                last_devpoll = now
            if now - last_flush >= METRICS_FLUSH_SECONDS:
                _flush_metrics(now)

        # The run span: parents every chunk/flag span below, and itself
        # parents under whatever is on this thread's context stack — the
        # server's ServerDistributor handler span for remote runs, the
        # controller's run span for in-process ones.
        run_span = obs_trace.start(
            "engine.run", attrs={"w": width, "h": height,
                                 "turns": params.turns,
                                 "start_turn": start_turn, "repr": repr_})
        obs_trace.TRACER.push(run_span)
        try:
            while self._turn < target and not quit_run:
                if self._killed or self._abort.is_set():
                    break
                # Per-iteration host-overhead accounting: everything this
                # iteration does on the host EXCEPT blocking on the
                # device token (wait_accum, credited inside _pop_oldest)
                # and excluded stalls (compile, pause, sync checkpoint)
                # is per-chunk overhead — the quantity the overhead
                # bench legs gate.
                t_iter = time.monotonic()
                wait_accum = 0.0
                stall_excl = 0.0
                count_overhead = False
                preq = obs_prof.PROFILER.take()
                if preq is not None:
                    # On-demand capture (Profile RPC / POST /profile /
                    # GOL_PROFILE_DIR): drain the pipeline so the trace
                    # shows only the requested turns, run them
                    # synchronously under jax.profiler, and account them
                    # as traced chunks — profiler-skewed timing stays
                    # out of the pace/CUPS aggregates, exactly like the
                    # GOL_TRACE path below.
                    while inflight:
                        _pop_oldest()
                    profile_to = min(self._turn + preq.turns, target)
                    with obs_prof.PROFILER.capture(preq):
                        with obs_trace.span(
                                "engine.profile",
                                attrs={"turns": profile_to - self._turn,
                                       "source": preq.source}):
                            while self._turn < profile_to:
                                k = _next_chunk(chunk,
                                                profile_to - self._turn)
                                cells = run(cells, k, mesh, self._rule)
                                wait(cells)
                                chunks_done += 1
                                traced_chunks += 1
                                obs.ENGINE_TRACED_CHUNKS_TOTAL.inc()
                                obs.ENGINE_TURNS_TOTAL.inc(k)
                                with self._state_lock:
                                    self._cells = cells
                                    self._turn += k
                                obs.ENGINE_TURN.set(self._turn)
                                if reporter is not None:
                                    reporter.emit("traced_chunk",
                                                  turn=self._turn,
                                                  turns=k)
                    _reset_pace(time.monotonic())
                    continue
                k_cap = target - self._turn
                if next_ckpt_turn is not None:
                    # Land chunk boundaries exactly on checkpoint turns:
                    # checkpoint turns become a pure function of
                    # (start_turn, cadence), never of the adapter's
                    # timing-dependent chunk sizes — which is what makes
                    # an interrupted+resumed run's checkpoints comparable
                    # turn-for-turn against an uninterrupted one.
                    k_cap = min(k_cap, next_ckpt_turn - self._turn)
                if next_digest_turn is not None:
                    # Same exactness contract as checkpoint turns: a
                    # digest event's turn is a pure function of
                    # (start_turn, cadence), so the replay auditor can
                    # advance exactly that many turns and compare.
                    k_cap = min(k_cap, next_digest_turn - self._turn)
                k = _next_chunk(chunk, k_cap)
                # Trace the second chunk (first is compile-warmup), or the
                # first when it is the whole run; the traced result is kept
                # but its timing is not fed to the chunk adapter (profiler
                # overhead would skew it).
                trace_now = bool(trace_dir) and (
                    chunks_done == 1
                    or (chunks_done == 0 and k == target - self._turn)
                )
                if trace_now:
                    while inflight:  # a clean profile: nothing else queued
                        _pop_oldest()
                    with obs_trace.span("engine.traced_chunk",
                                        attrs={"k": k}):
                        with jax.profiler.trace(trace_dir):
                            cells = run(cells, k, mesh, self._rule)
                            wait(cells)
                    trace_dir = ""
                    traced_chunks += 1
                    obs.ENGINE_TRACED_CHUNKS_TOTAL.inc()
                    obs.ENGINE_TURNS_TOTAL.inc(k)
                    if reporter is not None:
                        # Profiler path: deliberately no wall_s/cups —
                        # a traced chunk's timing is profiler-skewed and
                        # stays out of the pace/CUPS aggregates, exactly
                        # as it stays out of the chunk adapter.
                        reporter.emit("traced_chunk",
                                      turn=self._turn + k, turns=k)
                    _reset_pace(time.monotonic())
                else:
                    count_overhead = True
                    t_issue = time.monotonic()
                    # Opened at issue, finished by _pop_oldest — the
                    # span rides the pipeline with its chunk (6th tuple
                    # element) so a flight dump mid-run shows exactly
                    # which turns were in flight on the device. Gated:
                    # with no span consumer configured the per-chunk
                    # span is pure hot-loop overhead, so it is not
                    # created at all (engine.run always is).
                    chunk_span = (obs_trace.start(
                        "engine.chunk",
                        attrs={"k": k, "turn": self._turn + k})
                        if hot_spans else None)
                    cells, token = tokened(cells, k)
                    issue_cost = time.monotonic() - t_issue
                    if issue_cost > 0.05:
                        # First dispatch of a new chunk size compiles
                        # synchronously (~1 s even from the persistent
                        # cache); shifting last_pop keeps the in-flight
                        # chunk's own RTT+compute measurable while
                        # excluding the compile stall.
                        _reset_pace(last_pop + issue_cost)
                        stall_excl += issue_cost
                    # Start the token's device->host copy NOW: the pop's
                    # device_get then reads a transfer that completed in
                    # the background instead of paying a serialized
                    # tunnel fetch round trip per chunk. r5 interleaved
                    # A/B (512², 30M-turn reps): async 5.59-5.60M
                    # turns/s rock-steady vs 3.8-5.2M drifty without —
                    # the serialized fetch was both the residual
                    # engine-vs-kernel gap AND its window-to-window
                    # variance.
                    token.copy_to_host_async()
                    inflight.append(
                        (cells, token, k, self._turn + k, issue_cost,
                         chunk_span))
                    while len(inflight) >= (1 if ramping else depth):
                        _pop_oldest()
                chunks_done += 1
                with self._state_lock:
                    self._cells = cells
                    self._turn += k
                cb = self._bcast_notify
                if cb is not None:
                    cb()
                if (next_ckpt_turn is not None
                        and self._turn >= next_ckpt_turn):
                    _ckpt_submit(cells, "periodic")
                    next_ckpt_turn = (
                        self._turn // ckpt_every_turns + 1
                    ) * ckpt_every_turns
                if (next_digest_turn is not None
                        and self._turn >= next_digest_turn):
                    # Only the cadence pointer advances here — it must,
                    # or the next issue's k_cap above would collapse to
                    # zero. The digest itself happens at POP time
                    # (`_pop_oldest`), on the already-completed chunk's
                    # cells: digesting the frontier would device_get an
                    # in-flight array and drain the whole pipeline per
                    # digest (measured ~7% wall at a 256-turn cadence
                    # on a fast 512² host vs ~0% overlapped).
                    next_digest_turn = (
                        self._turn // digest_every_turns + 1
                    ) * digest_every_turns
                if ckpt_path and \
                        time.monotonic() - last_ckpt >= ckpt_every:
                    t_sync = time.monotonic()
                    self.save_checkpoint(ckpt_path)
                    last_ckpt = time.monotonic()
                    _reset_pace(last_ckpt)
                    # A synchronous legacy save is a stall, not
                    # per-chunk overhead (the manifest writer above is
                    # async and DOES count).
                    stall_excl += last_ckpt - t_sync
                if self._turn < target:
                    # Only honour flags while turns remain — a pause landing
                    # with the final chunk must not park a finished run.
                    # Fast path: reading the queue's underlying deque is
                    # atomic, and an empty queue with no kill/abort set
                    # means _handle_flags would do nothing — skip the
                    # timer pair, the span, and the get_nowait/Empty
                    # exception per chunk. A concurrent cf_put lands on
                    # the next boundary, the same worst-case latency the
                    # chunk wall already imposes.
                    if (self._flags.queue or self._killed
                            or self._abort.is_set()):
                        t_flags = time.monotonic()
                        if hot_spans:
                            with obs_trace.span("engine.flags"):
                                quit_run = self._handle_flags()
                        else:
                            quit_run = self._handle_flags()
                        flag_cost = time.monotonic() - t_flags
                        pend_flags.append(flag_cost)
                        flag_pending += flag_cost
                        if flag_cost > 0.01:
                            # A pause (or slow flag drain) stalled the
                            # host.
                            _reset_pace(time.monotonic())
                            stall_excl += flag_cost
                if count_overhead:
                    host_overhead += max(
                        0.0,
                        time.monotonic() - t_iter - wait_accum
                        - stall_excl)
                    overhead_iters += 1
            if ckpt_writer is not None and chunks_done > 0:
                # Every loop exit inside the try — completion, quit,
                # kill, abort — leaves durable state at the final turn,
                # so a restart resumes exactly where this run stopped.
                _ckpt_submit(cells, "final")
        except Exception as e:
            # The black box: an unhandled chunk-loop error dumps the
            # flight ring — recent spans/events plus the chunk spans
            # still riding the pipeline — before the error propagates
            # to the dispatcher.
            obs_flight.crash("engine.run_loop", e, turn=self._turn)
            if ckpt_writer is not None:
                # Emergency best-effort checkpoint alongside the flight
                # dump: synchronous (there is no later boundary to wait
                # for) and never allowed to mask the original error.
                try:
                    ckpt_writer.write_sync(
                        self._ckpt_snapshot("emergency"))
                except Exception:
                    pass
            raise
        finally:
            # Drain remaining in-flight chunks so the LAST publication is
            # the final state's exact (alive, turn) — the chunks are
            # already dispatched, so these pops cost no more than the
            # blocking `_materialize` below would anyway.
            try:
                while inflight:
                    _pop_oldest()
            except Exception:
                # Device error: return what we have. Close the orphaned
                # chunk spans so they don't read as in-flight forever.
                for _item in inflight:
                    if _item[5] is not None:
                        obs_trace.finish(_item[5])
                inflight.clear()
            # The traced chunk (and a turns=0 run) bypass the token, so
            # the drained publication can trail the final turn by one
            # chunk: reconcile with one dispatch, on the run thread, once
            # per run — the POLL path stays dispatch-free.
            if (self._alive_pub is None
                    or self._alive_pub[1] != self._turn):
                try:
                    alive = self._alive_dispatch(
                        self._cells, self._repr, self._pad_rows)
                    with self._state_lock:
                        self._publish_locked(alive, self._turn)
                except Exception:
                    pass
            with self._state_lock:
                # Capture THIS run's final state in the same critical
                # section that releases the engine: once _running drops, a
                # spin-retrying submitter (the partition-recovery flow)
                # can install a new board, and a later _snapshot() would
                # hand the first caller the second run's state.
                final_cells, final_repr = self._cells, self._repr
                final_pad = self._pad_rows
                final_turn = self._turn
                self._chunk_hints[hint_key] = chunk
                self._running = False
                self._run_token = None
                self._abort.clear()
            if ckpt_writer is not None:
                # Bounded drain: the run is over, so blocking here costs
                # nothing pipelined — but a wedged disk must not park
                # the engine forever (the daemon thread finishes or
                # dies with the process).
                ckpt_writer.close(timeout=60.0)
            if journal_writer is not None:
                # After the ckpt drain, so the final checkpoint's digest
                # event precedes the end bookend in the chain.
                try:
                    journal_writer.append("end", turn=final_turn)
                except Exception:
                    pass
            # Run-end flush: the batched counters/histograms land before
            # anyone can observe the run as finished, so post-run totals
            # are exact (test_obs counts on this).
            _flush_metrics(time.monotonic())
            obs.ENGINE_TURN.set(final_turn)
            if reporter is not None:
                # Final gol_dev_* poll so the run report carries the
                # run's device footprint (None fields on stat-less
                # backends — the schema grows by addition).
                devmem = obs_devstats.poll_device_memory()
                reporter.emit(
                    "run_end", turn=final_turn,
                    turns_total=final_turn - start_turn,
                    chunks=chunks_done - traced_chunks,
                    traced_chunks=traced_chunks,
                    wall_s=round(time.monotonic() - run_t0, 6),
                    chunk_overhead_us=round(self._chunk_overhead_us, 2),
                    device_kind=devmem["device_kind"],
                    dev_live_bytes=devmem["live_bytes"],
                    dev_peak_bytes=devmem["peak_bytes"])
                reporter.close()
            run_span.attrs["final_turn"] = final_turn
            obs_trace.TRACER.pop(run_span)
            obs_trace.finish(run_span)
        # On kill_prog mid-run, still hand back the partial board — the
        # state exists and discarding completed turns helps nobody; further
        # RPCs on this engine raise EngineKilled.
        return (self._materialize(final_cells, final_repr, final_pad),
                final_turn)

    def alive_count(self) -> Tuple[int, int]:
        """(alive, completed turn), coherent pair (ref `Server:69-75`).
        For Generations boards "alive" is the FIRING population (state
        1) — the multi-state analog of the reference's 255-cell count.

        Dispatch-free (r5): returns the pair published at the last chunk
        boundary (`_tokened_run` folds the count into the completion
        token), so a telemetry poll never dispatches a device program
        and never blocks behind the pipeline — worst case it reports a
        boundary up to one chunk old, and every reported pair is exact
        for its turn (the reference's own ticker contract,
        `Server/gol/distributor.go:69-75`)."""
        self._check_alive()
        with self._state_lock:
            pub = self._alive_pub
            cells, turn, repr_ = self._cells, self._turn, self._repr
            pad = self._pad_rows
            running = self._running
        # Invariant: whenever the engine is PARKED, the publication turn
        # equals the completed turn (submit, every pop, the finalize
        # drain+reconcile, and load_checkpoint all maintain it); while a
        # run is in flight the publication legitimately trails `_turn`
        # by the in-flight chunks. A parked-state mismatch therefore
        # means state was installed around the publication (direct
        # injection by an embedder) — fall through and count it.
        if pub is not None and (running or pub[1] == turn):
            return pub
        if cells is None:
            return 0, turn
        return self._alive_dispatch(cells, repr_, pad), turn

    @staticmethod
    def _alive_dispatch(cells, repr_: str, pad: int) -> int:
        """Count the firing population WITH device work — the reconcile
        and fallback path only, never the poll path."""
        if cells is None:
            raise RuntimeError("no board loaded")
        if pad:
            rows = _padded_row_counts(repr_, pad)(cells)
            return int(np.asarray(jax.device_get(rows),
                                  dtype=np.int64).sum())
        if repr_ == "packed":
            return packed_alive_count(cells)
        if repr_ == "u8":
            return alive_count_exact(cells)
        if repr_ == "f32":
            rows = _padded_row_counts("f32", 0)(cells)
            return int(np.asarray(jax.device_get(rows),
                                  dtype=np.int64).sum())
        if repr_ == "gen8":
            from gol_tpu.models.generations import state_alive_count

            return state_alive_count(cells)
        return packed_alive_count(cells[0])  # gen3: plane 0 is alive

    def get_world(self) -> Tuple[np.ndarray, int]:
        """({0,255} board snapshot, completed turn) (ref `Server:62-67`)."""
        self._check_alive()
        return self._snapshot()

    # Dense views/snapshots are board-anchored: two frames of the same
    # shape from the same run are always comparable, so the wire layer
    # may delta-encode them (contrast SparseEngine, whose frames are
    # window-anchored and drift with the pattern). Float boards (Lenia)
    # are the exception: their authoritative state is float32 and the
    # u8 views are lossy quantizations, so xrle deltas against them
    # would silently compound quantization error — not diffable.
    @property
    def frames_diffable(self) -> bool:
        return self._repr != "f32"

    @property
    def binary_pixels(self) -> bool:
        """True iff snapshots materialize as strict {0,255} pixels — the
        precondition for the wire's bit-packed codec. Generations and
        continuous (Lenia) boards carry gray levels and must never be
        packed."""
        return not isinstance(self._rule, (GenerationsRule, LeniaRule))

    def get_world_frame(self, caps) -> Tuple["object", int]:
        """(wire.Frame, completed turn): the codec-framed snapshot path
        (PR 5). The packed representation ships its device-resident
        words directly — no device-side unpack, 8× fewer wire bytes —
        and large boards stream as row bands whose device→host copy
        overlaps the caller's socket send (`_banded_device_rows`).
        Generations reprs fall back to pixel materialization (their
        gray levels aren't packable); all paths honour the negotiated
        `caps` and degrade to raw u8 for caps-less peers."""
        from gol_tpu import wire

        self._check_alive()
        with self._state_lock:
            cells, turn, repr_ = self._cells, self._turn, self._repr
            pad = self._pad_rows
        if cells is None:
            raise RuntimeError("no board loaded")
        caps = frozenset(caps)
        h = cells.shape[-2] - pad
        w = _board_width(cells, repr_)
        if repr_ == "packed":
            body = cells[:h] if pad else cells
            bands = _banded_device_rows(body, h, cells.shape[-1] * 4)
            return wire.packed_words_frame(h, w, bands, caps), turn
        if repr_ == "u8":
            body = cells[:h] if pad else cells
            bands = _banded_device_rows(body, h, w)
            # Bands come off the device as {0,1} cells; the frame
            # builder packs them as-is or scales to pixels per band —
            # either way the full-board to_pixels dispatch is gone.
            return wire.u8_band_frame(h, w, bands, caps, binary=True,
                                      values01=True), turn
        if repr_ == "f32":
            # Lossless float frame when the peer negotiated it; the
            # quantized u8 pixel view (the universal codec) otherwise.
            if wire.CAP_F32 in caps:
                state = self._float_state(cells, pad)
                return wire.encode_board_f32(state, caps), turn
        px = self._materialize(cells, repr_, pad)
        return wire.encode_board(px, caps, binary=False), turn

    def get_view(
        self, max_cells: int
    ) -> Tuple[np.ndarray, int, Tuple[int, int]]:
        """(pixel view, completed turn, (fy, fx) downsample factors):
        the full board when it fits `max_cells`, else an on-device
        block-brightest reduction whose transfer is O(max_cells) — the
        scalable live-view feed (r5, VERDICT r4 #3: a 65536² frame
        through `get_world` would move 4.3 GB per poll). View pixel
        (vy, vx) covers board rows [vy*fy, (vy+1)*fy) x columns
        [vx*fx, (vx+1)*fx) and is lit iff any cell there is."""
        self._check_alive()
        with self._state_lock:
            cells, turn, repr_ = self._cells, self._turn, self._repr
            pad = self._pad_rows
        if cells is None:
            raise RuntimeError("no board loaded")
        h = cells.shape[-2] - pad
        w = _board_width(cells, repr_)
        if max_cells <= 0 or h * w <= max_cells:
            return self._materialize(cells, repr_, pad), turn, (1, 1)
        f = view_factor(h, w, max_cells)
        view = np.asarray(jax.device_get(
            _view_program(repr_, pad, f, self._rule)(cells)))
        return view, turn, (f, f)

    def stats(self) -> dict:
        """Engine telemetry snapshot for operators (no device work):
        completed turn, run state, board geometry, current compiled chunk
        size, measured turns/s of the last full chunk, rule, devices.
        Beyond-reference observability (SURVEY §5: the Go system's only
        metric is the alive-count poll)."""
        from gol_tpu.ops.bitpack import WORD_BITS

        self._check_alive()
        with self._state_lock:
            cells = self._cells
            shape = None
            if cells is not None:
                h, w = cells.shape[-2] - self._pad_rows, cells.shape[-1]
                if self._repr in ("packed", "gen3"):
                    w *= WORD_BITS  # last axis is 32-cell words
                shape = [h, w]
            return {
                "turn": self._turn,
                "running": self._running,
                "board": shape,
                # Last published firing count (exact at "alive_turn",
                # which can trail "turn" by the in-flight chunks) —
                # free operator telemetry from the r5 publication.
                "alive": (self._alive_pub[0]
                          if self._alive_pub is not None else None),
                "alive_turn": (self._alive_pub[1]
                               if self._alive_pub is not None else None),
                "packed": self._packed,
                "chunk": self._last_chunk,
                "turns_per_s": round(self._turns_per_s, 1),
                # Mean host µs per chunk outside the device wait — the
                # small-board scaling cost (bench --overhead gates it).
                "chunk_overhead_us": round(self._chunk_overhead_us, 2),
                "rule": self._rule.rulestring,
                "devices": len(self._devices),
                # Geometry of the last submitted run's mesh (None before
                # the first run): axis sizes, shard count, grid shape.
                "mesh": self._mesh_geom,
            }

    # -------------------------------------------------------- checkpointing

    # Checkpoints at or below this payload size are zlib-compressed;
    # larger ones are written raw — compressing a 512 MB packed flagship
    # board would dominate the checkpoint interval for little gain.
    CKPT_COMPRESS_LIMIT = 64 * 1024 * 1024

    def geometry(self) -> dict:
        """Placement geometry for the reshard-at-restore contract
        (ckpt/reshard.py): a manifest recording a different mesh or
        representation family is refused at restore unless a reshard
        is requested."""
        with self._state_lock:
            cells, repr_, pad = self._cells, self._repr, self._pad_rows
        geo = {"kind": "dense", "devices": len(self._devices)}
        if cells is not None:
            geo["h"] = int(cells.shape[-2] - pad)
            geo["w"] = int(_board_width(cells, repr_))
            geo["repr"] = repr_
            # Logical CELL dtype, not storage dtype (packed boards hold
            # uint32 words of uint8 cells): the reshard-at-restore delta
            # refuses a float checkpoint on a binary engine and vice
            # versa without an explicit reshard.
            geo["dtype"] = "float32" if repr_ == "f32" else "uint8"
        return geo

    def _ckpt_snapshot(self, trigger: str = "manual"):
        """Capture current state as a ckpt.Snapshot (lock-held pointer
        copy — the expensive work happens in the writer)."""
        from gol_tpu import ckpt as ckpt_mod

        with self._state_lock:
            cells, repr_ = self._cells, self._repr
            pad, turn = self._pad_rows, self._turn
        if cells is None:
            raise RuntimeError("no board loaded")
        h = cells.shape[-2] - pad
        w = _board_width(cells, repr_)
        return ckpt_mod.Snapshot(cells, repr_, pad, turn, (h, w),
                                 self._rule.rulestring, trigger=trigger,
                                 mesh={"devices": len(self._devices)})

    def checkpoint_now(self, directory: Optional[str] = None,
                       trigger: str = "manual") -> Tuple[str, int]:
        """Write one durable manifest checkpoint SYNCHRONOUSLY to
        `directory` (default: the configured GOL_CKPT dir); returns
        (manifest_path, turn). The Checkpoint wire method and the
        SIGTERM handler land here — callers who need the durability
        guarantee before proceeding."""
        from gol_tpu import ckpt as ckpt_mod

        d = directory or os.environ.get(CKPT_ENV, "")
        if not d:
            raise RuntimeError(
                "checkpointing not configured: set GOL_CKPT or pass "
                "--checkpoint DIR")
        self._check_alive()
        snap = self._ckpt_snapshot(trigger)
        writer = ckpt_mod.CheckpointWriter(
            d, run_id=obs_flight.RUN_ID,
            keep_last=env_int(ckpt_mod.CKPT_KEEP_ENV,
                              ckpt_mod.CKPT_KEEP_DEFAULT),
            keep_every=env_int(ckpt_mod.CKPT_KEEP_EVERY_ENV, 0,
                               minimum=0))
        return writer.write_sync(snap), snap.turn

    def restore_run(self, path: str, reshard: bool = False) -> int:
        """Verified manifest/legacy restore (ckpt.restore_engine over
        this engine); returns the restored turn. `reshard=True` accepts
        a checkpoint whose recorded geometry disagrees with this engine
        by routing it through the host-side canonical repack."""
        from gol_tpu import ckpt as ckpt_mod

        return ckpt_mod.restore_engine(self, path, reshard=reshard)

    def save_checkpoint(self, path: str) -> None:
        """Atomically write the board state + turn + rulestring as .npz.

        Packed boards are stored AS PACKED WORDS (`words` + `width`) —
        8x smaller than pixels and with no device-side unpack, which is
        what keeps periodic checkpointing viable at 65536² (the pixel
        route would unpack 512 MB of words into a 4.3 GB array every
        interval). Unpacked boards store `world` pixels (also the legacy
        format `load_checkpoint` still accepts).

        The temp name is per-writer: the SIGTERM handler (main thread)
        can race the run thread's periodic save on the same target, and
        a shared '.tmp' would let the two writers interleave and publish
        a torn file; with unique temps each os.replace publishes a
        complete checkpoint (last one wins)."""
        with self._state_lock:
            cells, turn, repr_ = self._cells, self._turn, self._repr
            pad = self._pad_rows
        if cells is None:
            raise RuntimeError("no board loaded")
        if pad:
            cells = cells[..., : cells.shape[-2] - pad, :]
        if repr_ == "packed":
            from gol_tpu.ops.bitpack import WORD_BITS

            arrays = {
                "words": np.asarray(jax.device_get(cells)),
                "width": cells.shape[-1] * WORD_BITS,
            }
        elif repr_ == "gen3":
            from gol_tpu.ops.bitpack import WORD_BITS

            # Packed-native like "words": (2, H, Wp) planes, no unpack.
            arrays = {
                "gen_planes": np.asarray(jax.device_get(cells)),
                "width": cells.shape[-1] * WORD_BITS,
            }
        elif repr_ == "gen8":
            arrays = {"gen_state": np.asarray(jax.device_get(cells))}
        elif repr_ == "f32":
            # Float boards checkpoint their exact state — quantizing to
            # pixels would silently lose the continuous dynamics.
            arrays = {"float_state": np.asarray(
                jax.device_get(cells), dtype=np.float32)}
        else:
            arrays = {"world": np.asarray(
                jax.device_get(to_pixels(cells)))}
        payload = next(v for k, v in arrays.items() if k != "width")
        save = (np.savez_compressed
                if payload.nbytes <= self.CKPT_COMPRESS_LIMIT
                else np.savez)
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "wb") as f:
                save(f, turn=turn, rulestring=self._rule.rulestring,
                     **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load_checkpoint(self, path: str) -> int:
        """Restore (world, turn) from a checkpoint; returns the turn.
        The restored state serves `get_world`/`alive_count` immediately,
        so a controller can reattach with CONT=yes as if the engine had
        never died. A checkpoint recording a different rule than this
        engine's is rejected — silently resuming evolution under the
        wrong rule would corrupt the run."""
        self._check_alive()
        with np.load(path) as z:
            turn = int(z["turn"])
            if "rulestring" in z.files:
                ckpt_rule = str(z["rulestring"])
                if ckpt_rule != self._rule.rulestring:
                    raise ValueError(
                        f"checkpoint rule {ckpt_rule!r} != engine rule "
                        f"{self._rule.rulestring!r}")
            if "gen_planes" in z.files:
                planes = z["gen_planes"]
                width = int(z["width"])
                if (not isinstance(self._rule, GenerationsRule)
                        or self._rule.states != 3):
                    raise ValueError(
                        f"{path}: two-plane checkpoint needs a 3-state "
                        f"Generations engine, not {self._rule.rulestring}")
                if (planes.dtype != np.uint32 or planes.ndim != 3
                        or planes.shape[0] != 2
                        or planes.shape[-1] * 32 != width):
                    raise ValueError(
                        f"{path}: inconsistent planes checkpoint "
                        f"({planes.dtype} {planes.shape} for width "
                        f"{width})")
                cells, repr_ = jax.device_put(planes), "gen3"
            elif "gen_state" in z.files:
                state = z["gen_state"]
                if not isinstance(self._rule, GenerationsRule):
                    raise ValueError(
                        f"{path}: Generations checkpoint needs a "
                        f"Generations engine, not {self._rule.rulestring}")
                if (state.dtype != np.uint8 or state.ndim != 2
                        or int(state.max(initial=0)) >= self._rule.states):
                    raise ValueError(
                        f"{path}: bad Generations state checkpoint "
                        f"({state.dtype} {state.shape})")
                cells, repr_ = jax.device_put(state), "gen8"
            elif "float_state" in z.files:
                state = z["float_state"]
                if not isinstance(self._rule, LeniaRule):
                    raise ValueError(
                        f"{path}: float-state checkpoint needs a "
                        f"continuous-family engine, not "
                        f"{self._rule.rulestring}")
                if state.dtype != np.float32 or state.ndim != 2:
                    raise ValueError(
                        f"{path}: bad float-state checkpoint "
                        f"({state.dtype} {state.shape}); continuous "
                        f"boards are stored as 2-D float32")
                if not np.all(np.isfinite(state)):
                    raise ValueError(
                        f"{path}: float-state checkpoint carries "
                        f"non-finite values")
                cells = jax.device_put(np.clip(state, 0.0, 1.0))
                repr_ = "f32"
            elif "words" in z.files:
                # Packed-native checkpoint: no unpack/repack round trip.
                words = z["words"]
                width = int(z["width"])
                packed, _ = select_representation(width)
                if not packed or words.shape[-1] * 32 != width:
                    raise ValueError(
                        f"{path}: inconsistent packed checkpoint "
                        f"({words.shape} words for width {width})")
                if words.dtype != np.uint32:
                    # A foreign-tooled/tampered file: device_put would
                    # silently downcast (x64 disabled) and the carry-save
                    # kernels would evolve a bit-reinterpreted board, far
                    # from this load site.
                    raise ValueError(
                        f"{path}: packed words must be uint32, "
                        f"got {words.dtype}")
                cells, repr_ = jax.device_put(words), "packed"
            else:
                world = z["world"]  # legacy / unpacked pixel format
                height, width = world.shape
                if isinstance(self._rule, GenerationsRule):
                    # Pixel-encoded checkpoint of a Generations board:
                    # decode through the documented gray-level mapping.
                    from gol_tpu.models.generations import from_pixels_gen

                    cells = jax.device_put(
                        from_pixels_gen(world, self._rule))
                    repr_ = "gen8"
                elif isinstance(self._rule, LeniaRule):
                    # The /255 pixel decode is lossy; a float board's
                    # checkpoint always carries float_state, so a pixel
                    # file here is the wrong artifact, not a fallback.
                    raise ValueError(
                        f"{path}: pixel checkpoint cannot restore a "
                        f"continuous float board losslessly (want a "
                        f"float_state checkpoint)")
                elif isinstance(self._rule, LargerThanLifeRule):
                    # Conv-tier binary family: unpacked {0,1} cells
                    # (the conv kernels have no packed form).
                    cells = jax.device_put(from_pixels(world))
                    repr_ = "u8"
                else:
                    packed, _ = select_representation(width)
                    cells01 = from_pixels(world)
                    cells = (pack(cells01) if packed
                             else jax.device_put(cells01))
                    repr_ = "packed" if packed else "u8"
        with self._state_lock:
            if self._running:
                # Fail BEFORE the count dispatch below: device work that
                # queues behind the in-flight pipeline would cost
                # seconds only to be discarded by this error. (The
                # install re-checks under the lock — this early check
                # just keeps the error path cheap.)
                raise RuntimeError("cannot restore while running")
        # One-off count dispatch at restore so the poll path serves the
        # restored state dispatch-free from the first tick.
        alive = self._alive_dispatch(cells, repr_, 0)
        with self._state_lock:
            if self._running:
                raise RuntimeError("cannot restore while running")
            self._cells = cells
            self._repr = repr_
            self._packed = repr_ == "packed"
            self._pad_rows = 0  # checkpoints store cropped boards
            self._turn = turn
            self._publish_locked(alive, turn, reset_floor=True)
        return turn

    # ------------------------------------------------------------- internals

    def _resolve_mesh2d(self, height: int, width: int, packed: bool,
                        generations: bool = False):
        """The requested 2-D mesh, or None to use 1-D row sharding (no
        request, unpacked board, Generations rule, or a request the
        board/devices can't satisfy). Any unsatisfiable EXPLICIT request
        warns (r5 — VERDICT r4 #6): a silent downgrade would leave an
        operator believing their GOL_MESH took effect, the same stance
        as the 1-D divisor warning. Policy (docs/ARCHITECTURE.md "1-D
        vs 2-D"): 1-D is always the default; 2-D is explicit opt-in
        because its per-link advantage only materialises once row
        strips go thinner than ~4x the macro depth, far beyond
        single-host device counts."""
        if self._mesh_shape is None:
            return None

        def _fallback(reason: str):
            import warnings

            warnings.warn(
                f"2-D mesh request {self._mesh_shape} ignored ({reason}); "
                f"falling back to 1-D row sharding")
            return None

        if generations:
            return _fallback(
                f"the 2-D perimeter-halo path serves life-like packed "
                f"boards only; Generations rule {self._rule.rulestring} "
                f"uses 1-D row sharding")
        if not packed:
            return _fallback(
                f"width {width} is not a whole number of 32-bit words — "
                f"the 2-D perimeter-halo path is packed-only")
        from gol_tpu.ops.bitpack import WORD_BITS
        from gol_tpu.parallel.mesh2d import make_mesh2d

        r, c = self._mesh_shape
        wp = width // WORD_BITS
        if r <= 0 or c <= 0:
            return _fallback("non-positive mesh dims")
        if r * c > len(self._devices):
            return _fallback(
                f"{r}x{c} needs {r * c} devices, have "
                f"{len(self._devices)}")
        if height % r or wp % c:
            return _fallback(
                f"board {height} rows x {wp} words does not tile "
                f"{r}x{c} evenly")
        return make_mesh2d((r, c), self._devices)

    @staticmethod
    def _float_state(cells, pad: int) -> np.ndarray:
        """Device f32 state handle -> exact host float32 array (pad
        rows cropped) — the lossless counterpart of `_materialize` for
        the float wire frame and checkpoint paths."""
        if pad:
            cells = cells[..., : cells.shape[-2] - pad, :]
        return np.asarray(jax.device_get(cells), dtype=np.float32)

    def _snapshot(self) -> Tuple[np.ndarray, int]:
        with self._state_lock:
            cells, turn, repr_ = self._cells, self._turn, self._repr
            pad = self._pad_rows
        return self._materialize(cells, repr_, pad), turn

    def _materialize(self, cells, repr_: str, pad: int = 0) -> np.ndarray:
        """Device state handle -> host pixel array (blocks until the
        handle is real). Life-like boards materialize as {0,255};
        Generations boards as the documented state-scaled gray encoding
        (`models/generations.gray_levels`)."""
        if cells is None:
            raise RuntimeError("no board loaded")
        if pad:
            cells = cells[..., : cells.shape[-2] - pad, :]
        if repr_ == "packed":
            return np.asarray(jax.device_get(to_pixels(unpack(cells))))
        if repr_ == "u8":
            return np.asarray(jax.device_get(to_pixels(cells)))
        if repr_ == "f32":
            # Quantized presentation of continuous state (the float
            # frame/checkpoint paths read `_float_state` instead).
            state = np.asarray(jax.device_get(cells))
            return np.clip(np.rint(state * 255.0), 0, 255
                           ).astype(np.uint8)
        from gol_tpu.models.generations import to_pixels_gen

        if repr_ == "gen3":
            # One fused program + one transfer (two eager unpack
            # dispatches would double snapshot latency on the tunnel —
            # the same cost note as _padded_row_counts).
            state = np.asarray(jax.device_get(_gen3_state(cells)))
        else:  # gen8
            state = np.asarray(jax.device_get(cells))
        return to_pixels_gen(state, self._rule)

    def _adapt_chunk(self, chunk: int, k: int, elapsed: float) -> int:
        """Ramp-regime adapter (synchronous, one chunk in flight): size
        the power-of-two chunk so the MARGINAL compute per chunk
        approaches CHUNK_TARGET_SECONDS.

        Every dispatch carries a fixed host↔device cost (measured ~0.17 s
        per program round trip through the axon tunnel — independent of
        chunk size), so adapting on raw `elapsed` deadlocks: at chunk=1
        elapsed is already above any sub-second target and the run stays
        pinned at one turn per round-trip (~5 turns/s on a kernel capable
        of millions). The adapter tracks the smallest elapsed ever seen
        (`_fixed_cost_est`, the dispatch floor — no chunk can beat it) and
        grows while compute-above-floor is under target. Growth takes
        power-of-4 strides because every chunk size visited costs a
        one-off XLA compile (~1 s even from the persistent cache); an
        overshooting stride is corrected by at most one halving, still
        fewer sizes than doubling all the way up.

        Only valid for SYNCHRONOUS measurements (one chunk in flight —
        the ramp, or GOL_PIPELINE_DEPTH=1): once the dispatch pipeline
        opens, pop-to-pop times under-measure whenever the host falls
        behind and completions cluster, so the pipelined regime uses
        `_adapt_chunk_windowed` instead."""
        if k != chunk:
            return chunk  # partial (remainder) chunk — timing unrepresentative
        self._fixed_cost_est = min(self._fixed_cost_est, elapsed)
        marginal = elapsed - self._fixed_cost_est
        if marginal < self._chunk_target:
            # Every ramp chunk costs a full synchronous round trip
            # (~0.17 s on the tunnel), so fewer, larger strides are the
            # cheapest path to equilibrium. While compute is more than
            # 16x under target a x16 stride provably cannot overshoot
            # the [target, 2target] band; nearer the target fall back to
            # the x4/x2 strides (r4: cut the 512² ramp from ~11 sync
            # chunks to ~7, ~0.7 s of fixed cost per cold run).
            if (marginal * 16 <= self._chunk_target
                    and chunk * 16 <= self._max_chunk):
                return chunk * 16
            if chunk * 4 <= self._max_chunk:
                return chunk * 4
            if chunk * 2 <= self._max_chunk:
                return chunk * 2
        if marginal > self._chunk_target * 2 and chunk > 1:
            return chunk // 2
        return chunk

    def _adapt_chunk_windowed(self, chunk: int, now: float, k: int) -> int:
        """Pipelined-regime adapter: estimate the per-turn pace from a
        sliding window of pop completions and size the chunk so
        chunk × pace lands in [target, 2×target].

        Individual pop-to-pop times are unusable once chunks overlap — a
        slow host drains several queued completions microseconds apart,
        and growing on those near-zero readings runs away until a single
        chunk takes minutes (the quit-latency bug this replaces). The
        windowed turns/second rate spans several chunks, so clustered
        completions and transient stalls (a concurrent Alivecount poll,
        tunnel jitter) average out; the pipeline's fixed dispatch costs
        are amortized INTO the rate, which is exactly the pace that
        matters for control latency."""
        if self._pace_skip > 0:
            # Pops right after a pace reset may be completions that
            # finished DURING the excluded stall, draining microseconds
            # apart — recording them would anchor the fresh window on a
            # near-zero span and inflate the rate. At most `depth` chunks
            # can be queued, so skipping that many suspect pops suffices.
            # (Clusters elsewhere are harmless: the windowed rate is a
            # true average as long as the span is real, and a mid-window
            # cluster of ≤ depth entries cannot consume the whole span —
            # the window out-sizes the pipeline by 5 entries.)
            self._pace_skip -= 1
            return chunk
        self._pace_window.append((now, k))
        rate = self._pace_rate()
        if rate is None:
            return chunk
        est = chunk / rate
        if est < self._chunk_target and chunk * 2 <= self._max_chunk:
            return chunk * 2
        if est > self._chunk_target * 2 and chunk > 1:
            return chunk // 2
        return chunk

    def _pace_rate(self) -> Optional[float]:
        """Turns/second across the sliding pop window (turns completed
        between the first and last pop timestamps); None until the window
        holds enough pops to be meaningful."""
        win = self._pace_window
        if len(win) < 4:
            return None
        span = win[-1][0] - win[0][0]
        turns = sum(kk for _, kk in list(win)[1:])
        if span <= 0 or turns <= 0:
            return None
        return turns / span
