"""Viewer gateway: the C10k half of the broadcast tier.

One `selectors`-based event-loop thread owns every Subscribe-upgraded
socket, replacing thread-per-connection FOR THE STREAMING PATH ONLY —
control-plane RPCs (and legacy raw-u8 / per-viewer GetView peers) keep
the server's threaded dispatch untouched. The accept path stays as-is
too: a Subscribe request arrives on a normal threaded connection, the
handler sends the ACK reply, then hands the live socket here
(`adopt`); the handler thread exits immediately, so ten thousand
subscribers never occupy conn slots or threads.

Per subscriber the gateway holds a few ints and a memoryview into the
stream's shared frozen frame — O(1) memory per viewer, zero copies.
Writes are non-blocking with partial-send resume; a stalled socket is
skipped forward to the stream's newest keyframe at the next frame
boundary (never mid-frame — framing integrity), with the skipped
frames metered as `gol_bcast_frames_dropped_total`. Fan-out latency
(frame publication -> last byte handed to a subscriber socket) feeds a
PR-8 log-bucket estimator flushed to `gol_bcast_fanout_ms{q}`.

Adopted sockets get TCP_NODELAY + SO_KEEPALIVE, matching the server
accept-path posture; `GOL_GATEWAY_MAX` caps adopted connections.
"""

from __future__ import annotations

import selectors
import socket
import threading
import time
from collections import deque
from typing import Optional

from gol_tpu.obs import catalog as obs
from gol_tpu.obs import slo as obs_slo
from gol_tpu.obs.log import log as obs_log
from gol_tpu.utils.envcfg import env_int
from gol_tpu import wire

GATEWAY_MAX_ENV = "GOL_GATEWAY_MAX"
GATEWAY_MAX_DEFAULT = 16384

# Metrics/gauge flush cadence; matches the PR-8 SLO batching discipline.
FLUSH_SECONDS = 0.5

_READ = selectors.EVENT_READ
_WRITE = selectors.EVENT_WRITE


class _Sub:
    """One adopted subscriber socket and its stream cursor."""

    __slots__ = ("sock", "fd", "stream", "next_seq", "cur", "mv", "off",
                 "want_write")

    def __init__(self, sock: socket.socket, stream) -> None:
        self.sock = sock
        self.fd = sock.fileno()
        self.stream = stream
        self.next_seq = 0
        self.cur = None      # BcastFrame currently being sent
        self.mv = None       # memoryview into cur.raw
        self.off = 0
        self.want_write = False


class ViewerGateway:
    def __init__(self) -> None:
        self._sel = selectors.DefaultSelector()
        # Self-wake pipe: adopt()/notify() from any thread nudge the
        # select() out of its sleep.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, _READ, None)
        self._adopt_q: deque = deque()
        self._lock = threading.Lock()
        self._subs: dict = {}  # fd -> _Sub
        self._max = env_int(GATEWAY_MAX_ENV, GATEWAY_MAX_DEFAULT)
        self._reserved = 0  # adopted + mid-adoption, for the cap
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        self._fanout = obs_slo.LogBucketEstimator()
        self._fanout_lock = threading.Lock()
        self._samples: list = []
        self._sent_bytes = 0
        self._sent_msgs = 0
        # Delivered per-run (frames, bytes) since the last flush —
        # touched only by the event-loop thread, handed to the usage
        # meter at the 0.5 s flush (PR 19).
        self._usage_pend: dict = {}
        self._last_flush = 0.0

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="gol-gateway", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
        self.notify()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def connections(self) -> int:
        with self._lock:
            return self._reserved

    # ---------------------------------------------------------- adoption

    def try_reserve(self) -> bool:
        """Claim one connection slot under GOL_GATEWAY_MAX. The server
        reserves BEFORE sending the Subscribe ACK so an over-capacity
        peer gets an error reply, never a dead ACKed socket."""
        with self._lock:
            if self._stopping or self._reserved >= self._max:
                return False
            self._reserved += 1
            return True

    def release_reservation(self) -> None:
        with self._lock:
            self._reserved = max(0, self._reserved - 1)

    def adopt(self, conn: socket.socket, stream) -> None:
        """Hand an ACKed, upgraded socket to the event loop. Requires a
        successful `try_reserve`; never blocks and never raises."""
        with self._lock:
            self._adopt_q.append((conn, stream))
        self.notify()

    def notify(self) -> None:
        """Wake the loop (new adoptions, or the hub published frames).
        Coalescing is fine — one byte wakes one scan."""
        try:
            self._wake_w.send(b"\x01")
        except (BlockingIOError, InterruptedError):
            pass  # wake pipe already full = a wake is already pending
        except OSError:
            pass

    # --------------------------------------------------------- telemetry

    def fanout_snapshot(self) -> dict:
        """Flush pending samples and return the estimator snapshot
        ({count, sum, p50, p95, p99} in seconds) — bench/test surface."""
        with self._fanout_lock:
            if self._samples:
                self._fanout.observe_batch(self._samples)
                self._samples = []
            return self._fanout.snapshot()

    def fanout_reset(self) -> None:
        """Discard accumulated fan-out samples. Admission catch-up
        frames carry the PUBLISH timestamp of a frame that predates
        the subscriber, so a measured window opened after a population
        change should reset first or its tail is attach lag, not
        fan-out latency."""
        with self._fanout_lock:
            self._fanout = obs_slo.LogBucketEstimator()
            self._samples = []

    # -------------------------------------------------------- event loop

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    break
            try:
                events = self._sel.select(timeout=FLUSH_SECONDS)
            except OSError:
                break
            now = time.monotonic()
            woke = False
            for key, mask in events:
                if key.data is None:
                    self._drain_wake()
                    woke = True
                    continue
                sub = key.data
                if mask & _READ:
                    self._on_readable(sub)
                if sub.fd in self._subs and (mask & _WRITE):
                    self._pump(sub, now)
            self._admit_pending(now)
            if woke:
                # Frames were published (or streams closed): pump every
                # subscriber that is not already write-blocked — blocked
                # ones resume via their EVENT_WRITE readiness.
                for sub in list(self._subs.values()):
                    if not sub.want_write:
                        self._pump(sub, now)
            if now - self._last_flush >= FLUSH_SECONDS:
                self._flush(now)
        self._teardown()

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _admit_pending(self, now: float) -> None:
        while True:
            with self._lock:
                if not self._adopt_q:
                    return
                conn, stream = self._adopt_q.popleft()
            try:
                conn.setblocking(False)
                wire.enable_nodelay(conn)
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            except OSError:
                self._close_quiet(conn)
                self.release_reservation()
                continue
            sub = _Sub(conn, stream)
            sub.next_seq = stream.attach()
            self._subs[sub.fd] = sub
            try:
                self._sel.register(conn, _READ, sub)
            except (OSError, ValueError):
                self._subs.pop(sub.fd, None)
                stream.detach()
                self._close_quiet(conn)
                self.release_reservation()
                continue
            self._pump(sub, now)

    def _pump(self, sub: _Sub, now: float) -> None:
        """Push stream bytes until the socket blocks or the subscriber
        is caught up. Skips (and meters) frames the ring dropped."""
        try:
            while True:
                if sub.mv is not None:
                    n = sub.sock.send(sub.mv[sub.off:])
                    self._sent_bytes += n
                    pend = self._usage_pend.setdefault(
                        sub.stream.run_id or "run0", [0, 0])
                    pend[1] += n
                    sub.off += n
                    if sub.off < len(sub.mv):
                        self._set_write(sub, True)
                        return
                    # Frame fully handed to the kernel: the fan-out
                    # latency sample for this (frame, subscriber).
                    self._sent_msgs += 1
                    self._usage_pend.setdefault(
                        sub.stream.run_id or "run0", [0, 0])[0] += 1
                    cur = sub.cur
                    sub.mv = None
                    sub.cur = None
                    if cur is not None:
                        with self._fanout_lock:
                            self._samples.append(max(0.0, now - cur.t_pub))
                        if cur.end:
                            self._disconnect(sub)
                            return
                nxt = sub.stream.next_frame(sub.next_seq)
                if nxt is None:
                    self._set_write(sub, False)
                    return
                frame, skipped = nxt
                if skipped:
                    obs.BCAST_FRAMES_DROPPED.inc(skipped)
                sub.cur = frame
                sub.mv = memoryview(frame.raw)
                sub.off = 0
                sub.next_seq = frame.seq + 1
        except (BlockingIOError, InterruptedError):
            self._set_write(sub, True)
        except OSError:
            self._disconnect(sub)

    def _on_readable(self, sub: _Sub) -> None:
        """Subscribers are server-push only: any bytes the peer sends
        are discarded; EOF or an error hangs the subscriber up."""
        try:
            while True:
                data = sub.sock.recv(1 << 16)
                if not data:
                    self._disconnect(sub)
                    return
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._disconnect(sub)

    def _set_write(self, sub: _Sub, want: bool) -> None:
        if want == sub.want_write:
            return
        sub.want_write = want
        try:
            self._sel.modify(sub.sock, _READ | (_WRITE if want else 0), sub)
        except (OSError, ValueError, KeyError):
            self._disconnect(sub)

    def _disconnect(self, sub: _Sub) -> None:
        try:
            self._sel.unregister(sub.sock)
        except (OSError, ValueError, KeyError):
            pass
        self._close_quiet(sub.sock)
        self._subs.pop(sub.fd, None)
        sub.stream.detach()
        self.release_reservation()

    @staticmethod
    def _close_quiet(sock_: socket.socket) -> None:
        try:
            sock_.close()
        except OSError:
            pass

    def _flush(self, now: float) -> None:
        self._last_flush = now
        with self._fanout_lock:
            if self._samples:
                self._fanout.observe_batch(self._samples)
                self._samples = []
            snap = self._fanout.snapshot()
        if snap["count"]:
            obs.BCAST_FANOUT_MS.labels(q="p50").set(snap["p50"] * 1e3)
            obs.BCAST_FANOUT_MS.labels(q="p95").set(snap["p95"] * 1e3)
            obs.BCAST_FANOUT_MS.labels(q="p99").set(snap["p99"] * 1e3)
        if self._sent_bytes:
            obs.BCAST_SENT_BYTES.inc(self._sent_bytes)
            obs.WIRE_BYTES.labels(direction="sent").inc(self._sent_bytes)
            obs.WIRE_MESSAGES.labels(direction="sent").inc(self._sent_msgs)
            self._sent_bytes = 0
            self._sent_msgs = 0
        if self._usage_pend:
            try:  # delivered per-(run, subscriber) attribution (PR 19)
                from gol_tpu.obs import usage as obs_usage
                obs_usage.METER.charge_broadcast_sent(self._usage_pend)
            except Exception:
                pass
            self._usage_pend = {}
        obs.BCAST_SUBSCRIBERS.set(len(self._subs))
        obs.GATEWAY_CONNECTIONS.set(self.connections())

    def _teardown(self) -> None:
        for sub in list(self._subs.values()):
            self._disconnect(sub)
        try:
            self._sel.unregister(self._wake_r)
        except (OSError, ValueError, KeyError):
            pass
        self._close_quiet(self._wake_r)
        self._close_quiet(self._wake_w)
        try:
            self._sel.close()
        except OSError:
            pass
        self._flush(time.monotonic())
        obs_log("gateway.stopped", level="info")
