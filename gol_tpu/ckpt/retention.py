"""Retention: keep-last-N ∪ keep-every-K-turns, crash-safe GC.

Deletion order is the mirror of publish order: the MANIFEST goes first
(the checkpoint stops being durable in one atomic unlink), the payload
second — a crash between the two leaves an orphan payload, which is
exactly the state a crash mid-publish leaves, and the same aged-orphan
sweep collects both. `keep_last` is clamped to >= 1 so no configuration
can delete the newest durable checkpoint.

Orphans and `*.tmp` litter are only swept once they are older than
`ORPHAN_GRACE_SECONDS`: a payload published moments ago by ANOTHER
process (the in-process writer holds `dir_lock` across
publish+retention, but a second process — say a SIGTERM'd predecessor —
does not share that lock) may still be waiting on its manifest.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict

from gol_tpu.ckpt import manifest as mf
from gol_tpu.obs.log import log as obs_log

ORPHAN_GRACE_SECONDS = 60.0

_DIR_LOCKS: Dict[str, threading.Lock] = {}
_DIR_LOCKS_GUARD = threading.Lock()


def dir_lock(directory: str) -> threading.Lock:
    """Process-wide per-directory mutation lock, so the run's background
    writer and an emergency write_sync on another thread never interleave
    publishes or sweep each other's in-flight payloads."""
    key = os.path.realpath(directory)
    with _DIR_LOCKS_GUARD:
        lock = _DIR_LOCKS.get(key)
        if lock is None:
            lock = _DIR_LOCKS[key] = threading.Lock()
        return lock


class RetentionPolicy:
    """keep_last newest checkpoints (always >= 1) plus every checkpoint
    whose turn is divisible by keep_every (0 disables pinning)."""

    def __init__(self, keep_last: int = 3, keep_every: int = 0) -> None:
        self.keep_last = max(1, int(keep_last))
        self.keep_every = max(0, int(keep_every))

    def apply(self, directory: str, locked: bool = False) -> dict:
        """Delete non-retained checkpoints and aged garbage; returns
        {"removed": [...], "kept": [...]} of checkpoint turns. `locked`
        asserts the caller already holds dir_lock (the writer's publish
        path, which must not re-acquire)."""
        if not locked:
            with dir_lock(directory):
                return self.apply(directory, locked=True)

        entries = list(mf.list_checkpoints(directory))  # turn-ascending
        keep = set(t for t, _, _ in entries[-self.keep_last:])
        if self.keep_every:
            keep.update(t for t, _, _ in entries
                        if t % self.keep_every == 0)
        removed = []
        for turn, man_path, m in entries:
            if turn in keep:
                continue
            payload = mf.payload_path(man_path, m)
            try:
                os.unlink(man_path)  # durability bit cleared FIRST
                if os.path.exists(payload):
                    os.unlink(payload)
                removed.append(turn)
            except OSError as e:
                obs_log("ckpt.gc_failed", level="warn",
                        path=man_path, error=str(e))
        swept = _sweep_garbage(directory)
        if removed or swept:
            obs_log("ckpt.gc", removed=len(removed), swept=swept,
                    kept=len(keep))
        return {"removed": removed, "kept": sorted(keep)}


def _sweep_garbage(directory: str) -> int:
    """Remove aged *.tmp litter and orphan payloads (payload without a
    manifest = a crash between the two publishes)."""
    now = time.time()
    swept = 0
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return 0
    present = set(names)
    for name in names:
        path = os.path.join(directory, name)
        orphan = (name.startswith(mf.CKPT_PREFIX)
                  and name.endswith(mf.PAYLOAD_SUFFIX)
                  and name[:-len(mf.PAYLOAD_SUFFIX)] + mf.MANIFEST_SUFFIX
                  not in present)
        if not (name.endswith(".tmp") or orphan):
            continue
        try:
            if now - os.path.getmtime(path) < ORPHAN_GRACE_SECONDS:
                continue
            os.unlink(path)
            swept += 1
        except OSError:
            continue
    return swept
