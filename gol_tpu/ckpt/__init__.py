"""Checkpoint/restore subsystem: async snapshots, integrity-verified
manifests, resumable and crash-recoverable runs.

The reference's only continuity mechanism is controller detach/reattach
(`CONT=yes`, `Local/gol/distributor.go:171-178`) — state lives in broker
globals and dies with the process. This package adds the durability
layer underneath: every checkpoint is a payload `.npz` (the exact format
`Engine.load_checkpoint` already speaks) plus a `gol-ckpt/1` JSON
manifest recording run identity, turn, rule, board geometry,
representation, and the SHA-256 of the payload, published
payload-first / manifest-last with tmp+fsync+rename at each step so a
crash at ANY instant leaves either a durable checkpoint or removable
garbage — never a torn file a resume could silently trust.

Layout of a checkpoint directory (GOL_CKPT / --checkpoint):

    ckpt-000000001024.npz    payload (published first)
    ckpt-000000001024.json   manifest (published second — durability bit)

Modules:

    manifest.py   schema, atomic write/read/verify, directory listing
    writer.py     background double-buffered writer (off the hot loop)
    retention.py  keep-last + keep-every-K-turns GC, crash-safe
    restore.py    resolve dir|manifest|legacy-npz -> verified engine state
    reshard.py    geometry contract + host-side canonical repack, so a
                  checkpoint resumes onto a different mesh shape,
                  representation family, or engine kind (bit-identical)

Env / flags (read at run time, like every GOL_* knob):

    GOL_CKPT=<dir>                --checkpoint DIR    checkpoint directory
    GOL_CKPT_EVERY_TURNS=<n>      --ckpt-every N      manifest ckpt cadence
    GOL_CKPT_KEEP=<n>             --ckpt-keep N       retention: keep last N
    GOL_CKPT_KEEP_EVERY=<turns>                       retention: pin every K
    GOL_CKPT_EVERY=<seconds>                          legacy single-file autosave

docs/ARCHITECTURE.md "Checkpoint / restore" is the narrative version.
"""

from gol_tpu.ckpt.manifest import (  # noqa: F401
    CheckpointIntegrityError,
    MANIFEST_SCHEMA,
    list_checkpoints,
    read_manifest,
    verify_manifest,
    write_manifest,
)
from gol_tpu.ckpt.reshard import (  # noqa: F401
    GeometryMismatch,
    load_canonical,
    reshard_into,
    restore_delta,
)
from gol_tpu.ckpt.restore import resolve, restore_engine  # noqa: F401
from gol_tpu.ckpt.retention import RetentionPolicy  # noqa: F401
from gol_tpu.ckpt.writer import (  # noqa: F401
    CheckpointWriter,
    CheckpointWriterPool,
    Snapshot,
)

# Env names (single source; engine/server/main/bench all import these).
CKPT_DIR_ENV = "GOL_CKPT"
CKPT_EVERY_TURNS_ENV = "GOL_CKPT_EVERY_TURNS"
CKPT_KEEP_ENV = "GOL_CKPT_KEEP"
CKPT_KEEP_EVERY_ENV = "GOL_CKPT_KEEP_EVERY"
CKPT_KEEP_DEFAULT = 3
