"""Resume: resolve a checkpoint reference, verify it, install it.

`--resume` accepts three spellings and `resolve()` normalizes them:

    a directory      -> the newest durable manifest inside it
    a *.json file    -> that manifest
    anything else    -> a legacy single-file .npz (the pre-manifest
                        autosave format), loaded without manifest
                        verification but with the engine's own
                        structural checks

Manifest restores are verified end-to-end (schema + payload size +
recomputed SHA-256) BEFORE any bytes reach the engine — a corrupted
checkpoint is refused with CheckpointIntegrityError, never half-loaded.
The payload itself is the legacy npz format, so the engine's existing
`load_checkpoint` does the actual install and the saved turn re-enters
the chunked run loop exactly where it left off.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from gol_tpu.ckpt import manifest as mf
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import trace as obs_trace
from gol_tpu.obs.log import log as obs_log


def resolve(path: str) -> Tuple[str, Optional[str]]:
    """Normalize a --resume reference to ("manifest", manifest_path) or
    ("legacy", npz_path). Raises FileNotFoundError when there is nothing
    to resume from."""
    if os.path.isdir(path):
        latest = mf.latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(
                f"{path}: no durable checkpoint (no readable "
                f"{mf.CKPT_PREFIX}*{mf.MANIFEST_SUFFIX} manifest)")
        return "manifest", latest[1]
    if not os.path.exists(path):
        raise FileNotFoundError(f"{path}: no such checkpoint")
    if path.endswith(mf.MANIFEST_SUFFIX):
        return "manifest", path
    return "legacy", path


def restore_engine(engine, path: str, verify: bool = True) -> int:
    """Verify + install a checkpoint into `engine`; returns the restored
    turn. `engine` is anything with the `load_checkpoint(npz_path)`
    surface (dense Engine or SparseEngine)."""
    kind, target = resolve(path)
    with obs_trace.span("ckpt.restore",
                        attrs={"kind": kind,
                               "path": os.path.basename(target)}) as span:
        try:
            if kind == "manifest":
                m = (mf.verify_manifest(target) if verify
                     else mf.read_manifest(target))
                payload = mf.payload_path(target, m)
                turn = engine.load_checkpoint(payload)
                if turn != m["turn"]:
                    # The payload decoded but disagrees with its own
                    # manifest — treat as corruption, refuse the state.
                    raise mf.CheckpointIntegrityError(
                        f"{target}: payload turn {turn} != manifest "
                        f"turn {m['turn']}")
            else:
                turn = engine.load_checkpoint(target)
        except mf.CheckpointIntegrityError:
            obs.CKPT_RESTORES.labels(status="rejected").inc()
            raise
        except Exception:
            obs.CKPT_RESTORES.labels(status="error").inc()
            raise
        span.attrs["turn"] = turn
    obs.CKPT_RESTORES.labels(status="ok").inc()
    obs_log("ckpt.restored", kind=kind, turn=turn,
            path=os.path.basename(target))
    return turn
