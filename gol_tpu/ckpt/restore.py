"""Resume: resolve a checkpoint reference, verify it, install it.

`--resume` accepts three spellings and `resolve()` normalizes them:

    a directory      -> the newest durable manifest inside it
    a *.json file    -> that manifest
    anything else    -> a legacy single-file .npz (the pre-manifest
                        autosave format), loaded without manifest
                        verification but with the engine's own
                        structural checks

Manifest restores are verified end-to-end (schema + payload size +
recomputed SHA-256) BEFORE any bytes reach the engine — a corrupted
checkpoint is refused with CheckpointIntegrityError, never half-loaded.
The payload itself is the legacy npz format, so the engine's existing
`load_checkpoint` does the actual install and the saved turn re-enters
the chunked run loop exactly where it left off.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from gol_tpu.ckpt import manifest as mf
from gol_tpu.ckpt import reshard as _reshard
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import trace as obs_trace
from gol_tpu.obs.log import log as obs_log


def resolve(path: str) -> Tuple[str, Optional[str]]:
    """Normalize a --resume reference to ("manifest", manifest_path) or
    ("legacy", npz_path). Raises FileNotFoundError when there is nothing
    to resume from."""
    if os.path.isdir(path):
        latest = mf.latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError(
                f"{path}: no durable checkpoint (no readable "
                f"{mf.CKPT_PREFIX}*{mf.MANIFEST_SUFFIX} manifest)")
        return "manifest", latest[1]
    if not os.path.exists(path):
        raise FileNotFoundError(f"{path}: no such checkpoint")
    if path.endswith(mf.MANIFEST_SUFFIX):
        return "manifest", path
    return "legacy", path


def restore_engine(engine, path: str, verify: bool = True,
                   reshard: bool = False) -> int:
    """Verify + install a checkpoint into `engine`; returns the restored
    turn. `engine` is anything with the `load_checkpoint(npz_path)`
    surface (dense Engine or SparseEngine).

    When the manifest records a geometry the engine disagrees with
    (mesh device count, representation family, sparse torus size — see
    ckpt/reshard.py), the restore is refused with `GeometryMismatch`
    (tagged rpc_error_kind="geometry") unless `reshard=True`, which
    routes the payload through the host-side canonical repack instead
    of the direct load — bit-identical, only the placement changes."""
    kind, target = resolve(path)
    with obs_trace.span("ckpt.restore",
                        attrs={"kind": kind,
                               "path": os.path.basename(target)}) as span:
        try:
            if kind == "manifest":
                m = (mf.verify_manifest(target) if verify
                     else mf.read_manifest(target))
                payload = mf.payload_path(target, m)
                delta = _reshard.restore_delta(m, engine)
                if delta and not reshard:
                    raise _reshard.GeometryMismatch(
                        f"{target}: checkpoint geometry does not match "
                        f"this engine ({'; '.join(delta)}); request a "
                        f"reshard (--reshard / reshard=True) to repack "
                        f"it")
                if delta:
                    span.attrs["reshard"] = "; ".join(delta)
                    turn = _reshard.reshard_into(engine, m, payload)
                else:
                    turn = engine.load_checkpoint(payload)
                if turn != m["turn"]:
                    # The payload decoded but disagrees with its own
                    # manifest — treat as corruption, refuse the state.
                    raise mf.CheckpointIntegrityError(
                        f"{target}: payload turn {turn} != manifest "
                        f"turn {m['turn']}")
            elif reshard:
                # Legacy npz has no manifest geometry to compare, but an
                # explicit reshard request still routes through the
                # canonical repack (e.g. a sparse autosave resumed on a
                # dense engine).
                turn = _reshard.reshard_into(engine, None, target)
            else:
                turn = engine.load_checkpoint(target)
        except mf.CheckpointIntegrityError:
            obs.CKPT_RESTORES.labels(status="rejected").inc()
            raise
        except Exception:
            obs.CKPT_RESTORES.labels(status="error").inc()
            raise
        span.attrs["turn"] = turn
    obs.CKPT_RESTORES.labels(status="ok").inc()
    obs_log("ckpt.restored", kind=kind, turn=turn,
            path=os.path.basename(target))
    return turn
