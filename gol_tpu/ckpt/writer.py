"""Background double-buffered checkpoint writer.

The turn loop must never block on disk (the < 5 % CUPS budget in
ISSUE/acceptance): at a chunk boundary the engine captures a Snapshot —
the IMMUTABLE device array handle plus metadata, a lock-held pointer
copy — and `submit()`s it. The writer thread then does everything
expensive off the hot loop: the device→host transfer (jax arrays are
immutable, so reading the handle races nothing; the engine meanwhile
dispatches the next chunks against newer handles), payload
serialization, SHA-256, the payload-first/manifest-last atomic publish,
and retention GC.

Double buffering: one snapshot in write + at most one pending. A third
submit before the disk catches up REPLACES the pending snapshot (newest
state wins — a checkpoint's only job is to be the freshest durable
state) and the superseded one is counted as
`gol_ckpt_writes_total{status="dropped"}` rather than queued: an
unbounded queue would turn a slow disk into unbounded host memory.

`write_sync()` is the same pipeline on the CALLING thread — the
emergency paths (SIGTERM, engine-loop exception, the Checkpoint wire
method) where there may be no later boundary to wait for.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Optional, Tuple

import numpy as np

from gol_tpu.ckpt import manifest as mf
from gol_tpu.ckpt.retention import RetentionPolicy, dir_lock
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import trace as obs_trace
from gol_tpu.obs.log import log as obs_log

# Same compression policy as the legacy engine autosave
# (engine.Engine.CKPT_COMPRESS_LIMIT): small payloads are
# zlib-compressed, huge ones written raw — compressing a 512 MB packed
# flagship board would dominate the checkpoint interval for little gain.
COMPRESS_LIMIT = 64 * 1024 * 1024

# Manifest trigger values (clamped — manifests are machine-read).
TRIGGERS = ("periodic", "final", "emergency", "sigterm", "manual",
            "remote")

# 8-bit popcount LUT for the packed-word alive marker (uint8 output is
# enough per byte; the sum accumulates in int64).
_POP8 = np.array([bin(i).count("1") for i in range(256)], dtype=np.uint8)


class Snapshot:
    """One checkpointable engine state, captured at a chunk boundary.

    `cells` is either a jax array handle (dense/sparse device state —
    the writer thread materializes it) or an already-host numpy array
    (the restore/inspect round-trip tests). `pad` rows are
    representation, not board, and are cropped host-side. `extra`
    carries the sparse window's (size, ox, oy)."""

    __slots__ = ("cells", "repr", "pad", "turn", "board", "rule",
                 "trigger", "extra", "mesh")

    def __init__(self, cells, repr_: str, pad: int, turn: int,
                 board: Tuple[int, int], rule: str,
                 trigger: str = "periodic", extra: Optional[dict] = None,
                 mesh: Optional[dict] = None):
        self.cells = cells
        self.repr = repr_
        self.pad = pad
        self.turn = turn
        self.board = board
        self.rule = rule
        self.trigger = trigger if trigger in TRIGGERS else "manual"
        self.extra = dict(extra or {})
        # The WRITING engine's placement geometry (at least {"devices"}).
        # Engines stamp this themselves: the global devstats mesh note
        # may describe a different engine in the same process, and the
        # reshard-at-restore delta (ckpt/reshard.py) compares it.
        self.mesh = dict(mesh) if mesh else None


def _materialize(snap: Snapshot) -> np.ndarray:
    """Device handle (or host array) -> host array, pad rows cropped.
    Blocks until the handle is real — on the WRITER thread, where that
    wait overlaps the engine's next chunks instead of stalling them."""
    import jax

    host = np.asarray(jax.device_get(snap.cells))
    if snap.pad:
        host = host[..., : host.shape[-2] - snap.pad, :]
    return host


def payload_arrays(host: np.ndarray, repr_: str, extra: dict) -> dict:
    """The payload .npz members for one representation — EXACTLY the
    format `Engine.load_checkpoint` / `SparseEngine.load_checkpoint`
    already accept, so every manifest payload doubles as a legacy
    checkpoint file."""
    if repr_ == "packed":
        return {"words": host, "width": host.shape[-1] * 32}
    if repr_ == "gen3":
        return {"gen_planes": host, "width": host.shape[-1] * 32}
    if repr_ == "gen8":
        return {"gen_state": host}
    if repr_ == "sparse":
        return {"sparse_words": host, "ox": int(extra["ox"]),
                "oy": int(extra["oy"]), "size": int(extra["size"])}
    if repr_ == "f32":
        # Continuous boards (Lenia) checkpoint their exact float32
        # state; a pixel quantization would corrupt the dynamics.
        return {"float_state": host.astype(np.float32, copy=False)}
    # u8 {0,1} cells -> the legacy {0,255} pixel format.
    return {"world": (host * np.uint8(255)).astype(np.uint8)}


def _alive_count(host: np.ndarray, repr_: str) -> int:
    """Firing population of the host payload — the manifest's second
    determinism marker, exact and representation-aware."""
    if repr_ in ("packed", "sparse"):
        return int(_POP8[host.view(np.uint8)].sum(dtype=np.int64))
    if repr_ == "gen3":
        return int(_POP8[host[0].view(np.uint8)].sum(dtype=np.int64))
    if repr_ == "gen8":
        return int((host == 1).sum(dtype=np.int64))
    if repr_ == "f32":
        from gol_tpu.models.lenia import ALIVE_THRESHOLD

        return int((host > ALIVE_THRESHOLD).sum(dtype=np.int64))
    return int(host.sum(dtype=np.int64))


class CheckpointWriter:
    def __init__(self, directory: str, run_id: str,
                 keep_last: int = 3, keep_every: int = 0) -> None:
        self.directory = directory
        self.run_id = run_id
        self.retention = RetentionPolicy(keep_last=keep_last,
                                         keep_every=keep_every)
        self._cv = threading.Condition()
        self._pending: Optional[Snapshot] = None
        self._busy = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self.last_manifest: Optional[str] = None
        self.last_error: Optional[BaseException] = None
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------ submit

    def submit(self, snap: Snapshot) -> bool:
        """Hand a snapshot to the background thread; returns False when
        it REPLACED an unwritten pending snapshot (counted as dropped).
        Never blocks beyond the condition lock."""
        with self._cv:
            if self._closed:
                raise RuntimeError("checkpoint writer is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="gol-ckpt-writer")
                self._thread.start()
            replaced = self._pending is not None
            self._pending = snap
            self._cv.notify_all()
        if replaced:
            obs.CKPT_WRITES.labels(status="dropped").inc()
        return not replaced

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait until the pending snapshot (if any) is durably written.
        True on drained, False on timeout."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cv:
            while self._pending is not None or self._busy:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(remaining)
        return True

    def close(self, timeout: Optional[float] = None) -> bool:
        """Flush then stop accepting snapshots. The daemon thread exits
        on its own once drained."""
        drained = self.flush(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        return drained

    # ----------------------------------------------------------- writing

    def write_sync(self, snap: Snapshot) -> str:
        """Write one checkpoint ON THIS THREAD (emergency/manual path);
        returns the manifest path. Raises on failure — synchronous
        callers (the Checkpoint wire method) need the error."""
        return self._write(snap)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait()
                if self._pending is None and self._closed:
                    return
                snap = self._pending
                self._pending = None
                self._busy = True
            try:
                self._write(snap)
            except Exception as e:
                # Periodic checkpointing must never kill the run it
                # exists to protect; the failure is counted, logged,
                # and kept for flush()-side inspection.
                self.last_error = e
                obs_log("ckpt.write_failed", level="error",
                        turn=snap.turn, error=f"{type(e).__name__}: {e}")
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _write(self, snap: Snapshot) -> str:
        t0 = time.monotonic()
        with obs_trace.span("ckpt.save",
                            attrs={"turn": snap.turn, "repr": snap.repr,
                                   "trigger": snap.trigger}) as span:
            try:
                path = self._write_inner(snap)
            except Exception:
                obs.CKPT_WRITES.labels(status="error").inc()
                raise
            finally:
                obs.CKPT_WRITE_SECONDS.observe(time.monotonic() - t0)
            span.attrs["path"] = os.path.basename(path)
        obs.CKPT_WRITES.labels(status="ok").inc()
        obs.CKPT_LAST_TURN.set(snap.turn)
        self.last_manifest = path
        return path

    def _write_inner(self, snap: Snapshot) -> str:
        host = _materialize(snap)
        arrays = payload_arrays(host, snap.repr, snap.extra)
        payload_member = next(v for v in arrays.values()
                              if hasattr(v, "nbytes"))
        save = (np.savez_compressed
                if payload_member.nbytes <= COMPRESS_LIMIT else np.savez)
        base = mf.ckpt_basename(snap.turn)
        payload_name = base + mf.PAYLOAD_SUFFIX
        payload = os.path.join(self.directory, payload_name)
        man_path = os.path.join(self.directory, base + mf.MANIFEST_SUFFIX)
        # One writer mutates a directory at a time (the run's background
        # writer vs a SIGTERM-handler write_sync on another thread):
        # publishes stay ordered and retention never sweeps mid-publish.
        with dir_lock(self.directory):
            fd, tmp = tempfile.mkstemp(prefix=payload_name + ".",
                                       suffix=".tmp", dir=self.directory)
            try:
                with os.fdopen(fd, "wb") as f:
                    save(f, turn=snap.turn, rulestring=snap.rule,
                         **arrays)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, payload)  # payload published FIRST
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            payload_bytes = os.path.getsize(payload)
            manifest = {
                "schema": mf.MANIFEST_SCHEMA,
                "run_id": self.run_id,
                "turn": int(snap.turn),
                "rule": snap.rule,
                "repr": snap.repr,
                "board": {"h": int(snap.board[0]),
                          "w": int(snap.board[1])},
                "dtype": str(payload_member.dtype),
                "shape": [int(s) for s in payload_member.shape],
                "payload": payload_name,
                "payload_sha256": mf.sha256_file(payload),
                "payload_bytes": int(payload_bytes),
                "board_sha256": mf.board_sha256(arrays),
                "alive": _alive_count(host, snap.repr),
                "trigger": snap.trigger,
                "created_unix": int(time.time()),
                "writer": _writer_ident(),
            }
            device = _device_ident()
            if device is not None:
                manifest["device"] = device
            mesh_geom = snap.mesh if snap.mesh else _mesh_ident()
            if mesh_geom:
                manifest["mesh"] = mesh_geom
            fuse_k = _fuse_ident()
            if fuse_k is not None:
                manifest["fuse"] = fuse_k
            if snap.extra:
                manifest["sparse"] = {k: int(v)
                                      for k, v in snap.extra.items()}
            jinfo = self._journal_digest(snap, manifest)
            if jinfo is not None:
                # The chain head rides the manifest: a restore/adoption
                # knows the newest journal state the checkpoint covers,
                # and a verifier can prove the file wasn't truncated.
                manifest["journal"] = jinfo
            mf.write_manifest(man_path, manifest)  # durability bit LAST
            obs.CKPT_BYTES.inc(payload_bytes)
            try:  # per-run storage attribution (PR 19), best-effort
                from gol_tpu.obs import usage as obs_usage
                obs_usage.METER.charge_ckpt(
                    self.run_id or "run0", payload_bytes)
            except Exception:
                pass
            self.retention.apply(self.directory, locked=True)
        return man_path

    def _journal_digest(self, snap: Snapshot,
                        manifest: dict) -> Optional[dict]:
        """Journal one board-digest event for this checkpoint and return
        the chain head to stamp into the manifest, or None while the run
        isn't journaling. Runs on the writer/pool thread — the board
        hash was already computed for the manifest, so the journal rides
        the checkpoint for free (the fleet's bounded writer pool is the
        only thread that ever touches a resident's journal digests)."""
        try:
            from gol_tpu import journal as journal_mod

            jw = journal_mod.get(self.run_id)
            if jw is None:
                return None
            jw.digest(snap.turn, manifest["board_sha256"],
                      repr_=snap.repr, trigger=snap.trigger,
                      alive=manifest["alive"])
            return jw.head_info()
        except Exception:  # journaling must never sink a checkpoint
            return None


# Shared fleet pool sizing: a couple of workers keep up with hundreds
# of residents (writes are newest-wins per run, so backlog collapses to
# one snapshot per run regardless of cadence pressure).
POOL_WRITERS_ENV = "GOL_FLEET_CKPT_WRITERS"
POOL_WRITERS_DEFAULT = 2


class CheckpointWriterPool:
    """Bounded shared writer pool for fleet cadence checkpoints.

    One CheckpointWriter per run means one daemon thread and one
    double buffer per resident — 512 residents would hold 512 writer
    threads. The pool replaces that with a FIXED set of worker threads
    draining a per-run newest-wins pending map in round-robin order
    (FIFO over distinct run_ids: every resident resubmitting each
    cadence gets served once per rotation, no run can starve another).
    A resubmit before a run's turn comes REPLACES its pending snapshot
    and counts as `gol_ckpt_writes_total{status="dropped"}` — the same
    double-buffer semantics as the per-run writer, bounded centrally.

    Writes reuse the CheckpointWriter pipeline via per-run thread-LESS
    writer cores (a core only starts a thread on `submit`, which the
    pool never calls): same atomic publish, retention, and manifest
    format bit for bit."""

    def __init__(self, workers: Optional[int] = None) -> None:
        if workers is None:
            try:
                workers = int(os.environ.get(POOL_WRITERS_ENV, "") or
                              POOL_WRITERS_DEFAULT)
            except ValueError:
                workers = POOL_WRITERS_DEFAULT
        self.workers = max(1, int(workers))
        self._cv = threading.Condition()
        self._pending: dict = {}      # run_id -> (core, Snapshot)
        self._order: list = []        # distinct run_ids, FIFO rotation
        self._cores: dict = {}        # run_id -> CheckpointWriter core
        self._busy = 0
        self._closed = False
        self._threads: list = []

    # ------------------------------------------------------------ submit

    def submit(self, directory: str, run_id: str, snap: Snapshot,
               keep_last: int = 3, keep_every: int = 0) -> bool:
        """Queue one run's cadence snapshot; returns False when it
        replaced that run's unwritten pending snapshot (metered as
        dropped). Never blocks beyond the condition lock."""
        with self._cv:
            if self._closed:
                raise RuntimeError("checkpoint writer pool is closed")
            self._ensure_threads()
            core = self._cores.get(run_id)
            if core is None or core.directory != directory:
                core = CheckpointWriter(directory, run_id,
                                        keep_last=keep_last,
                                        keep_every=keep_every)
                self._cores[run_id] = core
            replaced = run_id in self._pending
            self._pending[run_id] = (core, snap)
            if not replaced:
                self._order.append(run_id)
            obs.CKPT_POOL_DEPTH.set(len(self._pending))
            self._cv.notify()
        if replaced:
            obs.CKPT_WRITES.labels(status="dropped").inc()
        return not replaced

    def forget(self, run_id: str) -> None:
        """Drop a removed run's writer core. A pending snapshot is NOT
        cancelled — it drains normally (matching the flush-then-close
        semantics of the per-run writer this pool replaces)."""
        with self._cv:
            self._cores.pop(run_id, None)

    def depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait until every pending snapshot is durably written. True
        on drained, False on timeout."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cv:
            while self._pending or self._busy:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(remaining)
        return True

    def close(self, timeout: Optional[float] = None) -> bool:
        """Flush then stop accepting snapshots; workers exit drained."""
        drained = self.flush(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        return drained

    # ----------------------------------------------------------- workers

    def _ensure_threads(self) -> None:
        while len(self._threads) < self.workers:
            t = threading.Thread(
                target=self._worker, daemon=True,
                name=f"gol-ckpt-pool-{len(self._threads)}")
            self._threads.append(t)
            t.start()
        obs.CKPT_POOL_WRITERS.set(len(self._threads))

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._order and not self._closed:
                    self._cv.wait()
                if not self._order:
                    return  # closed and drained
                run_id = self._order.pop(0)
                core, snap = self._pending.pop(run_id)
                obs.CKPT_POOL_DEPTH.set(len(self._pending))
                self._busy += 1
            try:
                core._write(snap)
            except Exception as e:
                # Cadence checkpointing must never kill the runs it
                # protects; counted inside _write, logged here.
                obs_log("ckpt.pool_write_failed", level="error",
                        run_id=run_id, turn=snap.turn,
                        error=f"{type(e).__name__}: {e}")
            finally:
                with self._cv:
                    self._busy -= 1
                    self._cv.notify_all()


def _writer_ident() -> dict:
    ident = {"pid": os.getpid()}
    try:
        import jax
        import jaxlib

        ident["jax"] = jax.__version__
        ident["jaxlib"] = jaxlib.__version__
    except Exception:  # version probing must never sink a checkpoint
        pass
    ident["numpy"] = np.__version__
    return ident


def _mesh_ident() -> Optional[dict]:
    """Mesh geometry of the run being checkpointed (the engine stamps
    it at submit via devstats.note_mesh), or None before any sharded
    submit. read_manifest tolerates extra keys, so old readers skip it."""
    try:
        from gol_tpu.obs import devstats

        mesh = devstats.mesh_fields()
    except Exception:  # telemetry must never sink a checkpoint
        return None
    return mesh or None


def _fuse_ident() -> Optional[int]:
    """Temporal-fusion depth of the run being checkpointed (the engine
    stamps it at submit via devstats.note_fuse); None when unfused so
    legacy manifests stay byte-identical. read_manifest tolerates extra
    keys, so old readers skip it."""
    try:
        from gol_tpu.obs import devstats

        fuse_k = devstats.fuse_field()
    except Exception:  # telemetry must never sink a checkpoint
        return None
    return fuse_k if fuse_k > 1 else None


def _device_ident() -> Optional[dict]:
    """Device kind + memory footprint for the manifest, or None.

    read_manifest tolerates extra keys, so old readers skip this block;
    memory fields appear only where the backend reports stats."""
    try:
        from gol_tpu.obs import devstats

        snap = devstats.poll_device_memory()
    except Exception:  # telemetry must never sink a checkpoint
        return None
    if snap["device_kind"] is None:
        return None
    ident = {"kind": snap["device_kind"], "devices": snap["devices"]}
    if snap["supported"]:
        ident["live_bytes"] = snap["live_bytes"]
        ident["peak_bytes"] = snap["peak_bytes"]
    return ident
