"""Reshard-at-restore: resume any checkpoint onto any geometry.

Manifests have recorded placement geometry since PR 9/11 (the mesh
stamp) and the representation since PR 3 (`repr`), but restore always
assumed the writing process and the resuming process agreed on both:
a packed-words payload written on a 4-way mesh only loaded back onto a
4-way mesh, a sparse window only onto a same-size sparse torus, and a
fleet-bucket payload only into an identically shaped slot. This module
makes geometry *mutable* at the restore boundary:

* `restore_delta(manifest, engine)` names every way the checkpoint's
  recorded geometry disagrees with the engine that wants to load it
  (mesh device count, representation family, sparse torus size). A
  non-empty delta without an explicit reshard request is refused with
  `GeometryMismatch` — tagged `rpc_error_kind="geometry"` so the wire
  layer surfaces a diagnosable `geometry:` error instead of a shape
  crash deep in the install path.

* `reshard_into(engine, manifest, payload)` is the host-side repack:
  decode the payload to a canonical board (exact, bit-identical — no
  resampling, the board IS the state), then re-encode it in whatever
  npz dialect the target engine's own `load_checkpoint` verifies and
  installs. The target engine splits the board across ITS devices on
  install, which is what makes a 4-way checkpoint resume on 1/2/8-way
  without a bit of drift: the torus is device-count-invariant, only
  the halo partitioning changes.

Canonical decode covers every repr the writer emits
(`ckpt/writer.py::payload_arrays`): packed `words`, raw `world`
pixels, Generations `gen_planes`/`gen_state`, and the sparse
window-words form (embedded into its full torus with wraparound).
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

import numpy as np

from gol_tpu.obs.log import log as obs_log


class GeometryMismatch(ValueError):
    """Checkpoint geometry disagrees with the resuming engine and no
    reshard was requested. Tagged so server.py can answer with a
    `geometry:` error the client maps back to a tagged exception."""

    rpc_error_kind = "geometry"


class Canonical:
    """One checkpoint decoded to its exact host-side state.

    kind is "life" ({0,1} board01), "gen" (Generations state bytes),
    "float" (continuous float32 state — Lenia, PR 20) or "pixels"
    (raw u8 pixels whose interpretation the target engine's rule
    decides — the legacy `world` member round-trips verbatim)."""

    __slots__ = ("kind", "board", "turn", "rule")

    def __init__(self, kind: str, board: np.ndarray, turn: int,
                 rule: Optional[str]) -> None:
        self.kind = kind
        self.board = board
        self.turn = int(turn)
        self.rule = rule


def _words_to_board(words: np.ndarray, h: int, w: int) -> np.ndarray:
    from gol_tpu.ops.bitpack import unpack_np, words_bytes_np

    return unpack_np(words_bytes_np(np.asarray(words)), h, w)


def _board_to_words(board01: np.ndarray) -> np.ndarray:
    from gol_tpu.ops.bitpack import pack_np

    return pack_np(np.ascontiguousarray(board01)).view("<u4")


def load_canonical(payload_path: str) -> Canonical:
    """Decode any writer payload (or legacy autosave npz) to canonical
    host state. Pure host-side numpy — bit-exact by construction."""
    with np.load(payload_path) as z:
        turn = int(z["turn"]) if "turn" in z else 0
        rule = str(z["rulestring"]) if "rulestring" in z else None
        if "sparse_words" in z:
            sw = np.ascontiguousarray(z["sparse_words"], dtype=np.uint32)
            size = int(z["size"])
            ox, oy = int(z["ox"]), int(z["oy"])
            if sw.ndim != 2:
                raise ValueError("sparse_words must be 2-D")
            win = _words_to_board(sw, sw.shape[0], sw.shape[1] * 32)
            board = np.zeros((size, size), dtype=np.uint8)
            rows = (np.arange(win.shape[0]) + oy) % size
            cols = (np.arange(win.shape[1]) + ox) % size
            board[np.ix_(rows, cols)] = win
            return Canonical("life", board, turn, rule)
        if "gen_planes" in z:
            planes = np.asarray(z["gen_planes"], dtype=np.uint32)
            width = int(z["width"])
            if planes.ndim != 3 or planes.shape[0] != 2:
                raise ValueError("gen_planes must be (2, h, words)")
            h = planes.shape[1]
            state = (_words_to_board(planes[0], h, width)
                     + 2 * _words_to_board(planes[1], h, width)
                     ).astype(np.uint8)
            return Canonical("gen", state, turn, rule)
        if "gen_state" in z:
            state = np.ascontiguousarray(z["gen_state"], dtype=np.uint8)
            if state.ndim != 2:
                raise ValueError("gen_state must be 2-D")
            return Canonical("gen", state, turn, rule)
        if "float_state" in z:
            state = np.ascontiguousarray(z["float_state"],
                                         dtype=np.float32)
            if state.ndim != 2:
                raise ValueError("float_state must be 2-D")
            return Canonical("float", state, turn, rule)
        if "words" in z:
            words = np.ascontiguousarray(z["words"], dtype=np.uint32)
            width = int(z["width"])
            if words.ndim != 2 or words.shape[-1] * 32 != width:
                raise ValueError(
                    f"words shape {words.shape} inconsistent with "
                    f"width {width}")
            board = _words_to_board(words, words.shape[0], width)
            return Canonical("life", board, turn, rule)
        if "world" in z:
            world = np.ascontiguousarray(z["world"], dtype=np.uint8)
            if world.ndim != 2:
                raise ValueError("world must be 2-D")
            return Canonical("pixels", world, turn, rule)
    raise ValueError(
        f"{payload_path}: no decodable payload member (expected one of "
        f"sparse_words / gen_planes / gen_state / float_state / words / "
        f"world)")


def board01_of(can: Canonical) -> np.ndarray:
    """Canonical state as a {0,1} uint8 board (life-like kinds only)."""
    if can.kind == "life":
        return can.board
    if can.kind == "pixels":
        return (can.board != 0).astype(np.uint8)
    if can.kind == "float":
        raise GeometryMismatch(
            "continuous float state has no binary-board form; restore "
            "it onto an engine running its own (Lenia) rule")
    raise GeometryMismatch(
        "Generations state has no binary-board form; reshard it onto a "
        "Generations engine with the same rule family")


# -- engine geometry contract ------------------------------------------

def engine_geometry(engine) -> Optional[dict]:
    """The engine's declared geometry (duck-typed `geometry()`), or
    None when the engine predates the contract — then nothing is
    enforced and restore behaves exactly as before this module."""
    fn = getattr(engine, "geometry", None)
    if fn is None:
        return None
    return fn()


def restore_delta(manifest: dict, engine) -> List[str]:
    """Every way `manifest`'s recorded geometry disagrees with
    `engine`. Empty list = the direct payload load is already correct.
    Board height/width are deliberately NOT a delta for dense/fleet
    engines: their install paths have always adopted the checkpoint's
    shape, and refusing that now would regress working resumes."""
    geo = engine_geometry(engine)
    if geo is None:
        return []
    deltas: List[str] = []
    mrepr = str(manifest.get("repr", ""))
    kind = geo.get("kind")
    if kind == "sparse":
        if mrepr != "sparse":
            deltas.append(f"repr {mrepr} -> sparse engine")
        else:
            extra = manifest.get("sparse") or {}
            msize = int(extra.get("size",
                                  manifest.get("board", {}).get("h", 0)))
            if msize != int(geo.get("size", msize)):
                deltas.append(f"sparse torus {msize} -> "
                              f"{geo.get('size')}")
    elif mrepr == "sparse":
        deltas.append(f"repr sparse -> {kind} engine")
    mdev = (manifest.get("mesh") or {}).get("devices")
    gdev = geo.get("devices")
    if mdev and gdev and int(mdev) != int(gdev):
        deltas.append(f"mesh devices {mdev} -> {gdev}")
    # Cell-dtype family (PR 20): a float32 (Lenia) payload must not be
    # bit-reinterpreted into a binary engine or vice versa. The
    # manifest's dtype is the PAYLOAD dtype (uint32 words for packed),
    # so the comparison is float-vs-integer family, not exact dtype.
    mdtype = str(manifest.get("dtype", ""))
    gdtype = str(geo.get("dtype", ""))
    if mdtype and gdtype and \
            mdtype.startswith("float") != gdtype.startswith("float"):
        deltas.append(f"cell dtype {mdtype} -> {gdtype}")
    return deltas


# -- the repack itself -------------------------------------------------

def write_repacked(can: Canonical, engine, out_path: str) -> None:
    """Re-encode canonical state into the npz dialect the target
    engine's `load_checkpoint` accepts, at `out_path` (atomic via the
    caller's temp-file discipline)."""
    geo = engine_geometry(engine) or {}
    kind = geo.get("kind")
    meta = {"turn": np.int64(can.turn)}
    if can.rule is not None:
        meta["rulestring"] = np.str_(can.rule)
    if kind == "sparse":
        board = board01_of(can)
        size = int(geo.get("size", 0))
        if board.shape != (size, size):
            raise GeometryMismatch(
                f"board {board.shape[0]}x{board.shape[1]} cannot "
                f"reshard onto a {size}-torus sparse engine (the torus "
                f"size is fixed at construction)")
        np.savez(out_path, sparse_words=_board_to_words(board),
                 ox=np.int64(0), oy=np.int64(0), size=np.int64(size),
                 **meta)
        return
    if can.kind == "gen":
        np.savez(out_path, gen_state=can.board, **meta)
        return
    if can.kind == "float":
        # The float board is placement-invariant state; the target
        # engine's own load_checkpoint enforces that its rule family
        # can actually hold it.
        np.savez(out_path, float_state=can.board, **meta)
        return
    if can.kind == "pixels":
        np.savez(out_path, world=can.board, **meta)
        return
    # Life board01 -> legacy world pixels: the one dialect every dense
    # and fleet install path accepts at any width; the engine re-packs
    # to words itself when its representation choice says so.
    np.savez(out_path, world=(can.board * np.uint8(255)), **meta)


def reshard_into(engine, manifest: Optional[dict],
                 payload_path: str) -> int:
    """Decode `payload_path`, repack for `engine`, install through the
    engine's own verified `load_checkpoint`. Returns the restored turn
    (always the checkpoint's turn — resharding never advances time)."""
    can = load_canonical(payload_path)
    fd, tmp = tempfile.mkstemp(suffix=".npz", prefix="gol-reshard-")
    os.close(fd)
    try:
        write_repacked(can, engine, tmp)
        turn = engine.load_checkpoint(tmp)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    geo = engine_geometry(engine) or {}
    obs_log("ckpt.resharded", kind=can.kind, turn=turn,
            devices=geo.get("devices"), engine=geo.get("kind"),
            payload=os.path.basename(payload_path))
    return turn
