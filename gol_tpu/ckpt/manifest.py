"""`gol-ckpt/1` manifests: the durability + integrity contract.

A checkpoint is DURABLE iff its manifest exists — the payload `.npz` is
published first (tmp+fsync+rename), the manifest second (same dance),
so readers ordering on manifests can never observe a manifest whose
payload is missing or torn, and a crash between the two publishes
leaves only an orphan payload that retention GC sweeps. Each manifest
records the SHA-256 of the payload FILE (integrity: a flipped bit or a
truncation is refused at restore) and a canonical SHA-256 of the BOARD
bytes (determinism marker: two runs that agree on a turn agree on this
hash regardless of compression or container layout — the bit-identical
resume contract's checkable form). No RNG state is recorded because the
system has none: evolution is a pure function of (board, rule, turns),
which is exactly what makes kill→resume→compare testable.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Iterator, Optional

MANIFEST_SCHEMA = "gol-ckpt/1"
MANIFEST_SUFFIX = ".json"
PAYLOAD_SUFFIX = ".npz"
CKPT_PREFIX = "ckpt-"
# Zero-padded turn in names: lexicographic order == turn order, so a
# directory listing is already the checkpoint timeline.
_TURN_DIGITS = 12

# Representations a manifest may declare: the dense engine's four (see
# engine.py `_repr`) plus the sparse engine's window state.
KNOWN_REPRS = ("packed", "u8", "gen8", "gen3", "sparse", "f32")


class CheckpointIntegrityError(ValueError):
    """A manifest or payload failed validation (hash mismatch, missing
    payload, malformed schema). Typed so restore paths can refuse loudly
    while callers distinguish 'corrupt' from 'absent'."""


def ckpt_basename(turn: int) -> str:
    return f"{CKPT_PREFIX}{turn:0{_TURN_DIGITS}d}"


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def board_sha256(arrays: dict) -> str:
    """Canonical board hash: the payload arrays' raw bytes in sorted key
    order, shape-prefixed. Container-independent (compression level,
    npz member order, scalar metadata do not affect it) — the manifest's
    determinism marker."""
    h = hashlib.sha256()
    for key in sorted(arrays):
        v = arrays[key]
        if not hasattr(v, "tobytes"):
            continue  # scalars (width) ride the payload, not the hash
        h.update(key.encode())
        h.update(repr((v.dtype.str, v.shape)).encode())
        h.update(v.tobytes(order="C"))
    return h.hexdigest()


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp + fsync + rename publish in `path`'s directory: after this
    returns, `path` is either the complete new content or (on a crash
    mid-call) untouched — never a prefix."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                               suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def write_manifest(path: str, manifest: dict) -> None:
    if manifest.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"manifest schema must be {MANIFEST_SCHEMA!r}")
    atomic_write_bytes(
        path, (json.dumps(manifest, sort_keys=True, indent=1) + "\n")
        .encode())


_REQUIRED = {
    "schema": str, "run_id": str, "turn": int, "rule": str,
    "repr": str, "payload": str, "payload_sha256": str,
    "payload_bytes": int, "board_sha256": str,
}


def read_manifest(path: str) -> dict:
    """Parse + structurally validate one manifest. Raises
    CheckpointIntegrityError on anything malformed — a resume must
    never half-trust a manifest."""
    try:
        with open(path, encoding="utf-8") as f:
            m = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(f"{path}: unreadable manifest: "
                                       f"{e}") from e
    if not isinstance(m, dict):
        raise CheckpointIntegrityError(f"{path}: manifest is not an object")
    if m.get("schema") != MANIFEST_SCHEMA:
        raise CheckpointIntegrityError(
            f"{path}: schema {m.get('schema')!r} != {MANIFEST_SCHEMA!r}")
    for key, typ in _REQUIRED.items():
        v = m.get(key)
        if not isinstance(v, typ) or (typ is int and isinstance(v, bool)):
            raise CheckpointIntegrityError(
                f"{path}: field {key!r} missing or not {typ.__name__}")
    if m["repr"] not in KNOWN_REPRS:
        raise CheckpointIntegrityError(
            f"{path}: unknown repr {m['repr']!r} "
            f"(known: {', '.join(KNOWN_REPRS)})")
    if m["turn"] < 0:
        raise CheckpointIntegrityError(f"{path}: negative turn")
    if os.path.basename(m["payload"]) != m["payload"]:
        # The payload reference is a sibling basename by construction; a
        # path component would let a tampered manifest point a verifying
        # reader at an arbitrary file.
        raise CheckpointIntegrityError(
            f"{path}: payload {m['payload']!r} is not a bare filename")
    board = m.get("board")
    if board is not None and (
            not isinstance(board, dict)
            or not isinstance(board.get("h"), int)
            or not isinstance(board.get("w"), int)):
        raise CheckpointIntegrityError(f"{path}: malformed board dims")
    return m


def payload_path(manifest_path: str, manifest: dict) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(manifest_path)),
                        manifest["payload"])


def verify_manifest(manifest_path: str,
                    manifest: Optional[dict] = None) -> dict:
    """Full integrity check: parse/validate the manifest, then recompute
    the payload file's SHA-256 and compare. Returns the manifest dict.
    Raises CheckpointIntegrityError on any mismatch — the refusal the
    kill→resume contract requires for corrupted checkpoints."""
    m = manifest if manifest is not None else read_manifest(manifest_path)
    p = payload_path(manifest_path, m)
    if not os.path.exists(p):
        raise CheckpointIntegrityError(
            f"{manifest_path}: payload {m['payload']!r} is missing")
    size = os.path.getsize(p)
    if size != m["payload_bytes"]:
        raise CheckpointIntegrityError(
            f"{manifest_path}: payload is {size} bytes, manifest says "
            f"{m['payload_bytes']}")
    digest = sha256_file(p)
    if digest != m["payload_sha256"]:
        raise CheckpointIntegrityError(
            f"{manifest_path}: payload SHA-256 mismatch "
            f"({digest[:12]}… != {m['payload_sha256'][:12]}…) — "
            f"refusing a corrupted checkpoint")
    return m


def list_checkpoints(directory: str,
                     strict: bool = False) -> Iterator[tuple]:
    """Yield (turn, manifest_path, manifest) for every DURABLE checkpoint
    in `directory`, turn-ascending. Malformed manifests are skipped
    (strict=False: a directory shared with a crashed writer must still
    resume from its good checkpoints) or raised (strict=True: the
    inspect tool's audit mode)."""
    try:
        names = sorted(os.listdir(directory))
    except FileNotFoundError:
        return
    for name in names:
        if not (name.startswith(CKPT_PREFIX)
                and name.endswith(MANIFEST_SUFFIX)):
            continue
        path = os.path.join(directory, name)
        try:
            m = read_manifest(path)
        except CheckpointIntegrityError:
            if strict:
                raise
            continue
        yield m["turn"], path, m


def latest_checkpoint(directory: str) -> Optional[tuple]:
    """(turn, manifest_path, manifest) of the newest durable checkpoint,
    or None. Newest by TURN (names sort the same way by construction)."""
    best = None
    for item in list_checkpoints(directory):
        if best is None or item[0] >= best[0]:
            best = item
    return best
