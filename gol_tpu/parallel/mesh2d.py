"""2-D device mesh: board sharded over rows AND packed-word columns.

The 1-D row sharding (`parallel/mesh.py`) stops scaling when the shard
height approaches the halo depth, and its halo volume is O(W) per link.
For pod-scale boards (65536² over dozens of chips, BASELINE config 4) the
2-D mesh shards both axes: halo volume per link drops to the shard
*perimeter*, and the chip count is no longer bounded by the row count.

Packed-word geometry makes the horizontal halo cheap: a deep halo of up to
32 cells is exactly ONE uint32 word column. Each macro-step exchanges
T ≤ 32 turns worth of halo — T rows vertically (ppermute along "rows"),
one word column horizontally (ppermute along "cols", taken from the
row-extended window so the corners ride along) — then advances T turns
with zero communication, per the same corruption-front argument as the
1-D deep-halo path (`parallel/halo.py`): window-edge corruption moves one
cell per turn and exactly consumes the halo.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from gol_tpu.parallel.shmap import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule
from gol_tpu.parallel.halo import dispatch_obs, inner_kind
from gol_tpu.parallel.mesh import ROWS_AXIS

COLS_AXIS = "cols"

# T ≤ 32 so one word column covers the horizontal halo; T ≤ shard_rows so
# the vertical halo comes from the adjacent shard only.
MAX_T_2D = 16


def macro_T_2d(shard_rows: int, fuse: int = 0) -> int:
    """Macro depth for one 2-D dispatch: the native MAX_T_2D cap, or the
    pinned fuse depth k when set — still hard-capped at 32 (the one-word
    horizontal halo protects at most 32 turns of corruption) and at the
    shard height. `parallel/halo.halo_traffic` mirrors this exactly for
    the analytic counters — change both together."""
    if fuse > 1:
        return min(fuse, 2 * MAX_T_2D, shard_rows)
    return min(MAX_T_2D, shard_rows)


def make_mesh2d(
    shape: Tuple[int, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(rows_shards, cols_shards) mesh with axes ('rows', 'cols')."""
    devices = list(devices if devices is not None else jax.devices())
    r, c = shape
    if r * c > len(devices):
        raise ValueError(
            f"mesh {r}x{c} needs {r * c} devices, have {len(devices)}")
    grid = np.array(devices[: r * c]).reshape(r, c)
    return Mesh(grid, (ROWS_AXIS, COLS_AXIS))


def board_sharding2d(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROWS_AXIS, COLS_AXIS))


def shard_board2d(packed: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a packed (H, Wp) board on the 2-D mesh."""
    return jax.device_put(packed, board_sharding2d(mesh))


def _macro_2d(
    local: jax.Array,
    n_rows: int,
    n_cols: int,
    rule: LifeLikeRule,
    T: int,
    inner: str,
):
    """One T-turn macro-step of one (rows, wcols) shard."""
    from gol_tpu.parallel.halo import exchange_halos, run_window

    # Vertical: T rows from the ring neighbours above/below.
    top, bot = exchange_halos(local, n_rows, ROWS_AXIS, depth=T, axis=0)
    tall = jnp.concatenate([top, local, bot], axis=0)
    # Horizontal: one word column from the left/right ring neighbours,
    # taken from the row-extended window so corners are included.
    west, east = exchange_halos(tall, n_cols, COLS_AXIS, depth=1, axis=1)
    window = jnp.concatenate([west, tall, east], axis=1)
    return run_window(window, T, rule, inner)[T:-T, 1:-1]


@functools.lru_cache(maxsize=128)
def _make_compiled_run2d(
    mesh: Mesh, rule: LifeLikeRule, T: int, inner: str
):
    n_rows = mesh.shape[ROWS_AXIS]
    n_cols = mesh.shape[COLS_AXIS]
    spec = P(ROWS_AXIS, COLS_AXIS)

    @functools.partial(jax.jit, static_argnames=("num_macros",))
    def run(packed: jax.Array, num_macros: int) -> jax.Array:
        @functools.partial(
            shard_map, mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False,
        )
        def run_local(local):
            def body(p, _):
                return _macro_2d(p, n_rows, n_cols, rule, T, inner), None
            out, _ = lax.scan(body, local, None, length=num_macros)
            return out

        return run_local(packed)

    return run


def sharded_packed_run_turns_2d(
    packed: jax.Array,
    num_turns: int,
    mesh: Mesh,
    rule: LifeLikeRule = CONWAY,
    fuse: int = 0,
) -> jax.Array:
    """Advance a 2-D-sharded packed board `num_turns` turns.

    Requirements: mesh axes ('rows', 'cols'); board divisible by the mesh.
    A single word column per shard is fine — the 32-bit halo word protects
    up to 32 turns of corruption regardless of shard width. Turn counts
    are decomposed as full `macro_T_2d` macros plus one shallower
    remainder macro — any T ≥ 1 is valid here, so every count works.
    `fuse` pins the macro depth (one exchange round per k turns,
    capped at 32 by the one-word horizontal halo)."""
    n_rows = mesh.shape[ROWS_AXIS]
    n_cols = mesh.shape[COLS_AXIS]
    h, wp = packed.shape
    if h % n_rows or wp % n_cols:
        raise ValueError(
            f"board {packed.shape} not divisible by mesh "
            f"{n_rows}x{n_cols}")
    shard_rows, shard_cols = h // n_rows, wp // n_cols
    T = macro_T_2d(shard_rows, fuse)
    inner = inner_kind(mesh, (shard_rows + 2 * T, shard_cols + 2), T)
    run = _make_compiled_run2d(mesh, rule, T, inner)
    full, rem = divmod(num_turns, T)
    # dispatch_obs routes 2-D traffic by the mesh's cols axis; the one
    # span covers both the full-depth macros and the remainder macro.
    with dispatch_obs("packed", packed, num_turns, mesh, fuse):
        out = run(packed, full)
        if rem:
            # The remainder window has a DIFFERENT height and depth —
            # re-pick the inner engine for it (a height whose banded
            # band sizing worked at depth T may have no viable band at
            # depth rem).
            inner_rem = inner_kind(
                mesh, (shard_rows + 2 * rem, shard_cols + 2), rem)
            out = _make_compiled_run2d(mesh, rule, rem, inner_rem)(out, 1)
        return out


@functools.lru_cache(maxsize=32)
def fused_run_fn_2d(fuse: int):
    """Stable-identity 2-D run callable pinning the fuse depth — the
    2-D sibling of `parallel/halo.fused_run_fn` (same jit-cache
    staleness rationale)."""
    def run(cells, num_turns, mesh, rule=CONWAY):
        return sharded_packed_run_turns_2d(
            cells, num_turns, mesh, rule, fuse=fuse)

    return run
