from gol_tpu.parallel.mesh import (
    ROWS_AXIS,
    board_sharding,
    make_mesh,
    resolve_shard_count,
)
from gol_tpu.parallel.halo import sharded_run_turns, shard_board

__all__ = [
    "ROWS_AXIS",
    "board_sharding",
    "make_mesh",
    "resolve_shard_count",
    "sharded_run_turns",
    "shard_board",
]
