"""Device-mesh construction and shard-count policy.

The TPU-native replacement for the reference's worker topology: where the Go
broker dials a list of worker addresses and row-strips the board across them
(`Server/gol/distributor.go:85-120`), we lay a 1-D `jax.sharding.Mesh` over
the available chips and shard the board's row axis. The worker-address list
(`SUB` env, `Local/gol/distributor.go:100-105`) maps to a *requested shard
count*; goroutine `Threads` parallelism is subsumed by XLA within a chip.

Non-divisible heights: the reference spreads `H mod N` remainder rows across
the first strips (`Server:106-116`). The ENGINE serves such requests at the
exact count via wrap-extension (`parallel/halo.py`, r4/r5 — no divisor
fallback remains there, for any rule family); `resolve_shard_count` below is
the divisor POLICY utility for callers that want equal shards without the
extension's per-turn seam traffic (the bench harness sharding a board over
however many devices exist; all benchmark boards are powers of two, where
the downgrade is the identity).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS_AXIS = "rows"

# Fleet batch axis: a bucket's padded (cap, hb, wpb) batch is split over
# the mesh along the SLOT axis — embarrassingly parallel, zero halo
# traffic (the packed stencil rolls only along the trailing board axes).
SLOTS_AXIS = "slots"


def resolve_shard_count(height: int, requested: int) -> int:
    """Largest n ≤ requested with height % n == 0 (and n ≥ 1). A downgrade
    (non-divisor request, e.g. 7 shards for a 512-row board) is served at
    the reduced count and warned about. NOT used by the engine (which
    serves non-divisor requests exactly via wrap-extension); this is the
    equal-shards policy for direct kernel users like the bench harness."""
    if requested < 1:
        raise ValueError(f"shard request must be >= 1, got {requested}")
    n = max(1, min(requested, height))
    while height % n != 0:
        n -= 1
    if n != requested:
        import warnings

        warnings.warn(
            f"shard request {requested} downgraded to {n}: board height "
            f"{height} is not divisible by {requested}",
            stacklevel=2,
        )
    return n


def make_mesh(
    num_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D mesh over the first `num_shards` devices, axis name 'rows'."""
    devices = list(devices if devices is not None else jax.devices())
    n = num_shards if num_shards is not None else len(devices)
    if n > len(devices):
        raise ValueError(f"asked for {n} shards, have {len(devices)} devices")
    return Mesh(np.array(devices[:n]), (ROWS_AXIS,))


def board_sharding(mesh: Mesh) -> NamedSharding:
    """Board rows split over the mesh, columns replicated."""
    return NamedSharding(mesh, P(ROWS_AXIS, None))


def make_batch_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D mesh over the first `num_devices` devices, axis name 'slots' —
    the fleet-bucket batch mesh (`fleet/buckets.py`). Kept distinct from
    `make_mesh` so geometry stamps and jit caches can't confuse a
    batch-axis placement with a row-sharded one."""
    devices = list(devices if devices is not None else jax.devices())
    n = num_devices if num_devices is not None else len(devices)
    if n > len(devices):
        raise ValueError(
            f"asked for {n} batch shards, have {len(devices)} devices")
    return Mesh(np.array(devices[:n]), (SLOTS_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Bucket slots split over the mesh, each board fully on one device."""
    return NamedSharding(mesh, P(SLOTS_AXIS, None, None))


def mesh_geometry(mesh: Mesh) -> dict:
    """JSON-able geometry of a mesh: device count, shard count (equal
    for the 1-D and 2-D meshes built here — every device holds one
    shard), axis sizes by name, and the grid shape. The one dict every
    obs surface stamps: /healthz `mesh`, run-report `run_start`,
    checkpoint manifests, and bench detail records."""
    axes = {str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}
    return {
        "devices": int(mesh.size),
        "shards": int(mesh.size),
        "axes": axes,
        "shape": [int(s) for s in mesh.devices.shape],
    }
