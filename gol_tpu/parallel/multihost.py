"""Multi-host (multi-process) engine initialization.

The reference scales across hosts by deploying broker/worker binaries on
separate AWS nodes wired with net/rpc addresses (`SUB` env,
`Local/gol/distributor.go:100-105`). The TPU-native equivalent is JAX
multi-process SPMD: every engine host calls `initialize()` (same program,
different `process_id`), after which `jax.devices()` spans the whole pod
and the meshes built by `parallel/mesh.py` / `parallel/mesh2d.py` lay
shards across hosts — ppermute halos ride ICI within a slice and DCN
between slices, with no change to any kernel or engine code.

Environment mapping (mirrors the reference's `SER`/`SUB` env config):

    GOL_COORDINATOR   coordinator address host:port (falls back to
                      JAX_COORDINATOR_ADDRESS; neither set = single-host)
    GOL_NUM_PROCS     number of engine processes
    GOL_PROC_ID       this process's id (0-based)
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join (or skip joining) the multi-host engine cluster.

    Arguments fall back to GOL_COORDINATOR / GOL_NUM_PROCS / GOL_PROC_ID.
    Returns True when running multi-process after the call, False when no
    coordinator is configured (single-host mode — a no-op, the common
    localhost/test story). Safe to call twice."""
    global _initialized
    if _initialized:
        return True
    coordinator_address = (
        coordinator_address
        or os.environ.get("GOL_COORDINATOR", "")
        or os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    )
    if not coordinator_address:
        return False
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("GOL_NUM_PROCS", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("GOL_PROC_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def is_multihost() -> bool:
    return jax.process_count() > 1
