"""`shard_map` across JAX versions — the ONE import site for every
sharded kernel tier (`parallel/halo.py`, `parallel/mesh2d.py`).

Newer JAX exports `jax.shard_map` at top level with the replication
check spelled `check_vma`; older installs (≤0.4.x, e.g. the 0.4.37 in
the CPU CI image) carry the same transform as
`jax.experimental.shard_map.shard_map` with the flag's pre-rename
spelling `check_rep`. Call sites use the modern spelling; this wrapper
translates so one codebase runs on either."""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # pre-export JAX: the experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f=None, **kwargs):
    """`jax.shard_map`-compatible signature (callable first, config as
    keywords — the `functools.partial(shard_map, mesh=..., ...)`
    decorator pattern). Translates `check_vma` to the installed
    version's spelling."""
    if "check_vma" in kwargs and _CHECK_KW != "check_vma":
        kwargs[_CHECK_KW] = kwargs.pop("check_vma")
    if f is None:
        return lambda g: shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)
