"""Sharded torus stepping: `shard_map` + `lax.ppermute` halo exchange.

This is the heart of the distributed design and the deliberate departure
from the reference. The Go system is hub-and-spoke: every turn the broker
re-slices the whole board, ships haloed strips to each worker over net/rpc
and gathers the full board back — O(H·W) bytes through one node per turn
(`Server/gol/distributor.go:104-129,185-224`). Here the board lives sharded
across the mesh for the whole run; each turn every shard sends exactly its
two edge rows to its mesh neighbours over ICI (`lax.ppermute`) — O(W) bytes
per link per turn — and the turn loop is a `lax.scan` compiled into a single
XLA program, so multi-turn runs never touch the host.

Torus wrap-around comes free: the ppermute ring (shard n-1 → shard 0) IS the
vertical wrap, and horizontal wrap stays a roll within each shard — the same
ring pattern ring attention uses for sequence parallelism, applied to a
stencil halo.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule
from gol_tpu.ops.stencil import apply_rule
from gol_tpu.parallel.mesh import ROWS_AXIS, board_sharding


def shard_board(cells: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a (H, W) cells array row-sharded over the mesh."""
    return jax.device_put(cells, board_sharding(mesh))


def _local_step(local: jax.Array, n_shards: int, rule: LifeLikeRule):
    """One turn of one shard: exchange 1-row halos with ring neighbours,
    then the same separable stencil as the single-chip kernel.

    Shard i holds rows [i*H/n, (i+1)*H/n). The row above shard i's first row
    is shard i-1's last row, so each shard sends its LAST row "down" the ring
    (src j → dst j+1) and its FIRST row "up" (src j → dst j-1); with n=1 the
    self-permute degenerates to the torus roll.
    """
    down = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    up = [(j, (j - 1) % n_shards) for j in range(n_shards)]
    top_halo = lax.ppermute(local[-1:, :], ROWS_AXIS, down)
    bot_halo = lax.ppermute(local[:1, :], ROWS_AXIS, up)
    padded = jnp.concatenate([top_halo, local, bot_halo], axis=0)
    vert = padded[:-2, :] + padded[1:-1, :] + padded[2:, :]
    counts = (
        vert
        + jnp.roll(vert, 1, axis=1)
        + jnp.roll(vert, -1, axis=1)
        - local
    )
    return apply_rule(local, counts, rule)


@functools.lru_cache(maxsize=64)
def _compiled_run(mesh: Mesh, rule: LifeLikeRule):
    """jitted (cells, num_turns-static) → cells for one mesh+rule."""
    n_shards = mesh.shape[ROWS_AXIS]
    spec = P(ROWS_AXIS, None)

    @functools.partial(jax.jit, static_argnames=("num_turns",))
    def run(cells: jax.Array, num_turns: int) -> jax.Array:
        if num_turns == 0:
            return cells

        @functools.partial(
            shard_map, mesh=mesh, in_specs=spec, out_specs=spec
        )
        def run_local(local):
            def body(c, _):
                return _local_step(c, n_shards, rule), None
            out, _ = lax.scan(body, local, None, length=num_turns)
            return out

        return run_local(cells)

    return run


def sharded_run_turns(
    cells: jax.Array,
    num_turns: int,
    mesh: Mesh,
    rule: LifeLikeRule = CONWAY,
) -> jax.Array:
    """Advance a row-sharded board `num_turns` turns on the mesh."""
    return _compiled_run(mesh, rule)(cells, num_turns)
