"""Sharded torus stepping: `shard_map` + `lax.ppermute` halo exchange.

This is the heart of the distributed design and the deliberate departure
from the reference. The Go system is hub-and-spoke: every turn the broker
re-slices the whole board, ships haloed strips to each worker over net/rpc
and gathers the full board back — O(H·W) bytes through one node per turn
(`Server/gol/distributor.go:104-129,185-224`). Here the board lives sharded
across the mesh for the whole run; each turn every shard sends exactly its
two edge rows to its mesh neighbours over ICI (`lax.ppermute`) — O(W) bytes
per link per turn — and the turn loop is a `lax.scan` compiled into a single
XLA program, so multi-turn runs never touch the host.

Torus wrap-around comes free: the ppermute ring (shard n-1 → shard 0) IS the
vertical wrap, and horizontal wrap stays a roll within each shard — the same
ring pattern ring attention uses for sequence parallelism, applied to a
stencil halo.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from gol_tpu.parallel.shmap import shard_map

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule
from gol_tpu.ops.bitpack import (
    WORD_BITS,
    _rule_from_count_bits,
    neighbour_count_bits,
)
from gol_tpu.ops.stencil import apply_rule
from gol_tpu.parallel.mesh import ROWS_AXIS, board_sharding


def shard_board(cells: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a (H, W) cells array row-sharded over the mesh."""
    return jax.device_put(cells, board_sharding(mesh))


def _local_step(local: jax.Array, n_shards: int, rule: LifeLikeRule):
    """One turn of one shard: exchange 1-row halos with ring neighbours,
    then the same separable stencil as the single-chip kernel.

    Shard i holds rows [i*H/n, (i+1)*H/n). The row above shard i's first row
    is shard i-1's last row, so each shard sends its LAST row "down" the ring
    (src j → dst j+1) and its FIRST row "up" (src j → dst j-1); with n=1 the
    self-permute degenerates to the torus roll.
    """
    top_halo, bot_halo = _exchange_row_halos(local, n_shards)
    padded = jnp.concatenate([top_halo, local, bot_halo], axis=0)
    vert = padded[:-2, :] + padded[1:-1, :] + padded[2:, :]
    counts = (
        vert
        + jnp.roll(vert, 1, axis=1)
        + jnp.roll(vert, -1, axis=1)
        - local
    )
    return apply_rule(local, counts, rule)


@functools.lru_cache(maxsize=128)
def _make_compiled_run(mesh: Mesh, rule: LifeLikeRule, local_step):
    """jitted (board, num_turns-static) → board for one mesh+rule+step.

    `local_step(local, n_shards, rule)` is the per-shard turn function —
    the uint8 or the bit-packed stencil; everything else (shard_map over
    the row axis, the on-device `lax.scan` turn loop) is shared."""
    n_shards = mesh.shape[ROWS_AXIS]
    spec = P(ROWS_AXIS, None)

    @functools.partial(jax.jit, static_argnames=("num_turns",))
    def run(board: jax.Array, num_turns: int) -> jax.Array:
        if num_turns == 0:
            return board

        @functools.partial(
            shard_map, mesh=mesh, in_specs=spec, out_specs=spec
        )
        def run_local(local):
            def body(c, _):
                return local_step(c, n_shards, rule), None
            out, _ = lax.scan(body, local, None, length=num_turns)
            return out

        return run_local(board)

    return run


def sharded_run_turns(
    cells: jax.Array,
    num_turns: int,
    mesh: Mesh,
    rule: LifeLikeRule = CONWAY,
) -> jax.Array:
    """Advance a row-sharded board `num_turns` turns on the mesh."""
    with dispatch_obs("u8", cells, num_turns, mesh):
        return _make_compiled_run(mesh, rule, _local_step)(
            cells, num_turns)


# ----------------------------------------------------------------- packed

def exchange_halos(
    local: jax.Array,
    n_shards: int,
    axis_name: str,
    depth: int = 1,
    axis: int = 0,
):
    """(low_halo, high_halo) — `depth` slices of `axis` from each ring
    neighbour along mesh axis `axis_name` via ppermute. The low halo is the
    neighbour-below-in-index's trailing slice (goes above this shard), the
    high halo the neighbour-above's leading slice."""
    down = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    up = [(j, (j - 1) % n_shards) for j in range(n_shards)]
    size = local.shape[axis]
    trailing = lax.slice_in_dim(local, size - depth, size, axis=axis)
    leading = lax.slice_in_dim(local, 0, depth, axis=axis)
    low = lax.ppermute(trailing, axis_name, down)
    high = lax.ppermute(leading, axis_name, up)
    return low, high


def _exchange_row_halos(local: jax.Array, n_shards: int, depth: int = 1):
    """(top_halo, bot_halo) rows via the ppermute ring."""
    return exchange_halos(local, n_shards, ROWS_AXIS, depth=depth, axis=0)


def _packed_local_step(local: jax.Array, n_shards: int, rule: LifeLikeRule):
    """One turn of one shard of a bit-packed (rows, W/32 uint32) board:
    identical ring halo exchange as `_local_step`, but each halo row is W/32
    words (W/8 bytes) instead of W bytes, and the stencil is the carry-save
    adder network from `ops/bitpack.py`."""
    top, bot = _exchange_row_halos(local, n_shards)
    padded = jnp.concatenate([top, local, bot], axis=0)
    n0, n1, n2, n3 = neighbour_count_bits(
        padded[:-2, :], local, padded[2:, :]
    )
    return _rule_from_count_bits(local, n0, n1, n2, n3, rule)


def batched_packed_local_step(batch: jax.Array, n_shards: int,
                              rule: LifeLikeRule) -> jax.Array:
    """One turn of one row-shard of a (cap, local_rows, wpb) packed fleet
    bucket batch — the spatial-fallback inner step for big-board bucket
    classes (`fleet/buckets.py`): the same ppermute ring halo exchange as
    `_packed_local_step`, batched over the leading slot axis (the adder
    network and rolls below touch only the trailing two axes, so every
    slot rides the same exchanged halo rows)."""
    top, bot = exchange_halos(batch, n_shards, ROWS_AXIS, depth=1, axis=1)
    padded = jnp.concatenate([top, batch, bot], axis=1)
    n0, n1, n2, n3 = neighbour_count_bits(
        padded[:, :-2, :], batch, padded[:, 2:, :])
    return _rule_from_count_bits(batch, n0, n1, n2, n3, rule)


# Deep-halo macro-stepping (multi-shard packed path): instead of trading a
# 1-row halo every turn, each macro-step trades a T-row halo once and then
# advances T turns with no communication at all. The (rows + 2T)-row window
# is stepped with ordinary torus stepping; its vertical wrap feeds wrong
# rows to the window edges, but the corruption advances exactly one row per
# turn from each edge, so after T turns it has consumed precisely the 2T
# halo rows and the shard's own rows are exact (same argument a halo-deep
# banded stencil uses). Benefits: 1/T the ppermute latency exposures, and
# the T local turns form a closed single-device problem the VMEM-resident
# pallas kernel can run per-shard.
DEEP_HALO_T = 16


def _deep_halo_T(num_turns: int, shard_rows: int,
                 cap: int = DEEP_HALO_T) -> int:
    """Largest power of two that divides num_turns, capped by `cap`
    (DEEP_HALO_T natively, the pinned fuse depth on the fused fallback
    path) and by the shard height (a halo can only come from the
    adjacent shard)."""
    t = 1
    while (
        t * 2 <= min(cap, shard_rows)
        and num_turns % (t * 2) == 0
    ):
        t *= 2
    return t


def fused_halo_T(fuse: int, num_turns: int, shard_rows: int) -> int:
    """The deep-halo depth a fuse-k dispatch actually uses: k itself
    when it divides the dispatch and fits the shard height (one
    exchange per k turns — the temporal-fusion contract), else the
    largest power-of-two fallback capped by k (keeping the compiled
    macro scan remainder-free). fuse <= 1 is the native adaptive
    selection. `halo_traffic` mirrors this exactly — change both or
    the analytic counters go dishonest."""
    if fuse <= 1:
        return _deep_halo_T(num_turns, shard_rows)
    if fuse <= shard_rows and num_turns % fuse == 0:
        return fuse
    return _deep_halo_T(num_turns, shard_rows, cap=fuse)


@functools.lru_cache(maxsize=1024)
def halo_traffic(repr_, shape, mesh, num_turns, fuse=0) -> dict:
    """Analytic ppermute traffic of ONE dispatch of `num_turns` turns:
    {axis: (exchange_rounds, total_bytes)}. An exchange round is one
    paired send (`exchange_halos` issues its two ppermutes together, so
    the pair is the latency-exposure unit); bytes sum every shard's
    sends, i.e. whole-mesh traffic per round. Mirrors the dispatch
    logic below exactly (deep-halo T selection, 2-D macro decomposition)
    so the counts stay honest without touching the compiled program.

    Returns {} when the dispatch moves no explicit halo traffic: a
    single shard along every axis, zero turns, or the wrap-extension
    path (`extended_run_turns`), whose cross-shard seam collectives are
    GSPMD-inserted and not modelled here — engine callers gate on
    pad_rows == 0 for that reason.

    `repr_` is 'packed' | 'u8' | 'gen8' | 'gen3'; 2-D meshes (a 'cols'
    axis present) are packed-only and routed by the mesh itself.
    `shape` must be a plain tuple (this is an lru_cache key). `fuse`
    is the pinned fuse depth of the dispatch (0 = auto), mirrored
    through the same depth selection as the run paths. Note the fused
    byte totals are CONSERVED — a k-deep exchange ships 2k rows per k
    turns, the same 2 rows/turn as per-turn exchange — while the
    exchange-round (latency-exposure) count drops k-fold."""
    if num_turns <= 0:
        return {}
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_rows = int(axes.get(ROWS_AXIS, 1))
    if "cols" in axes:  # 2-D mesh (mesh2d.COLS_AXIS; literal avoids a
        n_cols = int(axes["cols"])  # circular import — mesh2d imports us)
        from gol_tpu.parallel.mesh2d import macro_T_2d

        h, wp = shape
        shard_rows, shard_cols = h // n_rows, wp // n_cols
        T = macro_T_2d(shard_rows, fuse)
        full, rem = divmod(num_turns, T)
        depths = [T] * full + ([rem] if rem else [])
        out = {}
        if n_rows > 1:
            out[ROWS_AXIS] = (
                len(depths),
                sum(2 * t * shard_cols * 4 * n_rows * n_cols
                    for t in depths))
        if n_cols > 1:
            # One word column per macro, cut from the row-extended
            # (shard_rows + 2t) window so the corners ride along.
            out["cols"] = (
                len(depths),
                sum(2 * (shard_rows + 2 * t) * 4 * n_rows * n_cols
                    for t in depths))
        return out
    if n_rows <= 1:
        return {}
    if repr_ == "gen3":
        rows_len = shape[1]  # stacked (2, H, Wp) planes
        row_bytes = shape[-1] * 4  # alive plane only is exchanged
    elif repr_ == "packed":
        rows_len = shape[0]
        row_bytes = shape[-1] * 4
    else:  # u8 / gen8 state boards
        rows_len = shape[0]
        row_bytes = shape[-1]
    if repr_ == "packed":
        shard_rows = rows_len // n_rows
        T = fused_halo_T(fuse, num_turns, shard_rows)
        if T > 1:
            rounds = num_turns // T
            return {ROWS_AXIS: (
                rounds, rounds * 2 * T * row_bytes * n_rows)}
    rounds = num_turns
    return {ROWS_AXIS: (rounds, rounds * 2 * row_bytes * n_rows)}


def dispatch_obs(repr_, cells, num_turns, mesh, fuse=0):
    """Host-side observability for one EAGER sharded dispatch: fold the
    analytic traffic into the gol_halo_* counters and, when span
    tracing is armed, return a 'halo.dispatch' span context covering
    the (asynchronous) dispatch. Tracer inputs are skipped entirely —
    under the engine's jit-composed token wrapper these wrappers run
    once per compilation, and the engine does its own per-chunk
    accounting through obs/halostats.flush_chunk_walls. Never raises:
    telemetry must not sink a dispatch."""
    if isinstance(cells, jax.core.Tracer):
        return contextlib.nullcontext()
    try:
        traffic = halo_traffic(repr_, tuple(cells.shape), mesh,
                               num_turns, fuse)
        if not traffic:
            return contextlib.nullcontext()
        from gol_tpu.obs import halostats, trace

        halostats.note_traffic(traffic, num_turns)
        if fuse > 1:
            from gol_tpu.obs import catalog

            catalog.FUSED_DISPATCHES.labels(tier="mesh").inc()
        if not trace.hot_spans_enabled():
            return contextlib.nullcontext()
        return trace.TRACER.span("halo.dispatch", attrs={
            "repr": repr_, "turns": num_turns,
            "shards": int(mesh.size),
            "exchange_rounds": halostats.total_rounds(traffic),
            "halo_bytes": halostats.total_bytes(traffic)})
    except Exception:
        return contextlib.nullcontext()


def _packed_deep_macro(
    local: jax.Array,
    n_shards: int,
    rule: LifeLikeRule,
    T: int,
    inner: str,
):
    """One macro-step: exchange T-row halos, advance the window T turns
    (`inner`: 'banded[-interpret]' | 'pallas[-interpret]' | 'jnp'), keep
    the exact middle."""
    top, bot = _exchange_row_halos(local, n_shards, depth=T)
    window = jnp.concatenate([top, local, bot], axis=0)
    return run_window(window, T, rule, inner)[T:-T]


def run_window(window: jax.Array, T: int, rule: LifeLikeRule, inner: str):
    """Advance a haloed per-shard window T torus turns with the engine
    named by `inner` (an `inner_kind` result, plus the '-interpret'
    variants tests use). The single dispatch point shared by the 1-D and
    2-D deep-halo macros — one place to add a kernel kind."""
    from gol_tpu.ops.bitpack import packed_run_turns
    from gol_tpu.ops.pallas_stencil import (
        banded_packed_run_turns,
        pallas_packed_run_turns,
    )

    if inner == "banded":
        return banded_packed_run_turns(window, T, rule)
    if inner == "banded-interpret":
        return banded_packed_run_turns(window, T, rule, interpret=True)
    if inner == "pallas":
        return pallas_packed_run_turns(window, T, rule)
    if inner == "pallas-interpret":
        return pallas_packed_run_turns(window, T, rule, interpret=True)
    return packed_run_turns(window, T, rule)


@functools.lru_cache(maxsize=128)
def _make_compiled_deep_run(
    mesh: Mesh, rule: LifeLikeRule, T: int, inner: str
):
    n_shards = mesh.shape[ROWS_AXIS]
    spec = P(ROWS_AXIS, None)

    @functools.partial(jax.jit, static_argnames=("num_macros",))
    def run(packed: jax.Array, num_macros: int) -> jax.Array:
        # check_vma=False: pallas_call's out_shape carries no varying-mesh-
        # axes annotation, which the default shard_map safety check rejects.
        @functools.partial(
            shard_map, mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False,
        )
        def run_local(local):
            def body(p, _):
                return (
                    _packed_deep_macro(p, n_shards, rule, T, inner),
                    None,
                )
            out, _ = lax.scan(body, local, None, length=num_macros)
            return out

        return run_local(packed)

    return run


def inner_kind(mesh: Mesh, window_shape, T: Optional[int] = None) -> str:
    """Per-shard engine for a deep-halo window — the same preference
    order as the single-device dispatch (`packed_run_kind`): the banded
    HBM kernel when the window's word axis is lane-aligned (the fastest
    tier, and the only one that scales to per-shard windows beyond VMEM),
    else the whole-window VMEM kernel when it fits, else the jnp packed
    scan. Shared by the 1-D and 2-D deep-halo paths.

    When the macro depth `T` is given, 'banded' is only reported if the
    banded kernel would actually sweep that depth (its Mosaic DMA needs
    8-sublane-aligned halo depths) — otherwise its internal fallback
    would silently run the jnp scan under a 'banded' label."""
    from gol_tpu.ops.pallas_stencil import banded_supported, fits_in_vmem

    platform = mesh.devices.flat[0].platform
    if platform == "tpu" and window_shape[-1] >= 2:  # wp==1: Mosaic 0-size
        if banded_supported(window_shape) and (
                T is None or T % 8 == 0 or fits_in_vmem(window_shape)):
            return "banded"
        if fits_in_vmem(window_shape):
            return "pallas"
    return "jnp"


def packed_run_kind(shape, platform: str) -> str:
    """Which single-device packed engine a (H, Wp) board gets on
    `platform`: 'banded' | 'vmem' | 'jnp'. Banded first even when the whole
    board would fit in VMEM: its small per-band working windows sustain ~5x
    the op throughput of one big fori_loop carry (measured 282e9 vs 176e9
    cups on 4096²). Static in (shape, platform) so callers can compose the
    chosen engine inside their own jitted programs (`models/sparse.py`
    fuses it with the occupancy reduction into one dispatch)."""
    from gol_tpu.ops.pallas_stencil import banded_supported, fits_in_vmem

    if platform == "tpu" and shape[-1] >= 2:
        # wp == 1 (a 32-cell-wide board) lowers to zero-size vector
        # slices in the Mosaic kernels ('vector types must have positive
        # constant sizes') — such boards run the jnp packed path, whose
        # size-1 rolls are the correct single-word torus wrap.
        if banded_supported(shape):
            return "banded"
        if fits_in_vmem(shape):
            return "vmem"
    return "jnp"


def packed_run_by_kind(kind: str):
    """The `(packed, num_turns, rule) -> packed` engine for a
    `packed_run_kind` result."""
    from gol_tpu.ops.bitpack import packed_run_turns
    from gol_tpu.ops.pallas_stencil import (
        banded_packed_run_turns,
        pallas_packed_run_turns,
    )

    return {
        "banded": banded_packed_run_turns,
        "vmem": pallas_packed_run_turns,
        "jnp": packed_run_turns,
    }[kind]


def _single_device_packed_run(
    packed: jax.Array, num_turns: int, rule: LifeLikeRule,
    platform: Optional[str] = None,
) -> jax.Array:
    """1-shard fast path — no shard_map wrapper; engine choice per
    `packed_run_kind`. `platform` must be supplied when `packed` may be
    a tracer (callers composing this inside their own jit — the engine's
    tokened chunk wrapper): a tracer has no devices to inspect."""
    if platform is None:
        devices = getattr(packed, "devices", None)
        dev = next(iter(devices())) if devices else jax.devices()[0]
        platform = dev.platform
    kind = packed_run_kind(packed.shape, platform)
    return packed_run_by_kind(kind)(packed, num_turns, rule)


def sharded_packed_run_turns(
    packed: jax.Array,
    num_turns: int,
    mesh: Mesh,
    rule: LifeLikeRule = CONWAY,
    fuse: int = 0,
) -> jax.Array:
    """Advance a row-sharded bit-packed board `num_turns` turns. `fuse`
    pins the temporal-fusion depth (0 = auto): a single shard routes to
    the fused single-device tier (`ops/fused.py`), a multi-shard mesh
    exchanges k-deep halos — one exchange round per k turns — via the
    deep-halo macro path at T = k (`fused_halo_T`)."""
    n_shards = mesh.shape[ROWS_AXIS]
    if n_shards == 1:
        # Platform from the (static) mesh, not the array: jit-composable.
        platform = mesh.devices.flat[0].platform
        if fuse > 1:
            from gol_tpu.ops.fused import fused_packed_run_turns

            return fused_packed_run_turns(
                packed, num_turns, rule, fuse, platform)
        return _single_device_packed_run(
            packed, num_turns, rule, platform)
    with dispatch_obs("packed", packed, num_turns, mesh, fuse):
        shard_rows = packed.shape[-2] // n_shards
        T = fused_halo_T(fuse, num_turns, shard_rows)
        if T > 1:
            window_shape = (shard_rows + 2 * T, packed.shape[-1])
            inner = inner_kind(mesh, window_shape, T)
            run = _make_compiled_deep_run(mesh, rule, T, inner)
            return run(packed, num_turns // T)
        return _make_compiled_run(mesh, rule, _packed_local_step)(
            packed, num_turns)


@functools.lru_cache(maxsize=32)
def fused_run_fn(fuse: int):
    """A stable-identity (cells, k, mesh, rule) run callable pinning the
    fuse depth — cached so the engine's `_tokened_run` lru cache keys on
    one object per depth. (Reading GOL_FUSE_K at trace time instead
    would freeze the first-seen value into the jit cache for the life
    of the process.)"""
    def run(cells, num_turns, mesh, rule=CONWAY):
        return sharded_packed_run_turns(
            cells, num_turns, mesh, rule, fuse=fuse)

    return run


# ----------------------------------------------- exact-N odd heights
#
# The reference serves EXACTLY the requested worker count on any board
# height by spreading the H mod N remainder rows over the first strips
# (`Server/gol/distributor.go:106-116`). shard_map needs equal shards,
# so the TPU-native equivalent is wrap-extension (the SURVEY §2c plan:
# "padding instead of remainder-spread"): extend the board with a copy
# of its first `ext` rows so H + ext divides N, step the extension as an
# ordinary (H+ext)-torus, and re-establish the invariant each turn.
#
# Correctness: with P = [B[0..H-1], B[0..ext-1]] and P2 = torus-step(P),
# row j in 1..H-1 sees true neighbours (P[H] = B[0] gives row H-1 its
# wrap), and P2[H] = f(B[H-1], B[0], B[1]) is the true new row 0 (needs
# ext >= 2 so P[H+1] = B[1] exists). So B' = [P2[H], P2[1:H]] exactly,
# and the extension is rebuilt from B' by construction. The per-turn
# slice/concat crosses shard boundaries — XLA/GSPMD inserts the
# collectives — making this the *correct-by-construction fallback* for
# heights the ppermute ring can't split evenly; even heights keep the
# faster equal-shard path.


def exact_shard_ext(height: int, n_shards: int) -> int:
    """Smallest extension >= 2 making height + ext divisible; 0 when the
    height already divides (no extension needed)."""
    if n_shards <= 1 or height % n_shards == 0:
        return 0
    ext = 2
    while (height + ext) % n_shards:
        ext += 1
    return ext


def extend_rows(board: np.ndarray, ext: int, axis: int = 0) -> np.ndarray:
    """[B; B[(0:ext) mod H]] along `axis` — the wrap-extended board
    (host-side, at submit). Cyclic indexing: when ext > H (tiny boards
    on wide meshes, e.g. H=2 over 8 shards needs ext=6) the extension is
    the torus unrolled, not a short slice. `axis=1` serves the stacked
    gen3 planes (2, H, Wp), whose row axis is not the leading one."""
    import numpy as _np

    idx = _np.arange(ext) % board.shape[axis]
    return _np.concatenate(
        [board, _np.take(board, idx, axis=axis)], axis=axis)


def _ext_repr(repr_) -> str:
    """Normalize the representation tag: legacy bool (life-like
    packed/u8) or one of 'packed'/'u8'/'gen8'/'gen3' (r5 — VERDICT r4
    #2 extends exact-N to the Generations family)."""
    if repr_ is True:
        return "packed"
    if repr_ is False:
        return "u8"
    if repr_ not in ("packed", "u8", "gen8", "gen3"):
        raise ValueError(f"unknown extended-run repr {repr_!r}")
    return repr_


@functools.lru_cache(maxsize=128)
def _make_extended_run(height: int, ext: int, repr_: str, mesh: Mesh,
                       rule):
    """jitted (extended board, num_turns-static) -> extended board:
    torus-step + invariant rebuild per turn, sharded exactly N ways over
    `mesh` rows via GSPMD (sharding constraint on the scan carry). All
    four representations ride the same rebuild; only the inner full-
    torus step and the row axis differ (gen3's stacked planes carry
    rows on axis 1)."""
    from jax.sharding import NamedSharding

    from gol_tpu.models.generations import _packed_step3, _step as gen_step
    from gol_tpu.ops.bitpack import packed_step
    from gol_tpu.ops.stencil import step as u8_step

    if ext < 2:
        # The rebuild reads P2[H], whose below-neighbour P[H+1] must
        # exist — a smaller extension silently computes garbage.
        raise ValueError(f"wrap extension needs ext >= 2, got {ext}")
    axis = 1 if repr_ == "gen3" else 0
    sh = NamedSharding(
        mesh,
        P(None, ROWS_AXIS, None) if repr_ == "gen3" else P(ROWS_AXIS, None))

    def inner(prev):
        if repr_ == "packed":
            return packed_step(prev, rule)
        if repr_ == "u8":
            return u8_step(prev, rule)
        if repr_ == "gen8":
            return gen_step(prev, rule)
        a2, d2 = _packed_step3(prev[0], prev[1], rule)
        return jnp.stack([a2, d2])

    ext_idx = tuple(range(ext))  # static; cyclic when ext > height

    @functools.partial(jax.jit, static_argnames=("num_turns",))
    def run(board: jax.Array, num_turns: int) -> jax.Array:
        idx = jnp.array([i % height for i in ext_idx], dtype=jnp.int32)

        def body(prev, _):
            stepped = inner(prev)
            core = jnp.concatenate(
                [lax.slice_in_dim(stepped, height, height + 1, axis=axis),
                 lax.slice_in_dim(stepped, 1, height, axis=axis)],
                axis=axis)
            nxt = jnp.concatenate(
                [core, jnp.take(core, idx, axis=axis)], axis=axis)
            return lax.with_sharding_constraint(nxt, sh), None

        out, _ = lax.scan(body, board, None, length=num_turns)
        return out

    return run


def extended_run_turns(
    board: jax.Array,
    num_turns: int,
    mesh: Mesh,
    rule=CONWAY,
    *,
    height: int,
    ext: int,
    packed,
) -> jax.Array:
    """Advance a wrap-extended board (see module note above) — the
    exact-shard-count path for heights not divisible by the mesh.
    `packed`: a repr tag per `_ext_repr` (bool accepted for the
    life-like callers)."""
    return _make_extended_run(height, ext, _ext_repr(packed), mesh, rule)(
        board, num_turns)


@functools.lru_cache(maxsize=128)
def extended_run_fn(height: int, ext: int, packed):
    """A stable-identity (cells, k, mesh, rule) run callable for the
    wrap-extension path — cached so the engine's `_tokened_run` lru
    cache keys on one object per (height, ext, tier)."""
    repr_ = _ext_repr(packed)

    def run(cells, num_turns, mesh, rule=CONWAY):
        return extended_run_turns(
            cells, num_turns, mesh, rule,
            height=height, ext=ext, packed=repr_)

    return run


# ------------------------------------------------------- Generations
#
# The multi-state family rides the SAME shard_map + ppermute machinery:
# a Generations turn only needs 1-row halos of the state board (neighbour
# counts are of the ALIVE plane, derived locally from the haloed state).
# Three-state rules on 32-aligned widths additionally get the bit-packed
# two-plane kernel (alive/dying planes stacked as one (2, rows, Wp)
# uint32 array so the engine's single-array state plumbing — checkpoint,
# token, publication — applies unchanged).


def _gen_local_step(local: jax.Array, n_shards: int, rule):
    """One Generations turn of one uint8 state shard."""
    from gol_tpu.models.generations import apply_generations_rule

    top, bot = _exchange_row_halos(local, n_shards)
    padded = jnp.concatenate([top, local, bot], axis=0)
    alive = (padded == 1).astype(jnp.uint8)
    vert = alive[:-2, :] + alive[1:-1, :] + alive[2:, :]
    n = (vert + jnp.roll(vert, 1, axis=1) + jnp.roll(vert, -1, axis=1)
         - alive[1:-1, :])
    return apply_generations_rule(local, n, rule)


def sharded_generations_run_turns(
    state: jax.Array, num_turns: int, mesh: Mesh, rule
) -> jax.Array:
    """Advance a row-sharded uint8 Generations state board."""
    with dispatch_obs("gen8", state, num_turns, mesh):
        return _make_compiled_run(mesh, rule, _gen_local_step)(
            state, num_turns)


def gen3_planes_sharding(mesh: Mesh):
    """Sharding for the stacked (2, rows, Wp) two-plane state: rows over
    the mesh, plane and word axes replicated within a shard."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, P(None, ROWS_AXIS, None))


def shard_board_gen3(stacked: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(stacked, gen3_planes_sharding(mesh))


def _gen3_local_step(stacked: jax.Array, n_shards: int, rule):
    """One turn of one shard of the stacked packed planes: halo-exchange
    the ALIVE plane's edge rows (the dying plane transitions locally),
    then the carry-save adder network + two-plane rule from
    `models/generations.packed_run_turns3`."""
    from gol_tpu.ops.bitpack import (
        gen3_transition,
        neighbour_count_bits,
        rule_masks,
    )

    a, d = stacked[0], stacked[1]
    top, bot = _exchange_row_halos(a, n_shards)
    padded = jnp.concatenate([top, a, bot], axis=0)
    n0, n1, n2, n3 = neighbour_count_bits(
        padded[:-2, :], a, padded[2:, :])
    born, surv = rule_masks(n0, n1, n2, n3, rule.born, rule.survive)
    a2, d2 = gen3_transition(a, d, born, surv)
    return jnp.stack([a2, d2])


@functools.lru_cache(maxsize=64)
def _make_compiled_gen3_run(mesh: Mesh, rule):
    n_shards = mesh.shape[ROWS_AXIS]
    spec = P(None, ROWS_AXIS, None)

    @functools.partial(jax.jit, static_argnames=("num_turns",))
    def run(stacked: jax.Array, num_turns: int) -> jax.Array:
        if num_turns == 0:
            return stacked

        @functools.partial(
            shard_map, mesh=mesh, in_specs=spec, out_specs=spec
        )
        def run_local(local):
            def body(s, _):
                return _gen3_local_step(s, n_shards, rule), None
            out, _ = lax.scan(body, local, None, length=num_turns)
            return out

        return run_local(stacked)

    return run


@functools.lru_cache(maxsize=64)
def _gen3_single_run(rule, platform: str):
    """Cached jit of the single-shard stacked-planes run (a fresh closure
    per call would re-trace/compile every chunk). `platform` is static
    (from the mesh) so the engine dispatch below composes inside jit."""
    from gol_tpu.models.generations import packed_run_turns3

    @functools.partial(jax.jit, static_argnames=("k",))
    def run1(s, k):
        a, d = packed_run_turns3(s[0], s[1], k, rule, platform=platform)
        return jnp.stack([a, d])

    return run1


def sharded_gen3_run_turns(
    stacked: jax.Array, num_turns: int, mesh: Mesh, rule, fuse: int = 0
) -> jax.Array:
    """Advance stacked packed (alive, dying) planes of a 3-state rule.
    Single-shard meshes dispatch straight to the best single-device
    gen3 engine (VMEM pallas kernel on TPU when the planes fit, else
    the scan — same fast-path policy as the life-like board); a pinned
    fuse depth routes them through the fused windowed tier
    (`ops/fused.fused_gen3_run_turns`). Multi-shard gen3 keeps the
    per-turn alive-plane exchange: a k-deep gen3 halo would have to
    ship BOTH planes' margins, changing the traffic model — out of the
    fused tier's scope (documented in docs/ARCHITECTURE.md)."""
    if mesh.shape[ROWS_AXIS] == 1:
        if fuse > 1:
            from gol_tpu.ops.fused import fused_gen3_run_turns

            return fused_gen3_run_turns(
                stacked, num_turns, rule, fuse,
                mesh.devices.flat[0].platform)
        return _gen3_single_run(
            rule, mesh.devices.flat[0].platform)(stacked, num_turns)
    with dispatch_obs("gen3", stacked, num_turns, mesh):
        return _make_compiled_gen3_run(mesh, rule)(stacked, num_turns)


@functools.lru_cache(maxsize=32)
def fused_gen3_run_fn(fuse: int):
    """Stable-identity gen3 run callable pinning the fuse depth — the
    gen3 sibling of `fused_run_fn`, same jit-cache-staleness rationale."""
    def run(cells, num_turns, mesh, rule):
        return sharded_gen3_run_turns(cells, num_turns, mesh, rule,
                                      fuse=fuse)

    return run


def select_representation(width: int):
    """The one place the packed-eligibility rule lives: returns
    (packed: bool, run_fn) — bit-packed whenever the width is a whole
    number of 32-bit words, else the uint8 path."""
    if width % WORD_BITS == 0:
        return True, sharded_packed_run_turns
    return False, sharded_run_turns
