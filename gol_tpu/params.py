"""Run parameters.

Mirrors the reference `gol.Params` struct (`Local/gol/gol.go:4-10`): the one
config object, forwarded verbatim from CLI to engine. `threads` is kept for
API parity with the reference's per-worker goroutine fan-out
(`SubServer/distributor.go:49-69`); on TPU intra-chip parallelism is XLA's
job, so `threads` only caps the *requested* shard count hint when no explicit
worker list is given.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Params:
    threads: int = 8
    image_width: int = 512
    image_height: int = 512
    turns: int = 100

    def __post_init__(self) -> None:
        if self.image_width <= 0 or self.image_height <= 0:
            raise ValueError(
                f"board must be non-empty, got "
                f"{self.image_width}x{self.image_height}"
            )
        if self.turns < 0:
            raise ValueError(f"turns must be >= 0, got {self.turns}")
        if self.threads <= 0:
            raise ValueError(f"threads must be >= 1, got {self.threads}")
