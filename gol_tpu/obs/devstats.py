"""Device memory + compilation telemetry (gol_dev_* / gol_compile_*).

Bridges jax's runtime introspection into the dependency-free metrics
registry.  jax is imported lazily inside the polling/hook functions so
that importing this module (e.g. from obs/http.py for /healthz fields)
never drags a device runtime into control-plane processes; every value
healthz needs is cached here after the last poll.

Three concerns live here:

* ``poll_device_memory()`` — reads ``device.memory_stats()`` for each
  addressable device and publishes live/peak/limit bytes gauges.
  Backends without stats (CPU) are the common case in tests: the poll
  degrades to ``gol_dev_mem_supported 0`` and returns ``None`` fields
  rather than raising.
* ``install_compile_hooks()`` — registers ``jax.monitoring`` listeners
  that count backend compilations (cache hits excluded), record
  compile wall time, and track persistent-cache hit/miss counters.
  Idempotent: jax offers no unregister, so we install once per process.
* ``note_signature()`` / ``compiled_cost()`` — engine-step signature
  tracking (each distinct signature implies a fresh trace+compile) and
  a normaliser for ``compiled.cost_analysis()`` whose return shape
  varies across jax versions (list-of-dicts vs dict).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from gol_tpu.obs import catalog as _cat

_lock = threading.Lock()
_hooks_installed = False
_signatures: set = set()

# Cache of the last successful poll, consumed by healthz_fields() so the
# HTTP layer never has to touch jax (and never blocks on a device sync).
_last_poll: dict = {
    "device_kind": None,
    "devices": None,
    "supported": None,
    "live_bytes": None,
    "peak_bytes": None,
    "per_device": None,
}

# memory_stats() key aliases across backends.  TPU/GPU PJRT clients use
# bytes_in_use/peak_bytes_in_use; bytes_limit is best-effort.
_LIVE_KEYS = ("bytes_in_use", "bytes_used", "allocated_bytes")
_PEAK_KEYS = ("peak_bytes_in_use", "peak_bytes")
_LIMIT_KEYS = ("bytes_limit", "bytes_reservable_limit", "largest_alloc_size")


def _first(stats: dict, keys) -> Optional[int]:
    for k in keys:
        v = stats.get(k)
        if v is not None:
            try:
                return int(v)
            except (TypeError, ValueError):
                continue
    return None


def memory_snapshot(device: Any) -> Optional[dict]:
    """Per-device memory stats, or None where the backend has none.

    Returns ``{"live_bytes", "peak_bytes", "limit_bytes", "raw"}``;
    ``raw`` keeps the backend's full stats dict for per-buffer
    breakdowns where available.
    """
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {
        "live_bytes": _first(stats, _LIVE_KEYS),
        "peak_bytes": _first(stats, _PEAK_KEYS),
        "limit_bytes": _first(stats, _LIMIT_KEYS),
        "raw": {k: int(v) for k, v in stats.items()
                if isinstance(v, (int, float))},
    }


def device_kind() -> Optional[str]:
    """Kind string of device 0 ("cpu", "TPU v4", ...), cached."""
    with _lock:
        if _last_poll["device_kind"] is not None:
            return _last_poll["device_kind"]
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    with _lock:
        _last_poll["device_kind"] = kind
    return kind


def poll_device_memory() -> dict:
    """Poll every addressable device, update gauges, cache for healthz.

    Returns a summary dict; live/peak fields are None on backends
    without memory stats (the graceful-None contract)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        summary = {"device_kind": None, "devices": 0, "supported": False,
                   "live_bytes": None, "peak_bytes": None,
                   "per_device": {}}
        with _lock:
            _last_poll.update(summary)
        return summary

    per_device = {}
    live_total = peak_total = 0
    supported = False
    for d in devices:
        snap = memory_snapshot(d)
        if snap is None:
            continue
        supported = True
        dev_id = str(d.id)
        per_device[dev_id] = {k: snap[k] for k in
                              ("live_bytes", "peak_bytes", "limit_bytes")}
        if snap["live_bytes"] is not None:
            live_total += snap["live_bytes"]
            _cat.DEV_LIVE_BYTES.labels(device=dev_id).set(
                snap["live_bytes"])
        if snap["peak_bytes"] is not None:
            peak_total += snap["peak_bytes"]
            _cat.DEV_PEAK_BYTES.labels(device=dev_id).set(
                snap["peak_bytes"])
        if snap["limit_bytes"] is not None:
            _cat.DEV_LIMIT_BYTES.labels(device=dev_id).set(
                snap["limit_bytes"])
    _cat.DEV_MEM_SUPPORTED.set(1.0 if supported else 0.0)
    _cat.DEV_DEVICES.set(float(len(devices)))

    summary = {
        "device_kind": devices[0].device_kind if devices else None,
        "devices": len(devices),
        "supported": supported,
        "live_bytes": live_total if supported else None,
        "peak_bytes": peak_total if supported else None,
        "per_device": per_device,
    }
    with _lock:
        _last_poll.update(summary)
    return summary


def healthz_fields() -> dict:
    """Cached device fields for /healthz — never imports jax."""
    with _lock:
        cached = dict(_last_poll)
    return {
        "device_kind": cached["device_kind"],
        "live_bytes": cached["live_bytes"],
        "compile_count": int(_cat.COMPILE_TOTAL.value),
    }


# ------------------------------------------------------------ compile hooks

# jax.monitoring event names (stable across 0.4.x).
_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_duration(event: str, duration: float, **kw) -> None:
    if event == _COMPILE_DURATION_EVENT:
        _cat.COMPILE_TOTAL.inc()
        _cat.COMPILE_SECONDS.observe(duration)


def _on_event(event: str, **kw) -> None:
    if event == _CACHE_HIT_EVENT:
        _cat.COMPILE_CACHE_HITS.inc()
    elif event == _CACHE_MISS_EVENT:
        _cat.COMPILE_CACHE_MISSES.inc()


def install_compile_hooks() -> bool:
    """Register jax.monitoring listeners once per process.

    Returns True if the hooks are (now) installed.  jax.monitoring has
    no unregister API, so the guard is what keeps double-installs from
    double-counting."""
    global _hooks_installed
    with _lock:
        if _hooks_installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        try:
            monitoring.register_event_duration_secs_listener(_on_duration)
            monitoring.register_event_listener(_on_event)
        except Exception:
            return False
        _hooks_installed = True
    return True


def note_signature(key: tuple) -> bool:
    """Record an engine-step signature; True if it is new this process.

    A new (representation, board shape, dtype, mesh, rule) tuple means
    jit will trace and compile a fresh executable — the counter is the
    operator-visible recompile-churn signal."""
    with _lock:
        if key in _signatures:
            return False
        _signatures.add(key)
    _cat.COMPILE_STEP_SIGNATURES.inc()
    return True


def signature_count() -> int:
    with _lock:
        return len(_signatures)


def compiled_cost(compiled: Any) -> Optional[dict]:
    """Normalise compiled.cost_analysis() to {"flops", "bytes_accessed"}.

    cost_analysis() returns a list of dicts on some jax versions and a
    bare dict on others; keys use spaces ("bytes accessed").  Returns
    None when the backend offers no cost model."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed", ca.get("bytes_accessed"))
    if flops is None and nbytes is None:
        return None
    return {
        "flops": float(flops) if flops is not None else None,
        "bytes_accessed": float(nbytes) if nbytes is not None else None,
    }
