"""Device memory + compilation telemetry (gol_dev_* / gol_compile_*).

Bridges jax's runtime introspection into the dependency-free metrics
registry.  jax is imported lazily inside the polling/hook functions so
that importing this module (e.g. from obs/http.py for /healthz fields)
never drags a device runtime into control-plane processes; every value
healthz needs is cached here after the last poll.

Three concerns live here:

* ``poll_device_memory()`` — reads ``device.memory_stats()`` for each
  addressable device and publishes live/peak/limit bytes gauges.
  Backends without stats (CPU) are the common case in tests: the poll
  degrades to ``gol_dev_mem_supported 0`` and returns ``None`` fields
  rather than raising.
* ``install_compile_hooks()`` — registers ``jax.monitoring`` listeners
  that count backend compilations (cache hits excluded), record
  compile wall time, and track persistent-cache hit/miss counters.
  Idempotent: jax offers no unregister, so we install once per process.
* ``note_signature()`` / ``compiled_cost()`` — engine-step signature
  tracking (each distinct signature implies a fresh trace+compile) and
  a normaliser for ``compiled.cost_analysis()`` whose return shape
  varies across jax versions (list-of-dicts vs dict).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from gol_tpu.obs import catalog as _cat

_lock = threading.Lock()
_hooks_installed = False
_signatures: set = set()

# Cache of the last successful poll, consumed by healthz_fields() so the
# HTTP layer never has to touch jax (and never blocks on a device sync).
_last_poll: dict = {
    "device_kind": None,
    "devices": None,
    "supported": None,
    "live_bytes": None,
    "peak_bytes": None,
    "per_device": None,
}

# Mesh geometry of the most recently submitted run (engine calls
# note_mesh at submit; bench legs may too). Same cached-for-healthz
# contract as _last_poll: the HTTP layer reads this dict, never jax.
_mesh: dict = {}
_fuse_k: int = 1

# memory_stats() key aliases across backends.  TPU/GPU PJRT clients use
# bytes_in_use/peak_bytes_in_use; bytes_limit is best-effort.
_LIVE_KEYS = ("bytes_in_use", "bytes_used", "allocated_bytes")
_PEAK_KEYS = ("peak_bytes_in_use", "peak_bytes")
_LIMIT_KEYS = ("bytes_limit", "bytes_reservable_limit", "largest_alloc_size")


def _first(stats: dict, keys) -> Optional[int]:
    for k in keys:
        v = stats.get(k)
        if v is not None:
            try:
                return int(v)
            except (TypeError, ValueError):
                continue
    return None


def memory_snapshot(device: Any) -> Optional[dict]:
    """Per-device memory stats, or None where the backend has none.

    Returns ``{"live_bytes", "peak_bytes", "limit_bytes", "raw"}``;
    ``raw`` keeps the backend's full stats dict for per-buffer
    breakdowns where available.
    """
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {
        "live_bytes": _first(stats, _LIVE_KEYS),
        "peak_bytes": _first(stats, _PEAK_KEYS),
        "limit_bytes": _first(stats, _LIMIT_KEYS),
        "raw": {k: int(v) for k, v in stats.items()
                if isinstance(v, (int, float))},
    }


def _kind_summary(kinds) -> Optional[str]:
    """Aggregate device-kind strings across a device list: the plain
    kind when homogeneous ("cpu"), '+'-joined sorted distinct kinds
    when mixed ("TPU v4+cpu"), None for an empty list — device 0 does
    not get to speak for a heterogeneous fleet."""
    distinct = sorted({str(k) for k in kinds if k})
    if not distinct:
        return None
    if len(distinct) == 1:
        return distinct[0]
    return "+".join(distinct)


def device_kind() -> Optional[str]:
    """Aggregated kind string over ALL devices ("cpu", "TPU v4",
    "TPU v4+cpu" when mixed), cached."""
    with _lock:
        if _last_poll["device_kind"] is not None:
            return _last_poll["device_kind"]
    try:
        import jax

        kind = _kind_summary(d.device_kind for d in jax.devices())
    except Exception:
        return None
    with _lock:
        _last_poll["device_kind"] = kind
    return kind


def poll_device_memory() -> dict:
    """Poll every addressable device, update gauges, cache for healthz.

    Returns a summary dict; live/peak fields are None on backends
    without memory stats (the graceful-None contract)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        summary = {"device_kind": None, "devices": 0, "supported": False,
                   "live_bytes": None, "peak_bytes": None,
                   "per_device": {}}
        with _lock:
            _last_poll.update(summary)
        return summary

    per_device = {}
    kind_counts: dict = {}
    live_total = peak_total = 0
    supported = False
    supported_devices = 0
    for d in devices:
        dev_id = str(d.id)
        try:
            kind = str(d.device_kind)
        except Exception:
            kind = "unknown"
        kind_counts[kind] = kind_counts.get(kind, 0) + 1
        snap = memory_snapshot(d)
        # Every device gets its supported child, so a heterogeneous
        # list (some devices with stats, some without) is visible per
        # device instead of collapsed into the scalar any-device flag.
        _cat.DEV_MEM_STATS_SUPPORTED.labels(device=dev_id).set(
            0.0 if snap is None else 1.0)
        if snap is None:
            continue
        supported = True
        supported_devices += 1
        per_device[dev_id] = {k: snap[k] for k in
                              ("live_bytes", "peak_bytes", "limit_bytes")}
        if snap["live_bytes"] is not None:
            live_total += snap["live_bytes"]
            _cat.DEV_LIVE_BYTES.labels(device=dev_id).set(
                snap["live_bytes"])
        if snap["peak_bytes"] is not None:
            peak_total += snap["peak_bytes"]
            _cat.DEV_PEAK_BYTES.labels(device=dev_id).set(
                snap["peak_bytes"])
        if snap["limit_bytes"] is not None:
            _cat.DEV_LIMIT_BYTES.labels(device=dev_id).set(
                snap["limit_bytes"])
    _cat.DEV_MEM_SUPPORTED.set(1.0 if supported else 0.0)
    _cat.DEV_DEVICES.set(float(len(devices)))
    for kind, count in kind_counts.items():
        _cat.DEV_KIND_DEVICES.labels(
            kind=_cat.dev_kind_label(kind)).set(float(count))

    summary = {
        "device_kind": _kind_summary(kind_counts),
        "devices": len(devices),
        "supported": supported,
        "supported_devices": supported_devices,
        "device_kinds": kind_counts,
        "live_bytes": live_total if supported else None,
        "peak_bytes": peak_total if supported else None,
        "per_device": per_device,
    }
    with _lock:
        _last_poll.update(summary)
    return summary


def note_mesh(geom: Optional[dict]) -> None:
    """Record the most recently submitted run's mesh geometry (a
    `parallel.mesh.mesh_geometry` dict) and publish the gol_mesh_*
    gauges. Called by the engine at run submit; empty/None input is a
    no-op so a failed geometry probe never clears the last good one."""
    if not geom:
        return
    with _lock:
        _mesh.clear()
        _mesh.update(geom)
    _cat.MESH_DEVICES.set(float(geom.get("devices", 0)))
    _cat.MESH_SHARDS.set(float(geom.get("shards", 0)))
    axes = geom.get("axes") or {}
    for axis in _cat.MESH_AXES:
        _cat.MESH_AXIS_SIZE.labels(axis=axis).set(
            float(axes.get(axis, 0)))


def mesh_fields() -> dict:
    """Cached mesh geometry of the last submitted run ({} before any
    note_mesh) — never imports jax."""
    with _lock:
        return dict(_mesh)


def note_fuse(fuse_k: int) -> None:
    """Record the most recently submitted run's effective temporal-
    fusion depth (1 = auto/unfused) and publish the gol_fuse_k gauge.
    Called by the engines at run submit, read back by the checkpoint
    writer so manifests attribute state to a kernel config."""
    global _fuse_k
    fuse_k = max(1, int(fuse_k))
    with _lock:
        _fuse_k = fuse_k
    _cat.FUSE_K.set(float(fuse_k))


def fuse_field() -> int:
    """Cached fuse depth of the last submitted run (1 before any
    note_fuse) — never imports jax."""
    with _lock:
        return _fuse_k


def healthz_fields() -> dict:
    """Cached device fields for /healthz — never imports jax."""
    with _lock:
        cached = dict(_last_poll)
    return {
        "device_kind": cached["device_kind"],
        "live_bytes": cached["live_bytes"],
        "compile_count": int(_cat.COMPILE_TOTAL.value),
        "mesh": mesh_fields(),
    }


# ------------------------------------------------------------ compile hooks

# jax.monitoring event names (stable across 0.4.x).
_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_duration(event: str, duration: float, **kw) -> None:
    if event == _COMPILE_DURATION_EVENT:
        _cat.COMPILE_TOTAL.inc()
        _cat.COMPILE_SECONDS.observe(duration)


def _on_event(event: str, **kw) -> None:
    if event == _CACHE_HIT_EVENT:
        _cat.COMPILE_CACHE_HITS.inc()
    elif event == _CACHE_MISS_EVENT:
        _cat.COMPILE_CACHE_MISSES.inc()


def install_compile_hooks() -> bool:
    """Register jax.monitoring listeners once per process.

    Returns True if the hooks are (now) installed.  jax.monitoring has
    no unregister API, so the guard is what keeps double-installs from
    double-counting."""
    global _hooks_installed
    with _lock:
        if _hooks_installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        try:
            monitoring.register_event_duration_secs_listener(_on_duration)
            monitoring.register_event_listener(_on_event)
        except Exception:
            return False
        _hooks_installed = True
    return True


def note_signature(key: tuple) -> bool:
    """Record an engine-step signature; True if it is new this process.

    A new (representation, board shape, dtype, mesh, rule) tuple means
    jit will trace and compile a fresh executable — the counter is the
    operator-visible recompile-churn signal."""
    with _lock:
        if key in _signatures:
            return False
        _signatures.add(key)
    _cat.COMPILE_STEP_SIGNATURES.inc()
    return True


def signature_count() -> int:
    with _lock:
        return len(_signatures)


def compiled_cost(compiled: Any) -> Optional[dict]:
    """Normalise compiled.cost_analysis() to {"flops", "bytes_accessed"}.

    cost_analysis() returns a list of dicts on some jax versions and a
    bare dict on others; keys use spaces ("bytes accessed").  Returns
    None when the backend offers no cost model."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed", ca.get("bytes_accessed"))
    if flops is None and nbytes is None:
        return None
    return {
        "flops": float(flops) if flops is not None else None,
        "bytes_accessed": float(nbytes) if nbytes is not None else None,
    }
