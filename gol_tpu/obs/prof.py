"""On-demand jax.profiler capture (the Profile wire method / HTTP trigger).

A process that was started with a profile directory (``--profile-dir``
or ``GOL_PROFILE_DIR``) can be told — at any point, without restart —
to capture the next N engine turns under ``jax.profiler``.  The request
side (wire dispatch thread, HTTP handler, keypress) only *arms* the
controller; the engine run loop is the sole consumer: it drains its
inflight pipeline, takes the request, and runs the N turns
synchronously inside :meth:`ProfileController.capture`, which brackets
them with ``start_trace``/``stop_trace`` and records the artifacts the
profiler wrote (``*.xplane.pb`` for XProf, ``*.trace.json.gz`` for
Perfetto — load either next to the span export from obs/trace.py).

Security posture matches RestoreRun: remote peers never choose the
server's filesystem path.  The capture directory is fixed by whoever
started the process; a Profile request can only pick the turn count.
"""

from __future__ import annotations

import glob
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import List, Optional

from gol_tpu.obs import catalog as _cat

PROFILE_DIR_ENV = "GOL_PROFILE_DIR"
PROFILE_TURNS_ENV = "GOL_PROFILE_TURNS"
DEFAULT_TURNS = 256


class ProfileUnavailable(RuntimeError):
    """No profile directory configured, or a capture is already armed."""


@dataclass
class ProfileRequest:
    turns: int
    directory: str
    source: str
    requested_unix: float = field(default_factory=time.time)


def _scan_artifacts(directory: str, since: float) -> List[str]:
    """Profiler artifacts under `directory` modified at/after `since`."""
    found = []
    for pat in ("**/*.xplane.pb", "**/*.trace.json.gz"):
        for p in glob.glob(os.path.join(directory, pat), recursive=True):
            try:
                if os.path.getmtime(p) >= since - 1.0:
                    found.append(p)
            except OSError:
                continue
    return sorted(found)


class ProfileController:
    """Single-slot arm/take/capture state machine around jax.profiler."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._dir: Optional[str] = None
        self._pending: Optional[ProfileRequest] = None
        self._capturing = False
        self._last: dict = {}

    def configure(self, directory: Optional[str]) -> None:
        """Set (or clear) the capture directory for this process."""
        with self._lock:
            self._dir = os.path.abspath(directory) if directory else None

    @property
    def directory(self) -> Optional[str]:
        with self._lock:
            return self._dir

    def request(self, turns: int = 0, source: str = "wire") -> dict:
        """Arm a capture of the next `turns` engine turns.

        Raises ProfileUnavailable when no directory is configured or a
        capture is already pending/running — the caller maps that to a
        wire/HTTP error rather than silently queueing."""
        turns = int(turns) if turns else 0
        if turns <= 0:
            turns = int(os.environ.get(PROFILE_TURNS_ENV, DEFAULT_TURNS))
        with self._lock:
            if self._dir is None:
                raise ProfileUnavailable(
                    "no profile directory configured (start with "
                    "--profile-dir or GOL_PROFILE_DIR)")
            if self._pending is not None or self._capturing:
                raise ProfileUnavailable("profile capture already armed")
            self._pending = ProfileRequest(turns=turns, directory=self._dir,
                                           source=source)
        _cat.PROFILE_ARMED.set(1.0)
        return {"armed": True, "turns": turns, "dir": self._dir}

    def take(self) -> Optional[ProfileRequest]:
        """Consume the pending request (engine run loop only)."""
        # The engine loop calls this once per chunk iteration; the
        # attribute read is atomic under the GIL, and request() only
        # ever transitions None -> request under the lock, so a racing
        # arm is picked up one iteration later at worst.
        if self._pending is None:
            return None
        with self._lock:
            req, self._pending = self._pending, None
        return req

    @contextmanager
    def capture(self, req: ProfileRequest):
        """Run the engine's capture body under jax.profiler.trace.

        Yields the request; on exit stops the trace, scans for the
        artifacts jax wrote, and records outcome counters + status."""
        os.makedirs(req.directory, exist_ok=True)
        t0 = time.time()
        with self._lock:
            self._capturing = True
        started = False
        status = "error"
        try:
            import jax

            jax.profiler.start_trace(req.directory)
            started = True
            yield req
            status = "ok"
        finally:
            err = None
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:  # never sink the engine loop
                    err = repr(e)
                    status = "error"
            artifacts = _scan_artifacts(req.directory, t0)
            _cat.PROFILE_CAPTURES.labels(status=status).inc()
            _cat.PROFILE_ARMED.set(0.0)
            with self._lock:
                self._capturing = False
                self._last = {
                    "status": status,
                    "turns": req.turns,
                    "source": req.source,
                    "dir": req.directory,
                    "artifacts": artifacts,
                    "seconds": round(time.time() - t0, 3),
                    "finished_unix": int(time.time()),
                }
                if err:
                    self._last["error"] = err

    def status(self) -> dict:
        """Operator-facing snapshot for the /profile endpoint + Profile."""
        with self._lock:
            return {
                "dir": self._dir,
                "armed": self._pending is not None or self._capturing,
                "capturing": self._capturing,
                "pending_turns": (self._pending.turns
                                  if self._pending else None),
                "captures_ok": int(
                    _cat.PROFILE_CAPTURES.labels(status="ok").value),
                "captures_error": int(
                    _cat.PROFILE_CAPTURES.labels(status="error").value),
                "last": dict(self._last),
            }


PROFILER = ProfileController()


def arm_from_env() -> bool:
    """Configure PROFILER from GOL_PROFILE_DIR and arm one capture.

    Used by one-shot CLI runs: `--profile-dir` exports the env var and
    the engine captures the first GOL_PROFILE_TURNS turns of the run.
    Returns True if a capture was armed."""
    directory = os.environ.get(PROFILE_DIR_ENV)
    if not directory:
        return False
    PROFILER.configure(directory)
    try:
        PROFILER.request(source="env")
    except ProfileUnavailable:
        return False
    return True
