"""gol_tpu.obs — runtime observability: metrics registry, chunk
timeline run reports, structured logging, and the /metrics endpoint.

Deliberately jax-free so control-plane processes can import it without
pulling a device runtime. See docs/OBSERVABILITY.md.
"""

from gol_tpu.obs import catalog  # declare every metric family up front
from gol_tpu.obs import devstats, flight, prof, trace
from gol_tpu.obs.flight import FLIGHT, FlightRecorder
from gol_tpu.obs.log import exception, log
from gol_tpu.obs.metrics import REGISTRY, Registry, get_registry
from gol_tpu.obs.timeline import (RUN_REPORT_ENV, SCHEMA, RunReporter,
                                  from_env, read_report, validate_record)
from gol_tpu.obs.trace import TRACER, Span, Tracer

__all__ = [
    "catalog", "REGISTRY", "Registry", "get_registry",
    "RunReporter", "from_env", "read_report", "validate_record",
    "RUN_REPORT_ENV", "SCHEMA", "log", "exception",
    "trace", "flight", "TRACER", "Tracer", "Span",
    "FLIGHT", "FlightRecorder",
    "devstats", "prof", "PROFILER",
]

from gol_tpu.obs.prof import PROFILER  # noqa: E402  (jax-free; lazy jax)
