"""Per-run usage metering and capacity attribution (PR 19).

The fleet engine dispatches one compiled program per bucket quantum; a
quantum's wall clock is the only device time there is, so attribution
is an apportionment problem: split each measured dispatch wall across
the slots that were active in it.  Batch placement shares one program
across slots, so each active run gets ``elapsed / n_active``.  Spatial
placement gives every device to one board per dispatch, so each run is
charged the FULL quantum and the conservation denominator grows by the
same amount — per-run shares always sum to the measured wall, and the
gated ``usage_attribution_error_pct`` stays at float-rounding noise.

Cardinality posture (PR 8): exact accumulators exist only for runs the
owning member's engine registered via :func:`track` — a dict bounded by
the admission controller's ``max_runs``, not by lifetime run count.
Charges for unknown run ids fold into a single ``untracked`` aggregate
so late broadcast/checkpoint stragglers after a destroy can never grow
the map.  No per-run metric labels, ever: per-run detail lives on the
reference-swapped ``/healthz`` ``"usage"`` doc (top-K talkers, K
bounded by ``GOL_USAGE_TOPK``), the same pattern as
``obs_slo.set_fleet_health``.

Hot-path contract (PR 6): the engine loop only appends plain tuples to
a local list; everything here runs at the batched <=0.5 s flush.  All
meter work is self-timed into ``gol_usage_wall_us_total`` so
``bench.py --usage`` can gate ``usage_overhead_pct`` as a wall share,
the same contention-immune method as ``journal_overhead_pct``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from gol_tpu.obs import catalog as obs
from gol_tpu.utils.envcfg import env_float, env_int

TOPK_ENV = "GOL_USAGE_TOPK"
TOPK_DEFAULT = 8
FLUSH_ENV = "GOL_USAGE_FLUSH_S"
FLUSH_DEFAULT_S = 0.5

#: Compact per-run entries exported on the heartbeat snapshot ("use"
#: family) — deliberately smaller than the /healthz top-K so the
#: lowest-priority family stays cheap under GOL_FED_SNAPSHOT_MAX.
SNAP_TOP = 4


class RunUsage:
    """Exact accumulators for one resident run."""

    __slots__ = ("run_id", "device_s", "dispatches", "turns", "cells",
                 "wire_in", "wire_out", "bc_frames", "bc_bytes",
                 "sent_frames", "sent_bytes", "ckpt_bytes",
                 "journal_bytes", "created_s")

    def __init__(self, run_id: str, now: float) -> None:
        self.run_id = run_id
        self.device_s = 0.0      # apportioned dispatch wall
        self.dispatches = 0
        self.turns = 0
        self.cells = 0           # cell-updates advanced
        self.wire_in = 0         # RPC request bytes (ConnectionEncoder)
        self.wire_out = 0        # RPC reply bytes
        self.bc_frames = 0       # broadcast frames published
        self.bc_bytes = 0        # broadcast bytes published (pre fan-out)
        self.sent_frames = 0     # delivered frames, summed over subscribers
        self.sent_bytes = 0      # delivered bytes, summed over subscribers
        self.ckpt_bytes = 0
        self.journal_bytes = 0
        self.created_s = now

    def record(self, now: Optional[float] = None) -> dict:
        """Flat JSON-scalar record (journal ``usage`` event payload)."""
        rec = {
            "device_s": round(self.device_s, 6),
            "dispatches": self.dispatches,
            "turns": self.turns,
            "cells": self.cells,
            "wire_in": self.wire_in,
            "wire_out": self.wire_out,
            "bc_frames": self.bc_frames,
            "bc_bytes": self.bc_bytes,
            "sent_frames": self.sent_frames,
            "sent_bytes": self.sent_bytes,
            "ckpt_bytes": self.ckpt_bytes,
            "journal_bytes": self.journal_bytes,
        }
        if now is not None:
            rec["dur_s"] = round(max(0.0, now - self.created_s), 3)
        return rec


class UsageMeter:
    """Bounded per-run cost attribution for one member.

    Thread model: the engine loop never calls in here (it hands tuples
    to :meth:`ingest_dispatches` at its own flush); RPC threads, the
    gateway event loop and the checkpoint/journal writers do, so state
    moves under one small lock.  The public doc is swapped by
    reference and read without the lock (atomic under the GIL).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._runs: Dict[str, RunUsage] = {}
        self._untracked = RunUsage("", 0.0)
        self._untracked_events = 0
        # Conservation survives destroys: retired totals keep the
        # lifetime sums comparable against the lifetime wall.
        self._retired_device_s = 0.0
        self._retired_runs = 0
        self._wall_s = 0.0        # measured dispatch wall (denominator)
        self._attributed_s = 0.0  # sum of per-run shares (numerator)
        self._capacity: List[dict] = []
        self._doc: dict = {}
        self._doc_ts = 0.0

    # -- self-timing ---------------------------------------------------

    @staticmethod
    def _wall_begin() -> float:
        return time.perf_counter()

    @staticmethod
    def _wall_end(t0: float) -> None:
        obs.USAGE_WALL_US.inc((time.perf_counter() - t0) * 1e6)

    # -- lifecycle -------------------------------------------------------

    def track(self, run_id: str) -> None:
        """Open exact accumulators for a resident run (engine only)."""
        t0 = self._wall_begin()
        with self._lock:
            if run_id not in self._runs:
                self._runs[run_id] = RunUsage(run_id, time.time())
                obs.USAGE_RUNS_TRACKED.set(len(self._runs))
        self._wall_end(t0)

    def retire(self, run_id: str) -> Optional[dict]:
        """Close out a run; returns its final record, or None if the
        run was never tracked / already retired (idempotent — the
        migrate-out path retires before ``_remove_locked`` runs)."""
        t0 = self._wall_begin()
        now = time.time()
        with self._lock:
            u = self._runs.pop(run_id, None)
            if u is not None:
                self._retired_device_s += u.device_s
                self._retired_runs += 1
                obs.USAGE_RUNS_TRACKED.set(len(self._runs))
                rec = u.record(now)
            else:
                rec = None
        self._wall_end(t0)
        return rec

    def _get(self, run_id) -> RunUsage:
        """Accumulators for ``run_id``; the untracked fold otherwise.
        Callers hold the lock."""
        u = self._runs.get(run_id) if run_id else None
        if u is None:
            self._untracked_events += 1
            obs.USAGE_UNTRACKED.inc()
            return self._untracked
        return u

    # -- charging --------------------------------------------------------

    def ingest_dispatches(
            self,
            batch: Sequence[Tuple[str, float, int,
                                  Sequence[Tuple[str, int]]]]) -> None:
        """Apportion a flush window's dispatches.

        Each entry is ``(placement, elapsed_s, chunk_turns, active)``
        with ``active`` the ``(run_id, cells_per_turn)`` pairs stepped
        in that dispatch.  Spatial placement serializes boards across
        the full device mesh, so every active run is charged the whole
        quantum and the wall denominator scales with ``n`` — shares
        stay conservative under both placements.
        """
        if not batch:
            return
        t0 = self._wall_begin()
        with self._lock:
            for placement, elapsed, chunk, active in batch:
                n = len(active)
                if not n:
                    self._wall_s += elapsed
                    continue
                if placement == "spatial":
                    share = elapsed
                    self._wall_s += elapsed * n
                else:
                    share = elapsed / n
                    self._wall_s += elapsed
                for rid, cells in active:
                    u = self._get(rid)
                    u.device_s += share
                    u.dispatches += 1
                    u.turns += chunk
                    u.cells += chunk * cells
                    self._attributed_s += share
        self._wall_end(t0)

    def charge_turns(self, run_id: str, turns: int, cells: int) -> None:
        """Host-side turn advancement (e.g. fuse-trim) with no
        measured dispatch wall behind it."""
        t0 = self._wall_begin()
        with self._lock:
            u = self._get(run_id)
            u.turns += turns
            u.cells += cells
        self._wall_end(t0)

    def charge_wire(self, run_id, bytes_in: int, bytes_out: int) -> None:
        t0 = self._wall_begin()
        with self._lock:
            u = self._get(run_id)
            u.wire_in += bytes_in
            u.wire_out += bytes_out
        self._wall_end(t0)

    def charge_broadcast(self, run_id, frames: int, nbytes: int) -> None:
        """Publish-side frames (pre fan-out), from the PR-14 hub."""
        t0 = self._wall_begin()
        with self._lock:
            u = self._get(run_id)
            u.bc_frames += frames
            u.bc_bytes += nbytes
        self._wall_end(t0)

    def charge_broadcast_sent(
            self, pend: Dict[str, Sequence[int]]) -> None:
        """Delivered (run -> [frames, bytes]) batch from the gateway's
        0.5 s flush — per-subscriber sends pre-summed per run."""
        if not pend:
            return
        t0 = self._wall_begin()
        with self._lock:
            for rid, (frames, nbytes) in pend.items():
                u = self._get(rid)
                u.sent_frames += frames
                u.sent_bytes += nbytes
        self._wall_end(t0)

    def charge_ckpt(self, run_id, nbytes: int) -> None:
        t0 = self._wall_begin()
        with self._lock:
            self._get(run_id).ckpt_bytes += nbytes
        self._wall_end(t0)

    def charge_journal(self, run_id, nbytes: int) -> None:
        t0 = self._wall_begin()
        with self._lock:
            self._get(run_id).journal_bytes += nbytes
        self._wall_end(t0)

    # -- publication -----------------------------------------------------

    def publish(self, now: Optional[float] = None,
                capacity: Optional[Iterable[dict]] = None) -> None:
        """Rebuild the reference-swapped doc and set gauges.  Called
        from the engine flush; throttled to ``GOL_USAGE_FLUSH_S`` so
        RPC-driven rebuilds stay cheap too."""
        t0 = self._wall_begin()
        now = time.time() if now is None else now
        flush_s = env_float(FLUSH_ENV, FLUSH_DEFAULT_S)
        with self._lock:
            if capacity is not None:
                self._capacity = list(capacity)
                for row in self._capacity:
                    b = str(row.get("bucket", ""))
                    obs.CAPACITY_ADMISSIBLE_RUNS.labels(bucket=b).set(
                        row.get("admissible", 0))
                    obs.CAPACITY_CUPS_HEADROOM.labels(bucket=b).set(
                        row.get("cups_headroom", 0.0))
                    obs.CAPACITY_RUN_COST_BYTES.labels(bucket=b).set(
                        row.get("run_cost_bytes", 0))
                if self._capacity:
                    obs.CAPACITY_FREE_BYTES.set(
                        self._capacity[0].get("free_bytes", 0))
            if now - self._doc_ts >= flush_s or not self._doc:
                self._doc = self._build_doc_locked(now)
                self._doc_ts = now
                obs.USAGE_FLUSHES.inc()
        self._wall_end(t0)

    def _build_doc_locked(self, now: float) -> dict:
        k = env_int(TOPK_ENV, TOPK_DEFAULT)
        ranked = sorted(self._runs.values(),
                        key=lambda u: u.device_s, reverse=True)
        total = self._attributed_s
        top = []
        for u in ranked[:k]:
            row = u.record(now)
            row["run_id"] = u.run_id
            row["share_pct"] = round(
                u.device_s / total * 100.0, 2) if total > 0 else 0.0
            top.append(row)
        err_pct = (abs(self._attributed_s - self._wall_s)
                   / self._wall_s * 100.0) if self._wall_s > 0 else 0.0
        doc = {
            "runs_tracked": len(self._runs),
            "retired_runs": self._retired_runs,
            "k": k,
            "top": top,
            "attribution": {
                "wall_s": round(self._wall_s, 6),
                "attributed_s": round(self._attributed_s, 6),
                "error_pct": round(err_pct, 4),
            },
            "untracked": {
                "events": self._untracked_events,
                "device_s": round(self._untracked.device_s, 6),
                "wire_in": self._untracked.wire_in,
                "wire_out": self._untracked.wire_out,
                "bc_bytes": self._untracked.bc_bytes,
            },
            "capacity": list(self._capacity),
        }
        return doc

    def usage_doc(self, now: Optional[float] = None) -> dict:
        """Current usage doc; rebuilt lazily when stale so members
        without a live engine flush (pure RPC contexts) still answer
        ``GetUsage`` with fresh numbers."""
        now = time.time() if now is None else now
        if now - self._doc_ts >= env_float(FLUSH_ENV, FLUSH_DEFAULT_S):
            self.publish(now)
        return self._doc

    def run_doc(self, run_id: str) -> dict:
        """Live record for one tracked run; KeyError when absent (the
        server maps it onto the standard unknown-run redirect)."""
        with self._lock:
            u = self._runs.get(run_id)
            if u is None:
                raise KeyError(f"unknown run {run_id!r}")
            rec = u.record(time.time())
            rec["run_id"] = run_id
            return rec

    def export_summary(self) -> Optional[dict]:
        """Compact "use" family for the PR-16 heartbeat snapshot:
        scalars plus a tiny top list (``[[run_id, device_s], ...]``,
        at most ``SNAP_TOP`` rows).  None when there is nothing to
        report so the family costs zero snapshot bytes at idle."""
        with self._lock:
            if not self._runs and not self._capacity:
                return None
            ranked = sorted(self._runs.values(),
                            key=lambda u: u.device_s, reverse=True)
            adm = 0
            hr = 0.0
            for row in self._capacity:
                adm = max(adm, int(row.get("admissible", 0)))
                hr += float(row.get("cups_headroom", 0.0))
            return {
                "tracked": len(self._runs),
                "adm": adm,
                "hr": round(hr, 1),
                "top": [[u.run_id, round(u.device_s, 4)]
                        for u in ranked[:SNAP_TOP]],
            }

    def reset(self) -> None:
        """Test/bench hook: drop all state (not used in serving)."""
        with self._lock:
            self._runs.clear()
            self._untracked = RunUsage("", 0.0)
            self._untracked_events = 0
            self._retired_device_s = 0.0
            self._retired_runs = 0
            self._wall_s = 0.0
            self._attributed_s = 0.0
            self._capacity = []
            self._doc = {}
            self._doc_ts = 0.0
            obs.USAGE_RUNS_TRACKED.set(0)


METER = UsageMeter()


def usage_doc() -> dict:
    """Module-level accessor used by /healthz and the GetUsage RPC."""
    return METER.usage_doc()
