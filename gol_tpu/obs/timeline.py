"""Chunk-timeline run reports: JSON-lines records of how a run paced.

One `RunReporter` per engine run, activated by `GOL_RUN_REPORT=PATH`
(the `--run-report` CLI flag sets the env var, mirroring how `--trace`
drives `GOL_TRACE`). The engine chunk loop appends one record per
retired chunk plus `run_start` / `run_end` bookends; bench.py's
`--self-report` emits the same schema so bench artifacts and production
telemetry agree (this is the same schema *family* as the BENCH_*.json
one-JSON-object-per-line reports).

Schema `gol-run-report/1` — every record is one JSON object per line:

    common       schema, event, run_id, t (seconds since run start)
    run_start    w, h, model, repr, devices, turns_requested
    chunk        turn (after), turns (in chunk), chunk_size, wall_s,
                 cups, turns_per_s, token_wait_s, dispatch_s, flag_s,
                 alive
    traced_chunk turn, turns (profiler path: no wall/cups — excluded
                 from pace aggregates by design)
    run_end      turn, turns_total, chunks, traced_chunks, wall_s
    bench_leg    value (+ metric/unit/vs_baseline/detail — bench.py's
                 --self-report mirror of its stdout BENCH lines)

Reporter failures (disk full, bad path) must never sink a run: the
shared `obs.sink.GuardedLineSink` disables the reporter after the
first OSError and the engine carries on unmetered.
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterator, Optional

from gol_tpu.obs.sink import GuardedLineSink

SCHEMA = "gol-run-report/1"
RUN_REPORT_ENV = "GOL_RUN_REPORT"

_EVENT_FIELDS = {
    "run_start": ("w", "h"),
    "chunk": ("turn", "turns", "wall_s", "cups"),
    "traced_chunk": ("turn", "turns"),
    "run_end": ("turn", "turns_total", "chunks"),
    "bench_leg": ("value",),
}


class RunReporter:
    """Append-only JSON-lines writer for one run's timeline."""

    def __init__(self, path: str, run_id: Optional[str] = None) -> None:
        self.path = path
        self.run_id = run_id or f"run-{os.getpid()}-{int(time.time())}"
        self._t0 = time.monotonic()
        self._sink = GuardedLineSink(path)

    def emit(self, event: str, **fields) -> None:
        if self._sink.dead:
            return
        rec = {"schema": SCHEMA, "event": event, "run_id": self.run_id,
               "t": round(time.monotonic() - self._t0, 6)}
        rec.update(fields)
        self._sink.write_line(json.dumps(rec, sort_keys=True))

    def close(self) -> None:
        self._sink.close()


def from_env(environ=os.environ) -> Optional[RunReporter]:
    """A reporter for the path in GOL_RUN_REPORT, or None if unset."""
    path = environ.get(RUN_REPORT_ENV, "").strip()
    return RunReporter(path) if path else None


# ------------------------------------------------------------ validation

def validate_record(rec: dict) -> None:
    """Raise ValueError unless `rec` is a valid run-report record.
    Extra keys are fine (the schema grows by addition); missing
    required keys or wrong shapes are not."""
    if not isinstance(rec, dict):
        raise ValueError(f"record is {type(rec).__name__}, not object")
    if rec.get("schema") != SCHEMA:
        raise ValueError(f"schema {rec.get('schema')!r} != {SCHEMA!r}")
    event = rec.get("event")
    if event not in _EVENT_FIELDS:
        raise ValueError(f"unknown event {event!r}")
    if not isinstance(rec.get("run_id"), str) or not rec["run_id"]:
        raise ValueError("missing run_id")
    if not isinstance(rec.get("t"), (int, float)) or rec["t"] < 0:
        raise ValueError(f"bad t {rec.get('t')!r}")
    for key in _EVENT_FIELDS[event]:
        if key not in rec:
            raise ValueError(f"{event} record missing {key!r}")
        if not isinstance(rec[key], (int, float)):
            raise ValueError(
                f"{event}.{key} is {type(rec[key]).__name__}, "
                f"not a number")
    if event == "chunk":
        if rec["wall_s"] < 0:
            raise ValueError(f"chunk.wall_s {rec['wall_s']!r} < 0")
        if rec["turns"] <= 0:
            raise ValueError(f"chunk.turns {rec['turns']!r} <= 0")


def read_report(path: str) -> Iterator[dict]:
    """Parse and validate a run-report file, yielding records in order."""
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from e
            try:
                validate_record(rec)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: {e}") from e
            yield rec
