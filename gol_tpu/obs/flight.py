"""Crash-time flight recorder: bounded ring of recent spans + events.

A black box for the moments aggregate metrics can't explain: every
finished span (`obs/trace.py`) and every structured-log event
(`obs/log.py`) is also appended to a small in-memory ring, and when
something dies — SIGTERM on the server, the client heartbeat watchdog
declaring the engine lost, an unhandled exception in the engine chunk
loop — the ring is dumped as ONE JSON document (schema `gol-flight/1`)
containing the recent spans, the spans still OPEN at the instant of
death (the in-flight RPC / chunk), the recent log events, and a full
metrics-registry snapshot.

Recording is always on (a deque append per span/event — far below the
obs overhead budget); *writing* a dump needs `GOL_FLIGHT=PATH` (a file
path, or a directory to get one file per pid+reason). With it unset a
trigger still logs and counts, but writes nothing — a crash handler
must never surprise an operator with files. Dump failures are
swallowed: the flight recorder exists to explain deaths, not cause
them.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from gol_tpu.obs import catalog as obs
from gol_tpu.obs.metrics import REGISTRY

FLIGHT_ENV = "GOL_FLIGHT"          # dump destination (file or directory)
FLIGHT_CAP_ENV = "GOL_FLIGHT_CAP"  # ring size (spans and events each)
FLIGHT_CAP_DEFAULT = 256
SCHEMA = "gol-flight/1"

# Process-level identity shared by /healthz and every flight dump (the
# per-run-report ids in obs/timeline.py are per-RUN; this one names the
# process for the whole of its life).
RUN_ID = f"run-{os.getpid()}-{int(time.time())}"
_T0 = time.monotonic()


def uptime_s() -> float:
    """Seconds since gol_tpu.obs was first imported in this process."""
    return time.monotonic() - _T0


class FlightRecorder:
    """Thread-safe bounded ring of span records and log-event records,
    plus registered providers for spans still open at dump time."""

    def __init__(self, cap: Optional[int] = None) -> None:
        if cap is None:
            try:
                cap = int(os.environ.get(FLIGHT_CAP_ENV,
                                         FLIGHT_CAP_DEFAULT))
            except ValueError:
                cap = FLIGHT_CAP_DEFAULT
        cap = max(int(cap), 1)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=cap)
        self._events: deque = deque(maxlen=cap)
        # Callables returning a list of OPEN-span dicts (the tracer
        # registers one) — what was in flight when the trigger fired is
        # exactly what a post-mortem needs.
        self._providers: List[Callable[[], List[dict]]] = []

    def record_span(self, rec: dict) -> None:
        with self._lock:
            self._spans.append(rec)

    def record_event(self, rec: dict) -> None:
        with self._lock:
            self._events.append(rec)

    def register_open_spans_provider(
            self, fn: Callable[[], List[dict]]) -> None:
        with self._lock:
            if fn not in self._providers:
                self._providers.append(fn)

    def snapshot(self, reason: str = "manual") -> dict:
        """The dump document (JSON-serializable)."""
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
            providers = list(self._providers)
        open_spans: List[dict] = []
        for fn in providers:
            try:
                open_spans.extend(fn())
            except Exception:
                pass  # a broken provider must not sink the dump
        return {
            "schema": SCHEMA,
            "reason": reason,
            "run_id": RUN_ID,
            "pid": os.getpid(),
            "ts": round(time.time(), 3),
            "uptime_s": round(uptime_s(), 3),
            "open_spans": open_spans,
            "spans": spans,
            "events": events,
            "metrics": REGISTRY.snapshot(),
        }

    def resolve_path(self, reason: str,
                     path: Optional[str] = None) -> Optional[str]:
        """Explicit path, else GOL_FLIGHT (a directory gets one file per
        pid+reason), else None (dump disabled)."""
        p = path or os.environ.get(FLIGHT_ENV, "").strip()
        if not p:
            return None
        if os.path.isdir(p) or p.endswith(os.sep):
            safe = re.sub(r"[^A-Za-z0-9_.-]+", "-", reason) or "unknown"
            p = os.path.join(p, f"gol-flight-{os.getpid()}-{safe}.json")
        return p

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> Optional[str]:
        """Write the snapshot as JSON; returns the path written, or None
        (disabled or failed). Never raises — crash handlers call this."""
        try:
            target = self.resolve_path(reason, path)
            doc = self.snapshot(reason)
            if target is None:
                return None
            tmp = f"{target}.{os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, default=str)
                f.write("\n")
            os.replace(tmp, target)
            obs.FLIGHT_DUMPS_TOTAL.labels(
                reason=obs.flight_reason_label(reason)).inc()
            return target
        except Exception:
            return None


# The process-wide recorder — what the tracer, the structured logger,
# and every crash trigger share.
FLIGHT = FlightRecorder()


def crash(event: str, exc: BaseException,
          reason: str = "exception", **fields) -> Optional[str]:
    """Record an exception event into the ring and dump: the one-call
    crash trigger for the engine loop / server dispatch. Never raises."""
    try:
        import traceback

        rec = {"ts": round(time.time(), 3), "level": "error",
               "event": event,
               "error": f"{type(exc).__name__}: {exc}",
               "traceback": "".join(traceback.format_exception(
                   type(exc), exc, exc.__traceback__))}
        rec.update(fields)
        FLIGHT.record_event(rec)
        return FLIGHT.dump(reason)
    except Exception:
        return None


def validate_dump(doc: dict) -> None:
    """Raise ValueError unless `doc` is a structurally valid flight
    dump (the obs-smoke / test-side check)."""
    if not isinstance(doc, dict):
        raise ValueError(f"dump is {type(doc).__name__}, not object")
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"schema {doc.get('schema')!r} != {SCHEMA!r}")
    for key in ("reason", "run_id"):
        if not isinstance(doc.get(key), str) or not doc[key]:
            raise ValueError(f"missing {key}")
    for key in ("ts", "uptime_s", "pid"):
        if not isinstance(doc.get(key), (int, float)):
            raise ValueError(f"bad {key} {doc.get(key)!r}")
    for key in ("open_spans", "spans", "events"):
        if not isinstance(doc.get(key), list):
            raise ValueError(f"{key} is not a list")
    if not isinstance(doc.get("metrics"), dict):
        raise ValueError("metrics is not a dict")
