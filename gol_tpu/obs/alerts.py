"""Declarative threshold alerting over the aggregated fleet signals.

Rules fire on the signal dict the registry-side aggregator
(obs/export.py `FleetTelemetry`) computes at every router sweep — the
same rollups published as `gol_fed_agg_*` — with firing→resolved
hysteresis so a flapping signal cannot strobe the alert surface:

    inactive --breach--> pending --breach for `for_s`--> firing
    firing   --clear continuously for `clear_s`-->       inactive

A breach that clears while pending quietly resets (no event). A
firing rule that dips below threshold starts a clear timer; any
breach inside `clear_s` cancels it (flap suppression). Transitions
are fully observable: `gol_alerts_active{rule}` (0/1),
`gol_alerts_fired_total{rule}`, a flight-recorder event, and an
audit-log record per transition (via the callback the router wires).

Built-in rules (thresholds env-tunable):

    member-death        members_dead > 0            (immediate)
    staleness-ceiling   staleness_p99_ms > GOL_ALERT_STALENESS_MS
    queue-depth         queue_depth > GOL_ALERT_QUEUE_DEPTH
    resident-imbalance  imbalance_ratio > GOL_ALERT_IMBALANCE
                        (needs >= 2 members reporting)

Extra rules ride GOL_ALERT_RULES, a JSON list:

    [{"name": "cups-floor", "signal": "cups", "op": "<",
      "threshold": 1e6, "for_s": 10, "clear_s": 30}]

A rule whose signal is absent from the evaluation dict is skipped in
place — state and timers hold — so a member dropping a snapshot
family cannot fake a resolve. Stdlib-only, no jax import.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

__all__ = ["AlertRule", "AlertManager", "builtin_rules", "rules_from_env"]

ENV_RULES = "GOL_ALERT_RULES"
ENV_STALENESS = "GOL_ALERT_STALENESS_MS"
ENV_QUEUE = "GOL_ALERT_QUEUE_DEPTH"
ENV_IMBALANCE = "GOL_ALERT_IMBALANCE"
ENV_FOR = "GOL_ALERT_FOR_S"
ENV_CLEAR = "GOL_ALERT_CLEAR_S"

DEFAULT_STALENESS_MS = 30_000.0
DEFAULT_QUEUE_DEPTH = 64.0
DEFAULT_IMBALANCE = 3.0
DEFAULT_FOR_S = 1.0
DEFAULT_CLEAR_S = 5.0

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

# States of the per-rule hysteresis machine.
INACTIVE, PENDING, FIRING = "inactive", "pending", "firing"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class AlertRule:
    """One threshold rule. `requires` names signals that must be
    present (and truthy-nonzero) for the rule to evaluate at all —
    resident-imbalance uses it to stay quiet on 0/1-member fleets."""

    __slots__ = ("name", "signal", "op", "threshold", "for_s",
                 "clear_s", "requires")

    def __init__(self, name: str, signal: str, op: str = ">",
                 threshold: float = 0.0, for_s: float = 0.0,
                 clear_s: Optional[float] = None,
                 requires: tuple = ()) -> None:
        if op not in _OPS:
            raise ValueError(f"alert rule {name!r}: bad op {op!r}")
        self.name = str(name)
        self.signal = str(signal)
        self.op = op
        self.threshold = float(threshold)
        self.for_s = max(float(for_s), 0.0)
        self.clear_s = (_env_float(ENV_CLEAR, DEFAULT_CLEAR_S)
                        if clear_s is None else max(float(clear_s), 0.0))
        self.requires = tuple(requires)

    def breached(self, value: float) -> bool:
        return _OPS[self.op](float(value), self.threshold)

    def doc(self) -> dict:
        return {"name": self.name, "signal": self.signal, "op": self.op,
                "threshold": self.threshold, "for_s": self.for_s,
                "clear_s": self.clear_s}


def builtin_rules() -> List[AlertRule]:
    for_s = _env_float(ENV_FOR, DEFAULT_FOR_S)
    return [
        # A death verdict is already debounced by GOL_FED_DEAD_AFTER;
        # alerting adds zero extra latency on top of it.
        AlertRule("member-death", "members_dead", ">", 0.0, for_s=0.0),
        AlertRule("staleness-ceiling", "staleness_p99_ms", ">",
                  _env_float(ENV_STALENESS, DEFAULT_STALENESS_MS),
                  for_s=for_s),
        AlertRule("queue-depth", "queue_depth", ">",
                  _env_float(ENV_QUEUE, DEFAULT_QUEUE_DEPTH),
                  for_s=for_s),
        AlertRule("resident-imbalance", "imbalance_ratio", ">",
                  _env_float(ENV_IMBALANCE, DEFAULT_IMBALANCE),
                  for_s=for_s, requires=("members_multi",)),
    ]


def rules_from_env() -> List[AlertRule]:
    raw = os.environ.get(ENV_RULES, "").strip()
    if not raw:
        return []
    try:
        specs = json.loads(raw)
        return [AlertRule(
            name=s["name"], signal=s["signal"],
            op=s.get("op", ">"), threshold=s.get("threshold", 0.0),
            for_s=s.get("for_s", 0.0), clear_s=s.get("clear_s"),
        ) for s in specs]
    except (ValueError, KeyError, TypeError) as e:
        try:
            from gol_tpu.obs.log import log as obs_log
            obs_log("alerts.bad_rules", level="warning", error=repr(e))
        except Exception:
            pass
        return []


class _RuleState:
    __slots__ = ("state", "since", "clear_since", "value")

    def __init__(self) -> None:
        self.state = INACTIVE
        self.since = 0.0        # breach start (pending) / fire time
        self.clear_since = 0.0  # 0 = no clear window open
        self.value = 0.0


class AlertManager:
    """Evaluates the rule set against one signals dict per sweep.

    Single-threaded by contract (the router's sweep loop); the
    transition sink (`on_transition(rule, event, value, now)`) is
    where the router hangs its audit-log append."""

    def __init__(self, rules: Optional[List[AlertRule]] = None,
                 on_transition=None) -> None:
        self.rules = (builtin_rules() + rules_from_env()
                      if rules is None else list(rules))
        self.on_transition = on_transition
        self._state = {r.name: _RuleState() for r in self.rules}
        self._seed_metrics()

    def _seed_metrics(self) -> None:
        try:
            from gol_tpu.obs import catalog as obs
            for r in self.rules:
                obs.ALERTS_ACTIVE.labels(rule=r.name).set(0)
                obs.ALERTS_FIRED.labels(rule=r.name)
        except Exception:
            pass

    # --------------------------------------------------------- evaluate

    def evaluate(self, signals: dict,
                 now: Optional[float] = None) -> list:
        """One sweep. Returns the transitions that happened:
        [{"rule", "event": "fired"|"resolved", "value"}, ...]."""
        if now is None:
            now = time.time()
        transitions = []
        for rule in self.rules:
            st = self._state[rule.name]
            if any(not signals.get(req) for req in rule.requires):
                continue
            value = signals.get(rule.signal)
            if value is None:
                continue  # no data: hold state, no timer progress
            value = float(value)
            st.value = value
            breach = rule.breached(value)
            if st.state == INACTIVE and breach:
                # Enter pending; a for_s of 0 promotes to firing in the
                # same sweep (now - since == 0 satisfies >= 0).
                st.since = now
                st.state = PENDING
            if st.state == PENDING:
                if not breach:
                    st.state = INACTIVE
                elif now - st.since >= rule.for_s:
                    st.state = FIRING
                    st.since = now
                    st.clear_since = 0.0
                    self._transition(rule, "fired", value, now)
                    transitions.append({"rule": rule.name,
                                        "event": "fired",
                                        "value": value})
            elif st.state == FIRING:
                if breach:
                    st.clear_since = 0.0  # flap: cancel the clear window
                else:
                    if st.clear_since == 0.0:
                        st.clear_since = now
                    if now - st.clear_since >= rule.clear_s:
                        st.state = INACTIVE
                        st.clear_since = 0.0
                        self._transition(rule, "resolved", value, now)
                        transitions.append({"rule": rule.name,
                                            "event": "resolved",
                                            "value": value})
        return transitions

    def _transition(self, rule: AlertRule, event: str, value: float,
                    now: float) -> None:
        try:
            from gol_tpu.obs import catalog as obs
            obs.ALERTS_ACTIVE.labels(rule=rule.name).set(
                1 if event == "fired" else 0)
            if event == "fired":
                obs.ALERTS_FIRED.labels(rule=rule.name).inc()
        except Exception:
            pass
        try:
            from gol_tpu.obs.log import log as obs_log
            obs_log(f"alert.{event}", level="warning", rule=rule.name,
                    signal=rule.signal, value=value,
                    threshold=rule.threshold)
        except Exception:
            pass
        if self.on_transition is not None:
            try:
                self.on_transition(rule, event, value, now)
            except Exception:
                pass

    # ------------------------------------------------------------ reads

    def active(self) -> dict:
        return {name: {"since": st.since, "value": st.value}
                for name, st in self._state.items()
                if st.state == FIRING}

    def doc(self) -> dict:
        return {
            "rules": [r.doc() for r in self.rules],
            "active": self.active(),
            "states": {name: st.state
                       for name, st in self._state.items()},
        }
