"""Federated telemetry export/ingest: snapshots on the heartbeat.

Member half — `SnapshotExporter`. Every `RegisterMember` beat may
carry a `"snap"` key: a compact, delta-encoded snapshot of selected
catalog families (resident/queue counts, staleness & quantum
quantiles, SLO breach totals, CUPS, device memory) plus any pending
member audit events (obs/audit.note). Collection therefore costs ZERO
extra connections — the ops-per-byte discipline applied to
observability: telemetry rides bytes we already pay for.

    {"v": 1, "full": 1,                # first beat / after resync
     "m": {"res": 4, "q": 0,           # family values (short keys)
           "st": {"p50": 12.0, ...},   # staleness quantiles, ms
           "qt": {"64x64x8|p99": 3.1}, # quantum quantiles, ms
           "slo": 0, "cups": 2.1e8,
           "dev": {"live": 0, "peak": 0}},
     "ev": [{...audit events...}]}

Delta encoding is commit-on-ack: the baseline advances only after the
router acknowledged the beat, so a lost beat naturally re-ships its
changes. A router that has no state for a delta (restart) replies
`"snap_resync": true` and the next beat is full. The encoded snapshot
must fit `GOL_FED_SNAPSHOT_MAX` bytes (default 4 KiB; <= 0 disables
export entirely): an over-budget snapshot degrades by dropping its
LOWEST-priority families (metered via
gol_fed_snapshot_dropped_total{family}), then halving events — it
never fails or fattens the heartbeat past budget.

Registry half — `FleetTelemetry`. Ingests snapshots into the bounded
tsdb (obs/tsdb.py), computes fleet rollups at every router sweep
(resident total, aggregate CUPS, staleness p99 across members,
resident imbalance ratio — the exact signals ROADMAP item 3's
autoscaler consumes), publishes them as `gol_fed_agg_*` families,
feeds the alert manager (obs/alerts.py), and reference-swaps a
`"telemetry"` document for /healthz and `GetTelemetry`.

Stdlib-only, no jax import.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from gol_tpu.obs import audit as obs_audit
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import slo as obs_slo
from gol_tpu.obs.alerts import AlertManager
from gol_tpu.obs.tsdb import TSDB
from gol_tpu.utils.envcfg import env_float

__all__ = ["SnapshotExporter", "FleetTelemetry", "collect_families",
           "local_doc", "snapshot_budget", "set_active_telemetry",
           "active_telemetry_doc"]

SNAPSHOT_MAX_ENV = "GOL_FED_SNAPSHOT_MAX"
SNAPSHOT_MAX_DEFAULT = 4096
SNAPSHOT_VERSION = 1
EVENTS_PER_BEAT = 32

# Family keys in PRIORITY order (first = most important = dropped
# last when the encoding exceeds the byte budget). Short keys keep the
# wire encoding compact; the long names are the metric label values.
FAMILY_PRIORITY = ("res", "q", "st", "qt", "slo", "cups", "dev", "use")
FAMILY_LABELS = {"res": "resident", "q": "queue", "st": "staleness",
                 "qt": "quantum", "slo": "slo", "cups": "cups",
                 "dev": "dev_bytes", "use": "usage"}


def snapshot_budget() -> float:
    return env_float(SNAPSHOT_MAX_ENV, SNAPSHOT_MAX_DEFAULT)


def _encoded_len(snap: dict) -> int:
    return len(json.dumps(snap, separators=(",", ":"),
                          sort_keys=True, default=str))


def collect_families() -> dict:
    """Current values of the exported catalog families, compact keys.
    Registry reads only — never an engine lock or a device sync.
    Values are rounded so delta comparison is stable across beats."""
    out = {"res": int(obs.RUNS_RESIDENT.value),
           "q": int(obs.FLEET_QUEUE_DEPTH.value)}
    st = {k[0]: round(c.value, 1)
          for k, c in obs.FLEET_STALENESS_MS.children().items()
          if c.value}
    if st:
        out["st"] = st
    qt = {"|".join(k): round(c.value, 3)
          for k, c in obs.FLEET_QUANTUM_MS.children().items()
          if c.value}
    if qt:
        out["qt"] = qt
    slo = sum(c.value for c in obs.RPC_SLO_BREACHES.children().values())
    if slo:
        out["slo"] = int(slo)
    cups = obs.ENGINE_CUPS.value
    if cups:
        out["cups"] = round(float(cups), 1)
    live = sum(c.value for c in obs.DEV_LIVE_BYTES.children().values())
    peak = sum(c.value for c in obs.DEV_PEAK_BYTES.children().values())
    if live or peak:
        out["dev"] = {"live": int(live), "peak": int(peak)}
    # Usage & capacity summary (PR 19): tracked-run count, projected
    # admissible runs (best bucket class), aggregate CUPS headroom and
    # a tiny top-talker list. Lowest snapshot priority — the first
    # family dropped when the beat exceeds GOL_FED_SNAPSHOT_MAX.
    try:
        from gol_tpu.obs import usage as obs_usage
        use = obs_usage.METER.export_summary()
    except Exception:
        use = None
    if use:
        out["use"] = use
    return out


def local_doc() -> dict:
    """A member's own telemetry view (the member-side `GetTelemetry`
    answer): current family values plus export bookkeeping."""
    return {"families": collect_families(),
            "pending_events": len(obs_audit.peek_pending(10 ** 6)),
            "snapshot_max": snapshot_budget(),
            "ts": time.time()}


class SnapshotExporter:
    """Member-side snapshot builder with commit-on-ack deltas."""

    def __init__(self) -> None:
        self._base: Optional[dict] = None
        self._built = None  # (sent families dict, events sent)

    def build(self) -> Optional[dict]:
        """The `"snap"` value for the next beat, or None when export
        is disabled (budget <= 0) or nothing fits the budget."""
        budget = snapshot_budget()
        if budget <= 0:
            self._built = None
            return None
        cur = collect_families()
        full = self._base is None
        if full:
            body = cur
        else:
            base = self._base
            body = {k: v for k, v in cur.items() if v != base.get(k)}
        events = obs_audit.peek_pending(EVENTS_PER_BEAT)
        keys = [k for k in FAMILY_PRIORITY if k in body]
        dropped = []
        while True:
            snap = {"v": SNAPSHOT_VERSION,
                    "m": {k: body[k] for k in keys}}
            if full:
                snap["full"] = 1
            if events:
                snap["ev"] = events
            size = _encoded_len(snap)
            if size <= budget:
                break
            if keys:
                dropped.append(keys.pop())  # lowest priority present
            elif events:
                events = events[:len(events) // 2]
                if not events:
                    obs.FED_SNAPSHOT_DROPPED.labels(
                        family="events").inc()
            else:
                # Even the bare envelope misses the budget: beat plain.
                self._built = None
                return None
        for k in dropped:
            obs.FED_SNAPSHOT_DROPPED.labels(
                family=FAMILY_LABELS.get(k, "unknown")).inc()
        obs.FED_SNAPSHOT_BYTES.set(size)
        obs.FED_SNAPSHOT_TOTAL.labels(
            kind="full" if full else "delta").inc()
        self._built = ({k: cur[k] for k in keys}, len(events))
        return snap

    def commit(self, resp: dict) -> None:
        """Advance the delta baseline — call ONLY after the beat's ack
        arrived. Families dropped for budget stay uncommitted and
        re-ship on the next beat; a `snap_resync` ack voids the
        baseline so the next beat goes out full."""
        built, self._built = self._built, None
        if built is None:
            return
        sent, n_events = built
        obs_audit.commit_pending(n_events)
        if resp.get("snap_resync"):
            self._base = None
            return
        base = dict(self._base or {})
        base.update(sent)
        self._base = base


# ----------------------------------------------------- registry side

class FleetTelemetry:
    """Router-resident ingest + rollup + alerting + audit glue."""

    def __init__(self, tsdb: Optional[TSDB] = None,
                 audit_log: Optional[obs_audit.AuditLog] = None,
                 alerts: Optional[AlertManager] = None) -> None:
        self.tsdb = tsdb or TSDB()
        self.audit_log = audit_log
        self.alerts = alerts or AlertManager(
            on_transition=self._on_alert)
        self._lock = threading.Lock()
        self._members: Dict[str, dict] = {}  # mid -> {"fam", "stamp"}
        self._payload = obs_slo.LogBucketEstimator()
        self._doc: dict = {}

    # -------------------------------------------------------- alerts

    def _on_alert(self, rule, event: str, value: float,
                  now: float) -> None:
        if self.audit_log is not None:
            self.audit_log.append(
                f"alert_{event}", rule=rule.name, signal=rule.signal,
                value=value, threshold=rule.threshold, ts=now)

    # -------------------------------------------------------- ingest

    def ingest(self, member_id: str, snap, ack: dict) -> None:
        """Merge one heartbeat snapshot; mutates `ack` in place to
        request a resync when a delta arrives with no base state."""
        if not isinstance(snap, dict):
            return
        self._payload.observe(float(_encoded_len(snap)))
        obs.FED_SNAPSHOT_INGESTED.inc()
        with self._lock:
            st = self._members.get(member_id)
            if st is None:
                st = {"fam": {}, "stamp": 0.0}
                self._members[member_id] = st
                if not snap.get("full"):
                    ack["snap_resync"] = True
            st["fam"].update(snap.get("m") or {})
            st["stamp"] = time.time()
        if self.audit_log is not None:
            for ev in snap.get("ev") or []:
                if not isinstance(ev, dict):
                    continue
                fields = {k: v for k, v in ev.items()
                          if k not in ("schema", "seq", "ts", "kind")}
                self.audit_log.append(
                    str(ev.get("kind", "other")), member=member_id,
                    member_seq=ev.get("seq"), ts=ev.get("ts"),
                    **fields)

    # --------------------------------------------------------- sweep

    def sweep(self, members_doc: dict,
              now: Optional[float] = None) -> list:
        """One rollup pass (the router calls this from its sweep
        loop): aggregate the per-member states, publish gol_fed_agg_*,
        feed the tsdb, evaluate alerts. Returns alert transitions."""
        if now is None:
            now = time.time()
        live_ids = {d["member_id"]
                    for d in members_doc.get("members", [])
                    if d.get("state") == "live"}
        with self._lock:
            states = {mid: dict(st["fam"])
                      for mid, st in self._members.items()
                      if mid in live_ids}
        resident = sum(int(f.get("res", 0)) for f in states.values())
        queue_sum = sum(int(f.get("q", 0)) for f in states.values())
        queue_max = max((int(f.get("q", 0))
                         for f in states.values()), default=0)
        cups = sum(float(f.get("cups", 0.0)) for f in states.values())
        slo = sum(int(f.get("slo", 0)) for f in states.values())
        dev_live = sum(int((f.get("dev") or {}).get("live", 0))
                       for f in states.values())
        stale = {q: max((float((f.get("st") or {}).get(q, 0.0))
                         for f in states.values()), default=0.0)
                 for q in obs.SLO_QUANTILES}
        residents = [int(f.get("res", 0)) for f in states.values()]
        mean_res = (sum(residents) / len(residents)) if residents else 0
        imbalance = (max(residents) / mean_res
                     if residents and mean_res > 0 else 1.0)
        # Usage & capacity rollups (PR 19): sum the heartbeat-borne
        # "use" summaries and merge the per-member top-talker lists
        # into one fleet-wide bounded table.
        use_tracked = sum(int((f.get("use") or {}).get("tracked", 0))
                          for f in states.values())
        use_adm = sum(int((f.get("use") or {}).get("adm", 0))
                      for f in states.values())
        use_hr = sum(float((f.get("use") or {}).get("hr", 0.0))
                     for f in states.values())
        usage_top: List[list] = []
        for mid, fam in states.items():
            for row in (fam.get("use") or {}).get("top", []):
                try:
                    usage_top.append(
                        [str(row[0]), float(row[1]), mid])
                except (TypeError, IndexError, ValueError):
                    continue
        usage_top.sort(key=lambda r: r[1], reverse=True)
        usage_top = usage_top[:8]

        obs.FED_AGG_USAGE_RUNS_TRACKED.set(use_tracked)
        obs.FED_AGG_USAGE_ADMISSIBLE_RUNS.set(use_adm)
        obs.FED_AGG_USAGE_CUPS_HEADROOM.set(round(use_hr, 1))
        obs.FED_AGG_RUNS_RESIDENT.set(resident)
        obs.FED_AGG_QUEUE_DEPTH.set(queue_sum)
        obs.FED_AGG_CUPS.set(cups)
        obs.FED_AGG_SLO_BREACHES.set(slo)
        obs.FED_AGG_DEV_LIVE_BYTES.set(dev_live)
        obs.FED_AGG_IMBALANCE.set(round(imbalance, 4))
        obs.FED_AGG_MEMBERS_REPORTING.set(len(states))
        for q, v in stale.items():
            obs.FED_AGG_STALENESS_MS.labels(q=q).set(round(v, 1))
        payload = {}
        if self._payload.count:
            for q, v in zip(obs.SLO_QUANTILES,
                            self._payload.percentiles(
                                (0.50, 0.95, 0.99))):
                if v is not None:
                    payload[q] = round(v, 1)
                    obs.FED_AGG_PAYLOAD_BYTES.labels(q=q).set(
                        round(v, 1))

        t = self.tsdb
        t.append("fleet.runs_resident", resident, ts=now)
        t.append("fleet.queue_depth", queue_sum, ts=now)
        t.append("fleet.cups", cups, ts=now)
        t.append("fleet.staleness_p99_ms", stale.get("p99", 0.0),
                 ts=now)
        t.append("fleet.imbalance_ratio", imbalance, ts=now)
        for mid, fam in states.items():
            t.append("member.runs_resident", int(fam.get("res", 0)),
                     labels={"member": mid}, ts=now)
            t.append("member.cups", float(fam.get("cups", 0.0)),
                     labels={"member": mid}, ts=now)
            t.append("member.staleness_p99_ms",
                     float((fam.get("st") or {}).get("p99", 0.0)),
                     labels={"member": mid}, ts=now)

        signals = {
            "members_dead": float(members_doc.get("dead", 0)),
            "members_live": float(members_doc.get("live", 0)),
            "members_reporting": float(len(states)),
            "members_multi": len(states) >= 2,
            "runs_resident": float(resident),
            "queue_depth": float(queue_max),
            "queue_depth_sum": float(queue_sum),
            "staleness_p99_ms": stale.get("p99", 0.0),
            "imbalance_ratio": imbalance,
            "cups": cups,
            "slo_breaches": float(slo),
        }
        transitions = self.alerts.evaluate(signals, now)

        member_rows = {}
        for mid, fam in sorted(states.items()):
            member_rows[mid] = {
                "resident": int(fam.get("res", 0)),
                "queue_depth": int(fam.get("q", 0)),
                "cups": float(fam.get("cups", 0.0)),
                "staleness_p99_ms": float(
                    (fam.get("st") or {}).get("p99", 0.0)),
                "slo_breaches": int(fam.get("slo", 0)),
                "dev_live_bytes": int(
                    (fam.get("dev") or {}).get("live", 0)),
            }
        doc = {
            "fleet": {
                "runs_resident": resident,
                "queue_depth": queue_sum,
                "cups": cups,
                "staleness_p99_ms": stale.get("p99", 0.0),
                "imbalance_ratio": round(imbalance, 4),
                "members_reporting": len(states),
                "members_live": members_doc.get("live", 0),
                "members_dead": members_doc.get("dead", 0),
                "slo_breaches": slo,
                "dev_live_bytes": dev_live,
                "usage": {
                    "runs_tracked": use_tracked,
                    "admissible_runs": use_adm,
                    "cups_headroom": round(use_hr, 1),
                },
            },
            "usage_top": [
                {"run_id": rid, "device_s": dev_s, "member": mid}
                for rid, dev_s, mid in usage_top],
            "members": member_rows,
            "alerts": self.alerts.doc(),
            "tsdb": self.tsdb.doc(),
            "payload_bytes": payload,
            "ts": now,
        }
        if self.audit_log is not None:
            doc["audit_seq"] = self.audit_log.seq
        self._doc = doc  # reference swap: /healthz reads lock-free
        return transitions

    # --------------------------------------------------------- reads

    def doc(self) -> dict:
        return self._doc

    def query(self, name: str, labels=(), tier: str = "raw",
              since: float = 0.0) -> list:
        return self.tsdb.query(name, labels=labels, tier=tier,
                               since=since)

    def audit_tail(self, since_seq: int = 0,
                   limit: int = 100) -> list:
        if self.audit_log is None:
            return []
        return self.audit_log.tail(since_seq, limit)


# -- /healthz hook: the process's active aggregator --------------------

_active: Optional[FleetTelemetry] = None


def set_active_telemetry(t: Optional[FleetTelemetry]) -> None:
    global _active
    _active = t


def active_telemetry_doc() -> Optional[dict]:
    """The active aggregator's telemetry doc, or None when this
    process runs no registry tier (members add no telemetry key)."""
    t = _active
    return None if t is None else t.doc()
