"""Shared "observability must never sink a run" sink guard.

Every durable observability writer in this repo — the run reporter
(`obs/timeline.py`), the span exporter (`obs/trace.py`), and the run
journal (`gol_tpu/journal.py`) — has the same failure contract: the
first OSError (disk full, bad path, permission) permanently disables
the sink and the engine carries on unmetered. Before this module each
writer carried its own copy of that guard; now they share one.

`GuardedLineSink` is the append-only line writer: lazy open on first
write, write+flush under a lock, and `dead` latched forever after the
first OSError. `guarded_export` is the one-shot variant for exporters
that dump a whole artifact at close (the Chrome trace): call the thunk,
swallow OSError/ValueError, never raise into engine teardown.
"""

from __future__ import annotations

import threading
from typing import IO, Callable, Optional

__all__ = ["GuardedLineSink", "guarded_export"]


class GuardedLineSink:
    """Append-only line sink that disables itself after one OSError.

    Thread-safe; the file is opened lazily on the first `write_line`
    so constructing a sink for a bad path costs nothing until used.
    Once `dead`, every subsequent write is a silent no-op — the guard
    never un-latches (a sink that half-recovers would interleave holes
    into append-only logs, which is worse than stopping cleanly).
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._fh: Optional[IO[str]] = None
        self._dead = False

    @property
    def dead(self) -> bool:
        return self._dead

    def write_line(self, line: str) -> bool:
        """Append `line` + newline and flush. True iff it hit the file;
        False once the sink is dead (including the write that kills it).
        """
        with self._lock:
            if self._dead:
                return False
            try:
                if self._fh is None:
                    self._fh = open(self.path, "a", encoding="utf-8")
                self._fh.write(line + "\n")
                self._fh.flush()
                return True
            except OSError:
                self._kill_locked()
                return False

    def close(self) -> None:
        """Close the file and latch dead (idempotent)."""
        with self._lock:
            self._kill_locked()

    def _kill_locked(self) -> None:
        self._dead = True
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


def guarded_export(thunk: Callable[[], None]) -> bool:
    """Run a one-shot export thunk; swallow sink errors.

    For end-of-run exporters (Chrome trace dump) where there is no
    stream to disable — the whole artifact either lands or it doesn't.
    Returns True on success, False if the export failed; never raises
    into engine teardown (these run on shutdown paths).
    """
    try:
        thunk()
        return True
    except Exception:
        return False
