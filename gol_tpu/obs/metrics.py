"""In-process metrics registry: counters, gauges, windowed histograms.

Dependency-free (stdlib only — no jax import, so the control plane can
meter itself in processes that never touch a device) and deliberately
tiny: the framework's update discipline is that metrics move only at
CHUNK boundaries and RPC boundaries, never inside jit-compiled code, so
a lock per metric child is plenty (see docs/OBSERVABILITY.md for the
catalogue and the ≤2% overhead budget).

Two export surfaces, same data:

    snapshot()           JSON-serializable dict (the `GetMetrics` wire
                         method and the run-report family)
    render_prometheus()  Prometheus text exposition v0.0.4 (the
                         `/metrics` endpoint, `obs/http.py`)

Histograms are cumulative-bucket Prometheus histograms that ALSO keep a
sliding window of recent observations (min/mean/max over the last W),
because a long-lived engine's interesting latencies are the recent
ones, not the since-boot aggregate.
"""

from __future__ import annotations

import json
import math
import threading
from collections import deque
from typing import Dict, Optional, Sequence, Tuple

# Latency-shaped default buckets (seconds): spans a sub-ms RPC through a
# multi-second chunk wall; +Inf is implicit.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
HISTOGRAM_WINDOW = 64  # sliding-window observations kept per child


class Counter:
    """Monotonically non-decreasing count."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (set/inc/dec).

    `set` and `value` are lock-free: a Python float attribute store/load
    is atomic under the GIL, and a gauge set is a plain overwrite — no
    read-modify-write to protect. Only `inc`/`dec` (RMW) take the lock.
    This matters because the engine publishes a handful of gauges at
    every chunk boundary; at µs chunk walls the per-set lock was
    measurable hot-loop overhead."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Cumulative-bucket histogram plus a sliding observation window."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 window: int = HISTOGRAM_WINDOW) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds or any(math.isnan(b) for b in bounds):
            raise ValueError(f"bad histogram buckets {buckets!r}")
        self._lock = threading.Lock()
        self._bounds = bounds
        self._bucket_counts = [0] * (len(bounds) + 1)  # last = +Inf
        self._count = 0
        self._sum = 0.0
        self._window: deque = deque(maxlen=max(int(window), 1))

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            i = 0
            while i < len(self._bounds) and value > self._bounds[i]:
                i += 1
            self._bucket_counts[i] += 1
            self._count += 1
            self._sum += value
            self._window.append(value)

    def observe_batch(self, values: Sequence[float]) -> None:
        """Record many observations under ONE lock acquisition — the
        engine's chunk loop accumulates per-chunk latencies locally and
        flushes them here on a coarse interval, so the hot path pays a
        list append instead of a lock per chunk."""
        if not values:
            return
        with self._lock:
            for value in values:
                value = float(value)
                i = 0
                while i < len(self._bounds) and value > self._bounds[i]:
                    i += 1
                self._bucket_counts[i] += 1
                self._count += 1
                self._sum += value
                self._window.append(value)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        with self._lock:
            cumulative = []
            running = 0
            for bound, n in zip(self._bounds, self._bucket_counts):
                running += n
                cumulative.append([bound, running])
            win = list(self._window)
        out = {
            "count": self._count,
            "sum": self._sum,
            "buckets": cumulative,  # [upper_bound, cumulative_count]
        }
        if win:
            out["window"] = {
                "n": len(win),
                "min": min(win),
                "max": max(win),
                "mean": sum(win) / len(win),
                "last": win[-1],
            }
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """One named metric plus its labelled children. With no label names
    the family IS its single child (inc/set/observe delegate), so
    unlabelled call sites read naturally."""

    def __init__(self, name: str, help_: str, kind: str,
                 label_names: Tuple[str, ...] = (), **child_kw) -> None:
        self.name = name
        self.help = help_
        self.kind = kind
        self.label_names = tuple(label_names)
        self._child_kw = child_kw
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.label_names:
            self._children[()] = _KINDS[kind](**child_kw)

    def labels(self, **labels: str):
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _KINDS[self.kind](**self._child_kw)
                self._children[key] = child
            return child

    def _solo(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} is labelled {self.label_names}; "
                f"use .labels(...)")
        return self._children[()]

    # unlabelled delegation -------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def observe_batch(self, values: Sequence[float]) -> None:
        self._solo().observe_batch(values)

    @property
    def value(self):
        return self._solo().value

    def children(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._children)


class Registry:
    """Named metric families; snapshot-to-dict and Prometheus text."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    def _register(self, name: str, help_: str, kind: str,
                  label_names: Sequence[str], **child_kw) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                # Idempotent re-registration (re-imports, test reloads) —
                # but a KIND/label clash is a programming error, not a
                # cache hit.
                if fam.kind != kind or fam.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} re-registered as {kind}"
                        f"{tuple(label_names)}; existing is {fam.kind}"
                        f"{fam.label_names}")
                return fam
            fam = MetricFamily(name, help_, kind, tuple(label_names),
                               **child_kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "",
                label_names: Sequence[str] = ()) -> MetricFamily:
        return self._register(name, help_, "counter", label_names)

    def gauge(self, name: str, help_: str = "",
              label_names: Sequence[str] = ()) -> MetricFamily:
        return self._register(name, help_, "gauge", label_names)

    def histogram(self, name: str, help_: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  window: int = HISTOGRAM_WINDOW) -> MetricFamily:
        return self._register(name, help_, "histogram", label_names,
                              buckets=buckets, window=window)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> Dict[str, MetricFamily]:
        with self._lock:
            return dict(self._families)

    # ------------------------------------------------------------- exports

    def snapshot(self) -> dict:
        """JSON-serializable view of every family: the `GetMetrics` wire
        payload. Label values ride as dicts (JSON has no tuple keys)."""
        out = {}
        for name, fam in sorted(self.families().items()):
            values = []
            for key, child in sorted(fam.children().items()):
                labels = dict(zip(fam.label_names, key))
                if fam.kind == "histogram":
                    values.append({"labels": labels,
                                   "value": child.snapshot()})
                else:
                    values.append({"labels": labels, "value": child.value})
            out[name] = {"type": fam.kind, "help": fam.help,
                         "values": values}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4."""
        lines = []
        for name, fam in sorted(self.families().items()):
            if fam.help:
                lines.append(f"# HELP {name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for key, child in sorted(fam.children().items()):
                labels = dict(zip(fam.label_names, key))
                if fam.kind == "histogram":
                    snap = child.snapshot()
                    for bound, cum in snap["buckets"]:
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels({**labels, 'le': _fmt(bound)})}"
                            f" {cum}")
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels({**labels, 'le': '+Inf'})}"
                        f" {snap['count']}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(labels)}"
                        f" {_fmt(snap['sum'])}")
                    lines.append(
                        f"{name}_count{_fmt_labels(labels)}"
                        f" {snap['count']}")
                else:
                    lines.append(
                        f"{name}{_fmt_labels(labels)} "
                        f"{_fmt(child.value)}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    """Shortest faithful number: integral floats print without the
    trailing .0 Prometheus parsers don't need."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


# The process-wide default registry — what the engine, wire layer,
# `/metrics` endpoint, and `GetMetrics` wire method all share.
REGISTRY = Registry()


def get_registry() -> Registry:
    return REGISTRY


def snapshot_json() -> str:
    """Convenience: the default registry's snapshot as one JSON string."""
    return json.dumps(REGISTRY.snapshot(), sort_keys=True)
