"""Halo/collective telemetry aggregation (the gol_halo_* feeds).

Two producers feed this module, matching how sharded dispatches happen:

* EAGER callers (bench legs, direct kernel users, tests) call the
  `parallel/halo.py` / `parallel/mesh2d.py` run wrappers with concrete
  arrays; those wrappers note each dispatch here as it happens (and
  skip tracers, see below).
* The ENGINE composes the sharded run inside its jitted token wrapper
  (`engine._tokened_run`), so the wrappers only execute at trace time
  there — once per compilation, with tracers.  The engine therefore
  buffers (turns, wall_s) pairs per popped chunk in its hot-loop locals
  and drains them through `flush_chunk_walls` at the same batched
  metrics flush that owns every other engine gauge (the PR-6 cadence):
  zero registry traffic per chunk, everything at flush boundaries.

Semantics, stated honestly:

* gol_halo_exchanges_total / gol_halo_bytes_total are EXACT analytic
  counts derived from the static dispatch geometry (shard shape, macro
  depth T, turn count — `parallel.halo.halo_traffic`).  They count
  ppermute exchange rounds and the bytes those rounds move across the
  whole mesh; they are not a link probe.
* gol_halo_exchange_seconds is the dispatch wall divided by the number
  of exchange rounds in that dispatch: exact per-round latency for
  synchronous callers, pipeline-amortized for engine chunks.  It prices
  a round including whatever local compute it failed to overlap.
* gol_shard_imbalance_ratio is max/mean of per-shard cumulative
  readiness waits observed host-side in shard order — a completion-
  spread signal (1.0 = balanced), not a per-device timer.

Stdlib-only: jax never enters this module (the obs-package contract);
callers hand in plain ints/floats, and `measure_shard_imbalance` only
duck-types `.addressable_shards` on whatever array it is given.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, Optional, Tuple

from gol_tpu.obs import catalog as _cat

# Traffic dicts map mesh-axis name -> (exchange_rounds, total_bytes).
Traffic = Dict[str, Tuple[int, int]]


def note_traffic(traffic: Traffic, num_turns: int = 0) -> None:
    """Fold one dispatch's analytic traffic into the counters. When the
    caller supplies the dispatch's turn count, also refresh the per-turn
    gauges (`gol_halo_bytes_per_turn` / `gol_halo_exchanges_per_turn`) —
    the temporal-fusion observables: bytes/turn is CONSERVED under
    fusion while exchanges/turn drops ~k-fold (see catalog help)."""
    for axis, (rounds, nbytes) in traffic.items():
        lab = _cat.mesh_axis_label(axis)
        if rounds:
            _cat.HALO_EXCHANGES.labels(axis=lab).inc(int(rounds))
        if nbytes:
            _cat.HALO_BYTES.labels(axis=lab).inc(int(nbytes))
    if num_turns > 0:
        _set_per_turn(traffic, num_turns)


def _set_per_turn(per_axis: Traffic, num_turns: int) -> None:
    """Publish the per-turn halo gauges from an {axis: (rounds, bytes)}
    aggregate spanning `num_turns` turns."""
    for axis, (rounds, nbytes) in per_axis.items():
        lab = _cat.mesh_axis_label(axis)
        _cat.HALO_EXCHANGES_PER_TURN.labels(axis=lab).set(
            rounds / num_turns)
        _cat.HALO_BYTES_PER_TURN.labels(axis=lab).set(
            nbytes / num_turns)


def total_rounds(traffic: Traffic) -> int:
    return sum(int(r) for r, _ in traffic.values())


def total_bytes(traffic: Traffic) -> int:
    return sum(int(b) for _, b in traffic.values())


def observe_dispatch(elapsed_s: float, traffic: Traffic) -> None:
    """Price one SYNCHRONOUS dispatch (caller timed dispatch→ready):
    counters plus the amortized per-exchange-round latency."""
    note_traffic(traffic)
    observe_wall(elapsed_s, traffic)


def observe_wall(elapsed_s: float, traffic: Traffic) -> None:
    """Histogram-only variant for callers whose dispatch already went
    through a counting run wrapper (`parallel.halo.dispatch_obs` notes
    eager traffic itself) — feeding `observe_dispatch` there would
    double the counters."""
    rounds = total_rounds(traffic)
    if rounds and elapsed_s > 0:
        _cat.HALO_EXCHANGE_SECONDS.observe(elapsed_s / rounds)


def flush_chunk_walls(
    walls: Iterable[Tuple[int, float]],
    traffic_for_k: Callable[[int], Traffic],
) -> None:
    """Engine-side drain: `walls` holds (turns, wall_s) per popped
    chunk, `traffic_for_k(turns)` that chunk's analytic traffic.  One
    histogram lock via observe_batch; counters folded once per axis —
    the whole call is a handful of dict ops per flush window."""
    per_axis: Dict[str, Tuple[int, int]] = {}
    samples = []
    turns = 0
    for k, wall in walls:
        traffic = traffic_for_k(k)
        if not traffic:
            continue
        turns += int(k)
        rounds = 0
        for axis, (r, b) in traffic.items():
            er, eb = per_axis.get(axis, (0, 0))
            per_axis[axis] = (er + int(r), eb + int(b))
            rounds += int(r)
        if rounds and wall > 0:
            samples.append(wall / rounds)
    for axis, (rounds, nbytes) in per_axis.items():
        lab = _cat.mesh_axis_label(axis)
        if rounds:
            _cat.HALO_EXCHANGES.labels(axis=lab).inc(rounds)
        if nbytes:
            _cat.HALO_BYTES.labels(axis=lab).inc(nbytes)
    if turns:
        _set_per_turn(per_axis, turns)
    if samples:
        _cat.HALO_EXCHANGE_SECONDS.observe_batch(samples)


def measure_shard_imbalance(out) -> Optional[float]:
    """Publish gol_shard_imbalance_ratio from a sharded array's
    per-shard readiness spread; returns the ratio, or None when `out`
    has fewer than two addressable shards (nothing to compare).

    Blocks on each shard IN SHARD ORDER and records the cumulative wait
    at each step, so the ratio is order-biased toward late high-index
    shards — good enough to flag one straggling device, not a timer.
    Callers should pass a freshly dispatched (not yet awaited) array."""
    shards = getattr(out, "addressable_shards", None)
    if shards is None or len(shards) < 2:
        return None
    waits = []
    t0 = time.perf_counter()
    for s in shards:
        try:
            s.data.block_until_ready()
        except Exception:  # telemetry must never sink the caller
            return None
        waits.append(max(time.perf_counter() - t0, 0.0))
    mean = sum(waits) / len(waits)
    ratio = max(waits) / mean if mean > 1e-9 else 1.0
    _cat.SHARD_IMBALANCE.set(ratio)
    return ratio
