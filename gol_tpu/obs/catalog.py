"""The metric catalogue: every family the framework emits, declared in
one place against the shared default registry.

Importing `gol_tpu.obs` (or any instrumented module) pulls this in, so
`/metrics` and `GetMetrics` always expose the full set of families with
stable names even before the first engine chunk or RPC — zero-valued
children are pre-seeded for the known wire methods for the same reason.
docs/OBSERVABILITY.md is the human-readable mirror of this file.
"""

from __future__ import annotations

from gol_tpu.obs.metrics import REGISTRY

# Every method the wire protocol speaks, plus a catch-all so an
# unrecognised header can't mint unbounded label values.
WIRE_METHODS = (
    "ServerDistributor", "Alivecount", "GetWorld", "GetView", "GetWindow",
    "CFput", "DrainFlags", "KillProg", "Ping", "Stats", "AbortRun",
    "GetMetrics", "Checkpoint", "RestoreRun", "Profile",
    "CreateRun", "ListRuns", "AttachRun", "DestroyRun", "SetRule",
    "RegisterMember", "AdoptRun", "Subscribe",
    "Rescale", "ReceiveRun", "CommitRun", "PinRun",
    "GetTelemetry", "GetAudit", "GetJournal", "GetUsage",
    "unknown",
)

# ----------------------------------------------------------------- engine

ENGINE_TURN = REGISTRY.gauge(
    "gol_engine_turn",
    "Latest simulated turn completed by the engine chunk loop.")
ENGINE_PUBLISHED_TURN = REGISTRY.gauge(
    "gol_engine_published_turn",
    "Turn of the last published (alive, turn) pair; monotone within a "
    "run — regressions increment "
    "gol_engine_published_turn_regressions_total instead of moving this "
    "gauge backwards.")
ENGINE_PUBLISHED_ALIVE = REGISTRY.gauge(
    "gol_engine_published_alive",
    "Alive-cell count of the last published (alive, turn) pair.")
ENGINE_CUPS = REGISTRY.gauge(
    "gol_engine_cups",
    "Achieved cell updates per second over the most recent untraced "
    "chunk.")
ENGINE_TURNS_PER_S = REGISTRY.gauge(
    "gol_engine_turns_per_s",
    "Achieved turns per second over the most recent untraced chunk.")
ENGINE_CHUNK_SIZE = REGISTRY.gauge(
    "gol_engine_chunk_size",
    "Turns per compiled chunk currently chosen by the pace adapter.")

ENGINE_CHUNKS_TOTAL = REGISTRY.counter(
    "gol_engine_chunks_total",
    "Untraced chunks retired by the engine loop.")
ENGINE_TURNS_TOTAL = REGISTRY.counter(
    "gol_engine_turns_total",
    "Turns simulated across all retired chunks (traced included).")
ENGINE_TRACED_CHUNKS_TOTAL = REGISTRY.counter(
    "gol_engine_traced_chunks_total",
    "Chunks run under the GOL_TRACE profiler path; excluded from pace, "
    "CUPS, and chunk-latency aggregates.")
ENGINE_PUBLISHED_TURN_REGRESSIONS = REGISTRY.counter(
    "gol_engine_published_turn_regressions_total",
    "Publications that would have moved the published turn backwards "
    "within a run (should stay 0).")

ENGINE_CHUNK_SECONDS = REGISTRY.histogram(
    "gol_engine_chunk_seconds",
    "Wall seconds per retired untraced chunk (token wait + bookkeeping).")
ENGINE_FLAG_SERVICE_SECONDS = REGISTRY.histogram(
    "gol_engine_flag_service_seconds",
    "Seconds the chunk loop spent servicing control flags between "
    "chunk issues.")

ENGINE_CHUNK_OVERHEAD_US = REGISTRY.gauge(
    "gol_engine_chunk_overhead_us",
    "Mean host-side microseconds per retired chunk spent OUTSIDE the "
    "device-result wait (dispatch, publish, metrics, flag polling, "
    "pipeline bookkeeping) over the run so far; refreshed at each "
    "metrics flush and at run end.")
ENGINE_BAND_COPIES = REGISTRY.counter(
    "gol_engine_band_copies_total",
    "Banded device-to-host row copies started by snapshot streaming "
    "(engine._banded_device_rows); stays flat while no viewer or "
    "snapshot consumer is attached.")

# ---------------------------------------------------------- kernel tiers

# Every tier the conv-family dispatch can select (ops/conv.TIERS
# mirrors this; pre-seeded so /metrics always shows the full matrix).
KERNEL_TIERS = ("bitplane", "fused", "conv", "fft")

KERNEL_TIER = REGISTRY.gauge(
    "gol_kernel_tier",
    "One-hot active kernel tier of the most recent conv-family "
    "dispatch: the selected tier reads 1, every other 0 "
    "(ops/conv.select_tier policy; GOL_KERNEL_TIER forces).",
    label_names=("tier",))
CONV_DISPATCHES = REGISTRY.counter(
    "gol_conv_dispatches_total",
    "Conv-family kernel dispatches (LtL / Lenia run submissions and "
    "standalone run_turns calls), by selected tier.",
    label_names=("tier",))

for _t in KERNEL_TIERS:
    KERNEL_TIER.labels(tier=_t)
    CONV_DISPATCHES.labels(tier=_t)

# ------------------------------------------------------------ wire bytes

WIRE_BYTES = REGISTRY.counter(
    "gol_wire_bytes_total",
    "Bytes moved over the wire protocol, by direction.",
    label_names=("direction",))
WIRE_MESSAGES = REGISTRY.counter(
    "gol_wire_messages_total",
    "Wire-protocol messages moved, by direction.",
    label_names=("direction",))
for _d in ("sent", "received"):
    WIRE_BYTES.labels(direction=_d)
    WIRE_MESSAGES.labels(direction=_d)

# ----------------------------------------------------- wire codec frames

# Every codec the framing layer can put on the wire (wire.CODECS mirrors
# this; the tuple lives here so the catalogue stays import-light) —
# pre-seeded like the methods so /metrics always shows the full matrix.
WIRE_CODECS = ("u8", "packed", "u8+zlib", "packed+zlib", "xrle",
               "f32", "f32+zlib")

WIRE_FRAMES = REGISTRY.counter(
    "gol_wire_frames_total",
    "Codec-framed board payloads sent, by codec chosen after "
    "negotiation (legacy raw-u8 sends to caps-less peers are counted "
    "under gol_wire_messages_total only).",
    label_names=("codec",))
WIRE_FRAME_BYTES = REGISTRY.counter(
    "gol_wire_frame_bytes_total",
    "Encoded payload bytes of sent board frames, by codec.",
    label_names=("codec",))
WIRE_BYTES_SAVED = REGISTRY.counter(
    "gol_wire_bytes_saved_total",
    "Payload bytes NOT sent thanks to codec framing: sum over sent "
    "frames of (raw u8 size h*w − encoded size).")
WIRE_COMPRESSION_RATIO = REGISTRY.gauge(
    "gol_wire_compression_ratio",
    "raw u8 size / encoded size of the most recently sent board frame "
    "(8.0 = pure packed, higher = compression on top).")
WIRE_ENCODE_SECONDS = REGISTRY.histogram(
    "gol_wire_encode_seconds",
    "Seconds spent encoding a board frame before/while sending, by "
    "codec (banded senders accrue encode time as chunks stream).",
    label_names=("codec",))
WIRE_DECODE_SECONDS = REGISTRY.histogram(
    "gol_wire_decode_seconds",
    "Seconds spent decoding a received board frame, by codec.",
    label_names=("codec",))
WIRE_ENCODE_CALLS = REGISTRY.counter(
    "gol_wire_encode_calls_total",
    "Board/view frame encode invocations (any codec, eager or banded). "
    "Proves the no-viewer turn path does zero wire-encode work: this "
    "counter must not move while chunks retire without a snapshot "
    "consumer.")

for _c in WIRE_CODECS:
    WIRE_FRAMES.labels(codec=_c)
    WIRE_FRAME_BYTES.labels(codec=_c)

# ---------------------------------------------------------------- server

SERVER_REQUESTS = REGISTRY.counter(
    "gol_server_requests_total",
    "Requests dispatched by the engine server, by wire method.",
    label_names=("method",))
SERVER_ERRORS = REGISTRY.counter(
    "gol_server_errors_total",
    "Requests that raised inside the server dispatch, by wire method.",
    label_names=("method",))
SERVER_REQUEST_SECONDS = REGISTRY.histogram(
    "gol_server_request_seconds",
    "Server-side dispatch latency, by wire method.",
    label_names=("method",))

# ---------------------------------------------------------------- client

CLIENT_REQUESTS = REGISTRY.counter(
    "gol_client_requests_total",
    "RPCs issued by RemoteEngine, by wire method.",
    label_names=("method",))
CLIENT_ERRORS = REGISTRY.counter(
    "gol_client_errors_total",
    "RPCs that failed (socket or protocol error), by wire method.",
    label_names=("method",))
CLIENT_REQUEST_SECONDS = REGISTRY.histogram(
    "gol_client_request_seconds",
    "Round-trip RPC latency seen by RemoteEngine, by wire method.",
    label_names=("method",))

for _m in WIRE_METHODS:
    SERVER_REQUESTS.labels(method=_m)


def method_label(method: str) -> str:
    """Clamp arbitrary header method strings to the declared set."""
    return method if method in WIRE_METHODS else "unknown"


# ------------------------------------------------- chaos & fault tolerance

# Closed kind sets, pre-seeded like the wire methods so the resilience
# families are visible at zero before the first fault.
CHAOS_KINDS = ("drop", "delay", "truncate", "corrupt", "stall",
               "kill_member", "refuse", "migrate_fail")
RPC_ERROR_KINDS = ("timeout", "refused", "reset", "protocol")

CHAOS_INJECTED = REGISTRY.counter(
    "gol_chaos_injected_total",
    "Faults injected by the GOL_CHAOS wire-layer injector "
    "(gol_tpu/chaos.py), by kind: drop (socket closed instead of the "
    "operation), delay (bounded sleep), truncate (partial header then "
    "close), corrupt (one header byte zeroed so the peer sees a "
    "protocol error), stall (long sleep that outlasts read timeouts), "
    "refuse (dial-time ConnectionRefusedError before the socket "
    "connects), kill_member (process-level SIGKILL of a federation "
    "member at a seeded time), migrate_fail (one forced failure at a "
    "named Rescale migration phase). Stays 0 unless GOL_CHAOS is set.",
    label_names=("kind",))
for _k in CHAOS_KINDS:
    CHAOS_INJECTED.labels(kind=_k)

RPC_ERRORS = REGISTRY.counter(
    "gol_rpc_errors_total",
    "Transport-level RPC failures observed in the server per-connection "
    "handler, by method and kind: timeout (header/read deadline), "
    "refused (connect-phase failure), reset (peer closed or OS error "
    "mid-message), protocol (unparseable framing).",
    label_names=("method", "kind"))
for _m in WIRE_METHODS:
    for _k in RPC_ERROR_KINDS:
        RPC_ERRORS.labels(method=_m, kind=_k)


def rpc_error_kind_label(kind: str) -> str:
    """Clamp arbitrary transport-error kinds to the declared set."""
    return kind if kind in RPC_ERROR_KINDS else "reset"


CLIENT_RETRIES = REGISTRY.counter(
    "gol_client_retries_total",
    "RPC attempts re-issued by the RemoteEngine retry policy "
    "(exponential backoff with jitter) after a retryable transport "
    "error, by wire method. Excludes the first attempt.",
    label_names=("method",))

SERVER_DEDUP_HITS = REGISTRY.counter(
    "gol_server_dedup_hits_total",
    "Mutating requests answered from the server-side req_id dedupe "
    "window instead of re-executing (a retried RPC whose first attempt "
    "already committed), by wire method.",
    label_names=("method",))

SERVER_DRAIN_SECONDS = REGISTRY.gauge(
    "gol_server_drain_seconds",
    "Wall seconds the last graceful drain (SIGTERM) spent between "
    "stopping the accept loop and process exit: in-flight handler "
    "wait plus checkpointing.")
SERVER_DRAIN_INFLIGHT = REGISTRY.gauge(
    "gol_server_drain_inflight",
    "In-flight request handlers observed when the last graceful drain "
    "began.")

# ------------------------------------------------------------ fleet runs

RUNS_RESIDENT = REGISTRY.gauge(
    "gol_runs_resident",
    "Runs currently resident in the fleet engine (placed in a bucket "
    "slot, charged against the admission memory budget). Single-run "
    "engines leave this at 0.")
RUNS_ADMITTED = REGISTRY.counter(
    "gol_runs_admitted_total",
    "CreateRun admissions (a run became resident), including queued "
    "runs promoted when capacity freed.")
RUNS_REJECTED = REGISTRY.counter(
    "gol_runs_rejected_total",
    "CreateRun rejections, by reason: memory (admission byte budget), "
    "max_runs (GOL_FLEET_MAX_RUNS), queue_full, shape (board does not "
    "tile any configured bucket), rule (unsupported rule family), "
    "run_id (invalid or duplicate id).",
    label_names=("reason",))

# Same cardinality discipline as wire methods/flight reasons: reject
# reasons are clamped to a declared set and pre-seeded at zero.
RUN_REJECT_REASONS = ("memory", "max_runs", "queue_full", "shape",
                      "rule", "run_id", "unknown")
for _r in RUN_REJECT_REASONS:
    RUNS_REJECTED.labels(reason=_r)


def run_reject_label(reason: str) -> str:
    """Clamp arbitrary rejection reasons to the declared set."""
    return reason if reason in RUN_REJECT_REASONS else "unknown"


RUNS_DESTROYED = REGISTRY.counter(
    "gol_runs_destroyed_total",
    "DestroyRun removals: runs explicitly destroyed over the wire (or "
    "via FleetEngine.destroy_run), freeing their bucket slot and "
    "admission budget. QUIT/KILL-flag removals are not counted here.")

RUNS_QUARANTINED = REGISTRY.counter(
    "gol_runs_quarantined_total",
    "Runs evicted from their fleet bucket into state 'quarantined', by "
    "reason: popcount (implausible per-slot alive count), step (the "
    "shared bucket dispatch raised), restore (seed/restore of the slot "
    "failed). A quarantined board never re-enters the shared lax.scan "
    "dispatch until auto-restored from its last checkpoint.",
    label_names=("reason",))

QUARANTINE_REASONS = ("popcount", "step", "restore", "unknown")
for _r in QUARANTINE_REASONS:
    RUNS_QUARANTINED.labels(reason=_r)


def quarantine_label(reason: str) -> str:
    """Clamp arbitrary quarantine reasons to the declared set."""
    return reason if reason in QUARANTINE_REASONS else "unknown"


RUNS_QUARANTINE_RESTORES = REGISTRY.counter(
    "gol_runs_quarantine_restores_total",
    "Auto-restore attempts for quarantined runs from their last per-run "
    "checkpoint, by outcome: ok (run re-queued for placement), error "
    "(no usable checkpoint or restore raised; retried under backoff up "
    "to GOL_QUARANTINE_TRIES).",
    label_names=("status",))
for _s in ("ok", "error"):
    RUNS_QUARANTINE_RESTORES.labels(status=_s)


RUNS_RULE_MIGRATIONS = REGISTRY.counter(
    "gol_runs_rule_migrations_total",
    "SetRule bucket migrations: fleet runs moved between (size, "
    "rulestring) bucket classes via evict -> readmit through the "
    "placement queue, board preserved. No-op SetRule (same rulestring) "
    "is not counted.")

FLEET_DEVICE_RESIDENT = REGISTRY.gauge(
    "gol_fleet_device_resident_runs",
    "Resident fleet runs whose bucket slot lives on each device of the "
    "engine's placement mesh (batch-axis buckets put each slot on one "
    "device; spatially sharded buckets count their residents on every "
    "device holding a row shard). Label cardinality is bounded by the "
    "local device count, same as gol_dev_live_bytes.",
    label_names=("device",))

FLEET_MESH_DEVICES = REGISTRY.gauge(
    "gol_fleet_mesh_devices",
    "Devices in the fleet engine's placement mesh (1 = the unsharded "
    "single-device fleet; gol_mesh_* carries the full geometry stamp).")


def runs_doc() -> dict:
    """The /healthz runs summary: resident gauge + admission counters
    (registry reads only — never a device sync or an engine lock).
    On a mesh-placed fleet the summary also carries the placement
    device count and the per-device resident split."""
    rejected = 0.0
    for child in RUNS_REJECTED.children().values():
        rejected += child.value
    doc = {"resident": int(RUNS_RESIDENT.value),
           "admitted_total": int(RUNS_ADMITTED.value),
           "rejected_total": int(rejected)}
    mesh_devices = int(FLEET_MESH_DEVICES.value)
    if mesh_devices:
        doc["mesh_devices"] = mesh_devices
        doc["resident_by_device"] = {
            key[0]: int(child.value)
            for key, child in sorted(
                FLEET_DEVICE_RESIDENT.children().items())}
    return doc


# -------------------------------------------------------- serving-tier SLOs

# Quantile gauges published by obs/slo.py's log-bucket estimators at the
# r06 batched flush cadence. Cardinality is bounded by construction:
# kinds, quantiles, and methods are all closed tuples (methods clamp via
# method_label), and fleet bucket labels come from the configured bucket
# sizes — never from run ids.
RPC_KINDS = ("client", "handler", "wait")
SLO_QUANTILES = ("p50", "p95", "p99")

RPC_LATENCY_MS = REGISTRY.gauge(
    "gol_rpc_latency_ms",
    "RPC latency quantiles in milliseconds from the bounded-memory "
    "log-bucket estimators (obs/slo.py; <= one ~16% bucket width of "
    "error): kind=client (RemoteEngine end-to-end round trip), "
    "kind=handler (server dispatch, header received -> reply sent), "
    "kind=wait (server accept -> dispatch start: conn-slot scheduling "
    "plus header receipt).",
    label_names=("kind", "method", "q"))
RPC_SLO_BREACHES = REGISTRY.counter(
    "gol_slo_breaches_total",
    "Flush windows in which a method's p99 exceeded the configured "
    "GOL_SLO_P99_MS objective (0 = objective disabled); each breach "
    "also records a flight-recorder event.",
    label_names=("kind", "method"))

FLEET_QUANTUM_MS = REGISTRY.gauge(
    "gol_fleet_quantum_latency_ms",
    "Serving-quantum wall latency quantiles per fleet bucket class "
    "(dispatch issue -> popcounts home), in milliseconds.",
    label_names=("bucket", "q"))
FLEET_QUEUE_DEPTH = REGISTRY.gauge(
    "gol_fleet_queue_depth",
    "Runs currently waiting in the fleet admission queue.")
FLEET_QUEUE_WAIT_MS = REGISTRY.gauge(
    "gol_fleet_queue_wait_ms",
    "Admission queue wait quantiles in milliseconds (enqueue -> "
    "promotion to placement).",
    label_names=("q",))
FLEET_STALENESS_MS = REGISTRY.gauge(
    "gol_fleet_staleness_ms",
    "Per-run turn staleness quantiles in milliseconds across resident "
    "unpaused runs: time since each run's board last advanced. The "
    "top-K worst runs are named on /healthz, never as metric labels.",
    label_names=("q",))

for _k in RPC_KINDS:
    for _q in SLO_QUANTILES:
        RPC_LATENCY_MS.labels(kind=_k, method="unknown", q=_q)
for _q in SLO_QUANTILES:
    FLEET_QUEUE_WAIT_MS.labels(q=_q)
    FLEET_STALENESS_MS.labels(q=_q)


# ------------------------------------------------------- fleet federation

# Member lifecycle states the router's registry distinguishes. Closed
# set, pre-seeded like every other resilience family.
FED_MEMBER_STATES = ("live", "dead")

FED_MEMBERS = REGISTRY.gauge(
    "gol_fed_members",
    "Fleet servers known to the federation router's member registry, by "
    "state: live (heartbeat within GOL_FED_DEAD_AFTER) or dead "
    "(heartbeats lapsed; its runs are being adopted by survivors). A "
    "re-registering dead member moves back to live.",
    label_names=("state",))
for _s in FED_MEMBER_STATES:
    FED_MEMBERS.labels(state=_s)

FED_HEARTBEAT_AGE_MS = REGISTRY.gauge(
    "gol_fed_heartbeat_age_ms",
    "Heartbeat age quantiles in milliseconds across live members at the "
    "router's last registry sweep (now - last heartbeat stamp). Climbs "
    "toward GOL_FED_DEAD_AFTER*1000 as a member goes quiet.",
    label_names=("q",))
for _q in SLO_QUANTILES:
    FED_HEARTBEAT_AGE_MS.labels(q=_q)

FED_FAILOVERS = REGISTRY.counter(
    "gol_fed_failovers_total",
    "Members declared dead by the router's heartbeat sweep (each "
    "declaration triggers adoption of the member's placed runs by "
    "rendezvous re-placement over the survivors).")

FED_ADOPTED_RUNS = REGISTRY.counter(
    "gol_fed_adopted_runs_total",
    "Runs adopted from a dead member via its shared-root per-run "
    "checkpoints, by outcome: ok (the adopting member verified and "
    "restored the manifest, run re-queued for placement), error "
    "(adoption RPC failed or the restore exhausted its quarantine "
    "budget).",
    label_names=("status",))
for _s in ("ok", "error"):
    FED_ADOPTED_RUNS.labels(status=_s)

FED_ROUTER_OVERHEAD_MS = REGISTRY.gauge(
    "gol_fed_router_overhead_ms",
    "Router proxy overhead quantiles in milliseconds: client-facing "
    "wall time of a proxied RPC minus the member-facing round trip "
    "(relay framing + placement lookup + dedupe bookkeeping), from a "
    "log-bucket estimator flushed at the SLO cadence.",
    label_names=("q",))
for _q in SLO_QUANTILES:
    FED_ROUTER_OVERHEAD_MS.labels(q=_q)


# ----------------------------------------------- fleet telemetry plane

# Heartbeat-borne snapshot export (obs/export.py, member side). The
# drop family labels mirror export.FAMILY_LABELS plus "events" — a
# closed set, so an over-budget snapshot meters exactly what it shed.
SNAPSHOT_FAMILIES = ("resident", "queue", "staleness", "quantum",
                     "slo", "cups", "dev_bytes", "usage", "events",
                     "unknown")

FED_SNAPSHOT_BYTES = REGISTRY.gauge(
    "gol_fed_snapshot_bytes",
    "Encoded size in bytes of the most recent telemetry snapshot this "
    "member attached to its RegisterMember heartbeat; always <= "
    "GOL_FED_SNAPSHOT_MAX (over-budget snapshots degrade by dropping "
    "families, never by fattening the beat).")
FED_SNAPSHOT_TOTAL = REGISTRY.counter(
    "gol_fed_snapshot_total",
    "Telemetry snapshots built for heartbeat export, by kind: full "
    "(first beat, or after the router requested a resync) or delta "
    "(only families whose value changed since the last ACKED beat).",
    label_names=("kind",))
for _k in ("full", "delta"):
    FED_SNAPSHOT_TOTAL.labels(kind=_k)
FED_SNAPSHOT_DROPPED = REGISTRY.counter(
    "gol_fed_snapshot_dropped_total",
    "Snapshot families (or the event batch) dropped to fit the "
    "GOL_FED_SNAPSHOT_MAX byte budget, lowest priority first; dropped "
    "deltas stay uncommitted and re-ship on the next beat.",
    label_names=("family",))
for _f in SNAPSHOT_FAMILIES:
    FED_SNAPSHOT_DROPPED.labels(family=_f)
FED_SNAPSHOT_INGESTED = REGISTRY.counter(
    "gol_fed_snapshot_ingested_total",
    "Heartbeat telemetry snapshots the registry tier merged into its "
    "per-member state (router side).")

# Fleet rollups the registry tier re-publishes every sweep from the
# ingested member snapshots (router side) — the sensing inputs of the
# ROADMAP item-3 control loop.
FED_AGG_RUNS_RESIDENT = REGISTRY.gauge(
    "gol_fed_agg_runs_resident",
    "Fleet-wide resident-run total: exact sum of gol_runs_resident "
    "reported by live members at the router's last telemetry sweep.")
FED_AGG_QUEUE_DEPTH = REGISTRY.gauge(
    "gol_fed_agg_queue_depth",
    "Fleet-wide admission-queue depth: sum of gol_fleet_queue_depth "
    "across live members at the last telemetry sweep.")
FED_AGG_CUPS = REGISTRY.gauge(
    "gol_fed_agg_cups",
    "Aggregate cell updates per second: sum of gol_engine_cups across "
    "live members at the last telemetry sweep.")
FED_AGG_STALENESS_MS = REGISTRY.gauge(
    "gol_fed_agg_staleness_ms",
    "Fleet staleness quantiles in milliseconds: per-quantile MAX of "
    "gol_fleet_staleness_ms across live members (the worst member "
    "bounds the fleet).",
    label_names=("q",))
for _q in SLO_QUANTILES:
    FED_AGG_STALENESS_MS.labels(q=_q)
FED_AGG_IMBALANCE = REGISTRY.gauge(
    "gol_fed_agg_imbalance_ratio",
    "max/mean of per-member resident-run counts across reporting live "
    "members (1.0 = balanced or fewer than one resident anywhere).")
FED_AGG_MEMBERS_REPORTING = REGISTRY.gauge(
    "gol_fed_agg_members_reporting",
    "Live members whose telemetry snapshot the registry has ingested "
    "(<= gol_fed_members{state='live'}; lags one heartbeat on join).")
FED_AGG_SLO_BREACHES = REGISTRY.gauge(
    "gol_fed_agg_slo_breaches_total",
    "Fleet-wide SLO breach total: sum of each live member's "
    "gol_slo_breaches_total as last reported (a gauge of summed "
    "member counters, so member death can lower it).")
FED_AGG_DEV_LIVE_BYTES = REGISTRY.gauge(
    "gol_fed_agg_dev_live_bytes",
    "Fleet-wide live device memory in bytes: sum of per-device "
    "gol_dev_live_bytes across live members at the last sweep.")
FED_AGG_PAYLOAD_BYTES = REGISTRY.gauge(
    "gol_fed_agg_payload_bytes",
    "Quantiles of ingested heartbeat-snapshot sizes in bytes (router "
    "side, log-bucket estimator over all members since start).",
    label_names=("q",))
for _q in SLO_QUANTILES:
    FED_AGG_PAYLOAD_BYTES.labels(q=_q)

# Bounded time-series store (obs/tsdb.py, registry tier).
TSDB_SERIES = REGISTRY.gauge(
    "gol_tsdb_series",
    "Distinct series currently resident in the telemetry tsdb rings "
    "(hard-capped at GOL_TSDB_MAX_SERIES).")
TSDB_POINTS = REGISTRY.gauge(
    "gol_tsdb_points_total",
    "Samples appended to the telemetry tsdb since process start "
    "(every sample lands in all retention tiers at once).")
TSDB_EVICTIONS = REGISTRY.gauge(
    "gol_tsdb_evictions_total",
    "Series evicted least-recently-appended-first because the tsdb "
    "hit its GOL_TSDB_MAX_SERIES cardinality cap; a runaway label "
    "source degrades retention, never memory.")

# Alerting (obs/alerts.py). Built-in rule names are pre-seeded; rules
# added via GOL_ALERT_RULES seed their children at manager start.
ALERT_BUILTIN_RULES = ("member-death", "staleness-ceiling",
                       "queue-depth", "resident-imbalance")
ALERTS_ACTIVE = REGISTRY.gauge(
    "gol_alerts_active",
    "1 while the named alert rule is firing, else 0 (firing requires "
    "the breach to hold for the rule's for_s; resolving requires "
    "clear_s continuously below threshold — hysteresis, so a flapping "
    "signal cannot strobe this gauge).",
    label_names=("rule",))
ALERTS_FIRED = REGISTRY.counter(
    "gol_alerts_fired_total",
    "firing transitions of the named alert rule (each one also lands "
    "a flight-recorder event and a fleet audit-log record).",
    label_names=("rule",))
for _r in ALERT_BUILTIN_RULES:
    ALERTS_ACTIVE.labels(rule=_r)
    ALERTS_FIRED.labels(rule=_r)

# Fleet audit log (obs/audit.py): the gol-fleet-audit/1 record kinds,
# clamped like every other label set.
AUDIT_KINDS = ("member_join", "member_rejoin", "member_death", "adopt",
               "migrate", "quarantine", "alert_fired", "alert_resolved",
               "other")
AUDIT_RECORDS = REGISTRY.counter(
    "gol_audit_records_total",
    "gol-fleet-audit/1 records appended (durable log on the registry "
    "tier, bounded in-memory event ring on members), by kind.",
    label_names=("kind",))
for _k in AUDIT_KINDS:
    AUDIT_RECORDS.labels(kind=_k)

# Event-sourced run journal (gol_tpu/journal.py): the gol-journal/1
# hash-chained black box. Kinds mirror journal.KINDS — a closed set so
# an arbitrary append can't mint unbounded label values.
JOURNAL_KINDS = ("create", "rule", "reseed", "pause", "resume", "fuse",
                 "link", "restore", "digest", "migrate_out", "usage",
                 "end", "other")
JOURNAL_EVENTS = REGISTRY.counter(
    "gol_journal_events_total",
    "gol-journal/1 records appended to per-run hash-chained journals "
    "(GOL_JOURNAL), by event kind.",
    label_names=("kind",))
for _k in JOURNAL_KINDS:
    JOURNAL_EVENTS.labels(kind=_k)
JOURNAL_BYTES = REGISTRY.counter(
    "gol_journal_bytes_total",
    "Bytes appended to journal files, newline included — the black "
    "box's disk footprint rate.")
JOURNAL_WALL_US = REGISTRY.counter(
    "gol_journal_wall_us_total",
    "Host wall microseconds spent inside the journal hot path — "
    "canonical board digests, inline seed encodes, and hash-chained "
    "appends. The bench.py --journal leg gates this as a share of run "
    "wall (journal_overhead_pct), the same in-process cost-accounting "
    "pattern as telemetry_overhead_pct: a direct measure that cannot "
    "flap with host contention the way differential wall clock does.")
JOURNAL_DIGESTS = REGISTRY.counter(
    "gol_journal_digests_total",
    "Board-digest events journaled (engine chunk-boundary cadence via "
    "GOL_JOURNAL_DIGEST_EVERY plus every checkpoint written while "
    "journaling is on) — each one is a mid-history bit-identity "
    "assertion a replay can check.")
REPLAY_DIVERGENCE = REGISTRY.counter(
    "gol_replay_divergence_total",
    "Digest events at which a tools/replay_audit.py replay disagreed "
    "with the recorded journal. Stays 0 for a healthy deterministic "
    "engine; any increment means the recorded history and the engine "
    "no longer agree and the auditor has bisected to the first "
    "divergent turn.")


# ------------------------- per-run usage metering & capacity attribution

# Per-run accumulators live on the reference-swapped /healthz "usage"
# doc (top-K talkers, K = GOL_USAGE_TOPK), never as metric labels —
# the PR-8 cardinality posture. The registry only carries bounded
# scalars and the closed per-bucket capacity families below.
USAGE_RUNS_TRACKED = REGISTRY.gauge(
    "gol_usage_runs_tracked",
    "Runs with exact usage accumulators open on this member (resident "
    "runs only; bounded by the admission controller's max_runs, not by "
    "lifetime run count).")
USAGE_WALL_US = REGISTRY.counter(
    "gol_usage_wall_us_total",
    "Host wall microseconds spent inside the usage meter — dispatch "
    "apportionment, per-run charge updates, and doc rebuilds. The "
    "bench.py --usage leg gates this as a share of run wall "
    "(usage_overhead_pct), the same in-process cost-accounting pattern "
    "as journal_overhead_pct: a direct measure that cannot flap with "
    "host contention the way differential wall clock does.")
USAGE_FLUSHES = REGISTRY.counter(
    "gol_usage_flushes_total",
    "Usage-doc rebuilds (reference-swapped /healthz 'usage' doc), "
    "throttled to at most one per GOL_USAGE_FLUSH_S.")
USAGE_UNTRACKED = REGISTRY.counter(
    "gol_usage_untracked_total",
    "Charges whose run id had no open accumulator (late broadcast or "
    "checkpoint stragglers after destroy, unscoped legacy traffic) — "
    "folded into a single aggregate so stragglers can never grow the "
    "per-run map past its bound.")

# Capacity headroom model: measured quantum wall + the admission
# controller's memory charge, projected per bucket class. Bucket label
# values are the engine's configured "<h>x<w>" classes — env-dependent
# like gol_fleet_quantum_ms, so not pre-seeded here.
CAPACITY_ADMISSIBLE_RUNS = REGISTRY.gauge(
    "gol_capacity_admissible_runs",
    "Projected additional runs of this bucket class the member could "
    "admit right now: min(free admission budget // per-run memory "
    "charge, free slots).",
    label_names=("bucket",))
CAPACITY_CUPS_HEADROOM = REGISTRY.gauge(
    "gol_capacity_cups_headroom",
    "Projected additional cell updates per second this bucket class "
    "could absorb: admissible runs x cells per board x turns per "
    "dispatch / measured mean quantum wall (0 until a quantum has "
    "been measured).",
    label_names=("bucket",))
CAPACITY_RUN_COST_BYTES = REGISTRY.gauge(
    "gol_capacity_run_cost_bytes",
    "Admission memory charge for one run of this bucket class "
    "(height x words-per-row x 4 bytes x the double-buffer/halo cost "
    "factor).",
    label_names=("bucket",))
CAPACITY_FREE_BYTES = REGISTRY.gauge(
    "gol_capacity_free_bytes",
    "Uncommitted admission memory budget on this member "
    "(budget_bytes - committed_bytes).")

# Router-side fleet rollups of the heartbeat-borne "use" family.
FED_AGG_USAGE_RUNS_TRACKED = REGISTRY.gauge(
    "gol_fed_agg_usage_runs_tracked",
    "Fleet-wide sum of gol_usage_runs_tracked across live members at "
    "the router's last telemetry sweep.")
FED_AGG_USAGE_ADMISSIBLE_RUNS = REGISTRY.gauge(
    "gol_fed_agg_usage_admissible_runs",
    "Fleet-wide projected admissible runs: sum over live members of "
    "each member's best bucket-class gol_capacity_admissible_runs as "
    "last reported on the heartbeat snapshot.")
FED_AGG_USAGE_CUPS_HEADROOM = REGISTRY.gauge(
    "gol_fed_agg_usage_cups_headroom",
    "Fleet-wide aggregate CUPS headroom: sum of per-member capacity "
    "headroom (all bucket classes) as last reported on the heartbeat "
    "snapshot.")


# ------------------------------------------- live migration & resharding

# Terminal outcomes of one Rescale cutover. Closed set, pre-seeded:
# ok (target authoritative, source copy retired), rolled_back (a phase
# failed before the redirect committed; the source copy resumed),
# error (rollback itself failed — the source copy is still present and
# authoritative, but the failure needed operator-visible attribution).
MIGRATION_STATUSES = ("ok", "rolled_back", "error")

MIGRATIONS = REGISTRY.counter(
    "gol_migrations_total",
    "Rescale live-migration attempts completed by this process's "
    "migration coordinator, by terminal status: ok (two-phase cutover "
    "committed — the run now lives on the target member and the router "
    "placement is pinned there), rolled_back (a phase failed or a "
    "GOL_CHAOS migrate_fail fault fired before the redirect committed; "
    "the staged target copy was destroyed and the source copy resumed "
    "authoritative), error (rollback was itself incomplete; exactly-one-"
    "owner still holds — the source was never released — but the "
    "attempt needs attention).",
    label_names=("status",))
for _s in MIGRATION_STATUSES:
    MIGRATIONS.labels(status=_s)

MIGRATION_DOWNTIME_MS = REGISTRY.gauge(
    "gol_migration_downtime_ms",
    "Quantiles of the redirect-slice wall time in milliseconds across "
    "completed migrations: the window between pinning the router "
    "placement at the target and retiring the source copy (with viewer "
    "re-key), during which a racing run-scoped RPC is answered with a "
    "retryable 'moved:' redirect instead of an error — downtime is "
    "latency, never an error.",
    label_names=("q",))
for _q in SLO_QUANTILES:
    MIGRATION_DOWNTIME_MS.labels(q=_q)


# ------------------------------------------------- broadcast tier & gateway

# Deliberately run_id-free: one scalar per family. 100k spectators of
# one viral run must not become 100k label children, and per-run detail
# already lives on /healthz — same cardinality discipline as the fleet
# staleness gauges.
BCAST_STREAMS = REGISTRY.gauge(
    "gol_bcast_streams",
    "Epoch broadcast streams currently open on this server (one per "
    "(run, view geometry) with at least one subscriber ever; dropped "
    "with the run).")
BCAST_SUBSCRIBERS = REGISTRY.gauge(
    "gol_bcast_subscribers",
    "Viewer sockets currently attached to any broadcast stream "
    "(Subscribe upgrades adopted by the gateway event loop). "
    "Deliberately run_id-free — bounded cardinality regardless of how "
    "many runs or viewers exist.")
GATEWAY_CONNECTIONS = REGISTRY.gauge(
    "gol_gateway_connections",
    "Sockets currently owned by the selectors-based viewer gateway "
    "(subscribers plus connections mid-adoption); capped by "
    "GOL_GATEWAY_MAX.")

BCAST_FRAMES = REGISTRY.counter(
    "gol_bcast_frames_total",
    "Frames published into broadcast epoch streams, by kind: key "
    "(standalone keyframe — plain codec, decodable with no basis) or "
    "delta (xrle against the shared epoch basis). Each published frame "
    "is encoded exactly once no matter how many subscribers consume "
    "it: gol_wire_encode_calls_total advances by exactly 1 per "
    "publication (the bench.py --broadcast zero-work witness).",
    label_names=("kind",))
for _k in ("key", "delta"):
    BCAST_FRAMES.labels(kind=_k)

BCAST_FRAMES_DROPPED = REGISTRY.counter(
    "gol_bcast_frames_dropped_total",
    "Frames a slow subscriber never received because the stream ring "
    "overtook it and the gateway skipped it forward to the newest "
    "keyframe (the drop policy that keeps one stalled socket from "
    "backpressuring the ring, other subscribers, or the engine chunk "
    "loop).")
BCAST_SENT_BYTES = REGISTRY.counter(
    "gol_bcast_sent_bytes_total",
    "Bytes the gateway pushed to subscribers (every socket counts the "
    "shared frame bytes it actually received). Fan-out cost of the "
    "broadcast tier; the encode cost is in gol_wire_frame_bytes_total "
    "exactly once per published frame.")

BCAST_FANOUT_MS = REGISTRY.gauge(
    "gol_bcast_fanout_ms",
    "Fan-out latency quantiles in milliseconds from a log-bucket "
    "estimator (obs/slo.py): publication of a frame into the stream "
    "ring -> the last byte of that frame handed to a subscriber's "
    "socket, one sample per (frame, subscriber) delivery.",
    label_names=("q",))
for _q in SLO_QUANTILES:
    BCAST_FANOUT_MS.labels(q=_q)


# ------------------------------------------------- tracing / flight recorder

TRACE_SPANS_TOTAL = REGISTRY.counter(
    "gol_trace_spans_total",
    "Spans finished by the in-process span tracer (obs/trace.py).")
TRACE_SPAN_DROPS_TOTAL = REGISTRY.counter(
    "gol_trace_span_drops_total",
    "Finished spans dropped because the tracer's export buffer was full "
    "(the flight-recorder ring still keeps the most recent tail).")
FLIGHT_DUMPS_TOTAL = REGISTRY.counter(
    "gol_flight_dumps_total",
    "Flight-recorder dumps written, by trigger reason.",
    label_names=("reason",))

# Same cardinality discipline as wire methods: reasons are clamped to a
# declared set and pre-seeded at zero.
FLIGHT_REASONS = ("sigterm", "watchdog", "exception", "manual", "unknown")
for _r in FLIGHT_REASONS:
    FLIGHT_DUMPS_TOTAL.labels(reason=_r)


def flight_reason_label(reason: str) -> str:
    """Clamp arbitrary dump reasons to the declared set."""
    return reason if reason in FLIGHT_REASONS else "unknown"


# ------------------------------------------------------------- checkpoints

CKPT_WRITES = REGISTRY.counter(
    "gol_ckpt_writes_total",
    "Checkpoint write attempts by the ckpt writer, by outcome: ok "
    "(durable manifest published), error (write pipeline raised), "
    "dropped (snapshot superseded before the disk caught up).",
    label_names=("status",))
CKPT_WRITE_SECONDS = REGISTRY.histogram(
    "gol_ckpt_write_seconds",
    "Wall seconds per checkpoint write (device→host copy, serialize, "
    "hash, atomic publish, retention) — on the background writer "
    "thread, overlapping engine compute.")
CKPT_BYTES = REGISTRY.counter(
    "gol_ckpt_bytes_total",
    "Payload bytes durably published by the ckpt writer.")
CKPT_LAST_TURN = REGISTRY.gauge(
    "gol_ckpt_last_turn",
    "Turn of the most recent durable checkpoint (manifest published).")
CKPT_RESTORES = REGISTRY.counter(
    "gol_ckpt_restores_total",
    "Checkpoint restore attempts, by outcome: ok, rejected (integrity "
    "verification refused the checkpoint), error.",
    label_names=("status",))

for _s in ("ok", "error", "dropped"):
    CKPT_WRITES.labels(status=_s)
for _s in ("ok", "rejected", "error"):
    CKPT_RESTORES.labels(status=_s)

CKPT_POOL_WRITERS = REGISTRY.gauge(
    "gol_ckpt_pool_writers",
    "Worker threads in the shared fleet checkpoint writer pool "
    "(GOL_FLEET_CKPT_WRITERS; 0 until a fleet cadence checkpoint is "
    "first submitted). Replaces the one-CheckpointWriter-per-run "
    "design: 512 residents share this fixed pool.")
CKPT_POOL_DEPTH = REGISTRY.gauge(
    "gol_ckpt_pool_depth",
    "Runs with a cadence snapshot pending in the shared writer pool "
    "(newest-wins per run; superseded snapshots count as "
    "gol_ckpt_writes_total{status='dropped'}). Bounded by resident "
    "runs, drained round-robin.")

# -------------------------------------------------------- device telemetry

# Values come from devstats.poll_device_memory(); this module stays free
# of jax imports so control-plane processes (client, tools) can scrape
# the catalogue without a device runtime.
DEV_LIVE_BYTES = REGISTRY.gauge(
    "gol_dev_live_bytes",
    "Live device memory in bytes per device, from "
    "device.memory_stats(); absent stats (CPU backends) leave the "
    "family empty and flip gol_dev_mem_supported to 0.",
    label_names=("device",))
DEV_PEAK_BYTES = REGISTRY.gauge(
    "gol_dev_peak_bytes",
    "Peak device memory in bytes per device since process start, from "
    "device.memory_stats().",
    label_names=("device",))
DEV_LIMIT_BYTES = REGISTRY.gauge(
    "gol_dev_limit_bytes",
    "Device memory capacity in bytes per device, where the backend "
    "reports one.",
    label_names=("device",))
DEV_MEM_SUPPORTED = REGISTRY.gauge(
    "gol_dev_mem_supported",
    "1 if device.memory_stats() returns data on this backend, else 0.")
DEV_DEVICES = REGISTRY.gauge(
    "gol_dev_devices",
    "Number of addressable devices visible to the process.")

# ---------------------------------------------------- mesh & halo telemetry

# Geometry of the most recently submitted run's device mesh (from
# parallel.mesh.mesh_geometry via devstats.note_mesh) plus the halo/
# collective traffic of sharded dispatches (obs/halostats.py). Axis
# labels are clamped to the declared mesh axes; device-count labels are
# bounded by the local device count, same as gol_dev_live_bytes.
MESH_AXES = ("rows", "cols", "slots")

MESH_DEVICES = REGISTRY.gauge(
    "gol_mesh_devices",
    "Devices in the most recently submitted run's mesh (1 for "
    "single-device runs).")
MESH_SHARDS = REGISTRY.gauge(
    "gol_mesh_shards",
    "Board shards in the most recently submitted run's mesh (equal to "
    "gol_mesh_devices for the 1-D and 2-D meshes the engine builds).")
MESH_AXIS_SIZE = REGISTRY.gauge(
    "gol_mesh_axis_size",
    "Mesh extent along each named axis for the most recently submitted "
    "run; 0 for an axis the mesh does not have (a 1-D rows mesh leaves "
    "cols at 0).",
    label_names=("axis",))

HALO_EXCHANGES = REGISTRY.counter(
    "gol_halo_exchanges_total",
    "Halo exchange rounds dispatched, by mesh axis. One round is one "
    "paired ppermute send (both ring directions issued together) across "
    "the whole mesh — the latency-exposure unit of the deep-halo macro "
    "schedule. Analytic from the dispatch geometry, not a link probe.",
    label_names=("axis",))
HALO_BYTES = REGISTRY.counter(
    "gol_halo_bytes_total",
    "Bytes moved by halo exchange rounds, by mesh axis, summed over "
    "every shard's sends (whole-mesh traffic). Analytic from the "
    "dispatch geometry: rows x row-bytes x shards per round.",
    label_names=("axis",))
HALO_EXCHANGE_SECONDS = REGISTRY.histogram(
    "gol_halo_exchange_seconds",
    "Wall seconds per halo exchange round: dispatch wall divided by the "
    "round count of that dispatch. Exact for synchronous callers "
    "(bench legs); pipeline-amortized for engine chunks. Prices the "
    "round including any local compute it failed to overlap.",
    buckets=(1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3,
             1e-2, 5e-2, 1e-1, 5e-1, 1.0))
SHARD_IMBALANCE = REGISTRY.gauge(
    "gol_shard_imbalance_ratio",
    "max/mean of per-shard cumulative readiness waits observed host-"
    "side after a sharded dispatch (obs/halostats."
    "measure_shard_imbalance): a completion-spread signal (1.0 = "
    "balanced), not a per-device timer.")

FUSE_K = REGISTRY.gauge(
    "gol_fuse_k",
    "Effective temporal-fusion depth of the most recently submitted "
    "run: generations advanced per materialized HBM state / per halo "
    "exchange round (ops/fused.py). 1 = auto/unfused (each dispatch "
    "tier keeps its native adaptive depth).")
# Closed dispatch-site label set: the three stack tiers that route
# through a fused program when GOL_FUSE_K pins a depth.
FUSE_TIERS = ("engine", "mesh", "fleet")
FUSED_DISPATCHES = REGISTRY.counter(
    "gol_fused_dispatches_total",
    "Temporally fused dispatches issued (k > 1 only), by dispatch "
    "site: 'engine' single/sharded chunk dispatches, 'mesh' bench mesh "
    "legs, 'fleet' bucket scans.",
    label_names=("tier",))
HALO_BYTES_PER_TURN = REGISTRY.gauge(
    "gol_halo_bytes_per_turn",
    "Analytic halo bytes per TURN of the most recent sharded dispatch, "
    "by mesh axis. Conserved under temporal fusion on the rows axis (a "
    "k-deep exchange ships 2k rows per k turns = 2 rows/turn) — the "
    "honest companion to the k-fold drop in exchange rounds/turn.",
    label_names=("axis",))
HALO_EXCHANGES_PER_TURN = REGISTRY.gauge(
    "gol_halo_exchanges_per_turn",
    "Analytic halo exchange rounds per TURN of the most recent sharded "
    "dispatch, by mesh axis: 1/T for a T-deep macro schedule — drops "
    "~k-fold when GOL_FUSE_K pins the depth (the latency-exposure win "
    "of temporal fusion).",
    label_names=("axis",))

for _a in MESH_AXES:
    MESH_AXIS_SIZE.labels(axis=_a)
    HALO_EXCHANGES.labels(axis=_a)
    HALO_BYTES.labels(axis=_a)
    HALO_BYTES_PER_TURN.labels(axis=_a)
    HALO_EXCHANGES_PER_TURN.labels(axis=_a)
for _t in FUSE_TIERS:
    FUSED_DISPATCHES.labels(tier=_t)
FUSE_K.set(1)


def mesh_axis_label(axis: str) -> str:
    """Clamp arbitrary mesh-axis names to the declared set."""
    return axis if axis in MESH_AXES else "other"


def fuse_tier_label(tier: str) -> str:
    """Clamp arbitrary fused-dispatch site names to the declared set."""
    return tier if tier in FUSE_TIERS else "engine"


# Per-device kind census: heterogeneous device lists (a CPU host plus
# accelerators, or mixed TPU generations) get one gauge child per kind,
# clamped to a small declared budget so a pathological backend cannot
# mint unbounded label values.
DEV_KIND_MAX = 8

DEV_KIND_DEVICES = REGISTRY.gauge(
    "gol_dev_kind_devices",
    "Addressable devices by device_kind string, from the last device "
    "poll. At most DEV_KIND_MAX distinct kinds are labelled; overflow "
    "kinds aggregate under 'other'.",
    label_names=("kind",))

_seen_kinds: set = set()


def dev_kind_label(kind: str) -> str:
    """Clamp device-kind label values to a bounded set (first
    DEV_KIND_MAX distinct kinds seen, then 'other')."""
    if kind in _seen_kinds:
        return kind
    if len(_seen_kinds) < DEV_KIND_MAX:
        _seen_kinds.add(kind)
        return kind
    return "other"


DEV_MEM_STATS_SUPPORTED = REGISTRY.gauge(
    "gol_dev_mem_stats_supported",
    "1 if device.memory_stats() returns data for this device, else 0 — "
    "one child per addressable device, so heterogeneous lists (some "
    "devices with stats, some without) are visible per device rather "
    "than collapsed into the scalar gol_dev_mem_supported.",
    label_names=("device",))

# ------------------------------------------------------------ compilation

COMPILE_TOTAL = REGISTRY.counter(
    "gol_compile_total",
    "XLA backend compilations observed via jax.monitoring (cache hits "
    "do not count).")
COMPILE_CACHE_HITS = REGISTRY.counter(
    "gol_compile_cache_hits_total",
    "Persistent compilation-cache hits observed via jax.monitoring.")
COMPILE_CACHE_MISSES = REGISTRY.counter(
    "gol_compile_cache_misses_total",
    "Persistent compilation-cache misses observed via jax.monitoring.")
COMPILE_SECONDS = REGISTRY.histogram(
    "gol_compile_seconds",
    "Wall seconds per XLA backend compilation.",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             120.0, 300.0))
COMPILE_STEP_SIGNATURES = REGISTRY.counter(
    "gol_compile_step_signatures_total",
    "Distinct engine step signatures (representation, board shape, "
    "dtype, mesh, rule) seen this process; each new one implies a "
    "fresh jit trace + compile.")

# --------------------------------------------------------------- profiler

PROFILE_CAPTURES = REGISTRY.counter(
    "gol_profile_captures_total",
    "On-demand jax.profiler trace captures, by outcome.",
    label_names=("status",))
PROFILE_ARMED = REGISTRY.gauge(
    "gol_profile_armed",
    "1 while a profile capture request is pending or in progress.")

for _s in ("ok", "error"):
    PROFILE_CAPTURES.labels(status=_s)
