"""Bounded in-memory multi-resolution time-series rings.

The registry tier keeps a short history of every fleet signal it
aggregates (the sensing half of the ROADMAP item-3 control loop) —
but a router is a long-lived control-plane process and MUST NOT grow
without bound, so retention is rings all the way down:

    tier   resolution   capacity (default)   span
    raw    10 s         90 points            ~15 min
    1m     60 s         120 points           ~2 h
    10m    600 s        144 points           ~24 h

Every append lands in ALL tiers at once: each tier keeps one open
bucket at its ring tail and merges samples whose timestamp falls in
that bucket (count/sum/min/max/last), pushing a new slot — and
evicting the oldest when full — only when the bucket rolls over.
That makes downsampling EXACT (a 1m bucket's min/max/mean/last are
computed from the raw samples themselves, not re-aggregated from the
raw tier's buckets) and append O(1) with no background compaction
thread.

Cardinality is hard-capped: at most GOL_TSDB_MAX_SERIES distinct
(name, labels) series; inserting past the cap evicts the least-
recently-appended series and meters it (gol_tsdb_evictions_total).
A runaway label source degrades retention, never memory.

Stdlib-only, no jax import — same discipline as obs/metrics.py.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["TSDB", "Tier", "tier_table"]

# Env knobs (read at TSDB construction, so tests can monkeypatch).
ENV_RAW_RES = "GOL_TSDB_RAW_RES"        # raw tier bucket width, seconds
ENV_RAW_CAP = "GOL_TSDB_RAW_CAP"        # raw tier ring capacity, points
ENV_1M_CAP = "GOL_TSDB_1M_CAP"
ENV_10M_CAP = "GOL_TSDB_10M_CAP"
ENV_MAX_SERIES = "GOL_TSDB_MAX_SERIES"  # hard cardinality cap

DEFAULT_RAW_RES = 10.0
DEFAULT_RAW_CAP = 90
DEFAULT_1M_CAP = 120
DEFAULT_10M_CAP = 144
DEFAULT_MAX_SERIES = 512


def _env_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return v if v > 0 else default


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return v if v > 0 else default


class Tier:
    """One resolution ring: fixed-capacity deque of merged buckets.

    Each slot is a mutable list [bucket_start_s, count, min, max, sum,
    last] — the open bucket is always the tail; samples older than the
    open bucket merge into it rather than resurrecting closed buckets
    (out-of-order arrivals are rare and sub-resolution here)."""

    __slots__ = ("name", "res_s", "cap", "ring")

    def __init__(self, name: str, res_s: float, cap: int) -> None:
        self.name = name
        self.res_s = float(res_s)
        self.cap = max(int(cap), 1)
        self.ring: deque = deque(maxlen=self.cap)

    def append(self, ts: float, value: float) -> None:
        bucket = ts - (ts % self.res_s)
        ring = self.ring
        if ring and bucket <= ring[-1][0]:
            slot = ring[-1]
            slot[1] += 1
            if value < slot[2]:
                slot[2] = value
            if value > slot[3]:
                slot[3] = value
            slot[4] += value
            slot[5] = value
        else:
            # deque(maxlen) drops the head for us: eviction is the ring
            # overwriting its oldest bucket, by construction.
            ring.append([bucket, 1, value, value, value, value])

    def points(self, since: float = 0.0) -> list:
        out = []
        for slot in self.ring:
            if slot[0] < since:
                continue
            out.append({"t": slot[0], "count": slot[1], "min": slot[2],
                        "max": slot[3], "mean": slot[4] / slot[1],
                        "last": slot[5]})
        return out


def _tier_specs() -> Tuple[Tuple[str, float, int], ...]:
    raw_res = _env_float(ENV_RAW_RES, DEFAULT_RAW_RES)
    return (
        ("raw", raw_res, _env_int(ENV_RAW_CAP, DEFAULT_RAW_CAP)),
        ("1m", 60.0, _env_int(ENV_1M_CAP, DEFAULT_1M_CAP)),
        ("10m", 600.0, _env_int(ENV_10M_CAP, DEFAULT_10M_CAP)),
    )


def tier_table() -> list:
    """The retention table (resolution × capacity ⇒ span) as data —
    rendered on /healthz and in fleet_top, documented in
    docs/OBSERVABILITY.md."""
    return [{"tier": n, "res_s": r, "cap": c, "span_s": r * c}
            for n, r, c in _tier_specs()]


class _Series:
    __slots__ = ("name", "labels", "tiers")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 specs) -> None:
        self.name = name
        self.labels = labels
        self.tiers = {n: Tier(n, r, c) for n, r, c in specs}


class TSDB:
    """The bounded store: `append` is O(1) (one lock, one dict move,
    three ring merges); `query` returns merged buckets for one tier.

    Memory ceiling = max_series × Σ tier capacities × one 6-field
    slot — fixed at construction, independent of uptime."""

    def __init__(self, max_series: Optional[int] = None,
                 now=time.time) -> None:
        self._specs = _tier_specs()
        self.max_series = (_env_int(ENV_MAX_SERIES, DEFAULT_MAX_SERIES)
                           if max_series is None else max(int(max_series), 1))
        self._now = now
        self._lock = threading.Lock()
        # OrderedDict in least-recently-appended order: eviction pops
        # from the front, append moves the series to the back.
        self._series: "OrderedDict[tuple, _Series]" = OrderedDict()
        self._points_total = 0
        self._evictions_total = 0

    @staticmethod
    def _key(name: str, labels) -> tuple:
        if not labels:
            return (name,)
        if isinstance(labels, dict):
            labels = labels.items()
        return (name,) + tuple(sorted(
            (str(k), str(v)) for k, v in labels))

    def append(self, name: str, value: float, labels=(),
               ts: Optional[float] = None) -> None:
        try:
            value = float(value)
        except (TypeError, ValueError):
            return
        if ts is None:
            ts = self._now()
        key = self._key(name, labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                while len(self._series) >= self.max_series:
                    self._series.popitem(last=False)
                    self._evictions_total += 1
                series = _Series(name, key[1:], self._specs)
                self._series[key] = series
            else:
                self._series.move_to_end(key)
            for tier in series.tiers.values():
                tier.append(ts, value)
            self._points_total += 1
        self._publish()

    def _publish(self) -> None:
        try:  # metering is best-effort; the store works registry-less
            from gol_tpu.obs import catalog as obs
            obs.TSDB_SERIES.set(len(self._series))
            obs.TSDB_POINTS.set(self._points_total)
            obs.TSDB_EVICTIONS.set(self._evictions_total)
        except Exception:
            pass

    def query(self, name: str, labels=(), tier: str = "raw",
              since: float = 0.0) -> list:
        key = self._key(name, labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                return []
            t = series.tiers.get(tier)
            return t.points(since) if t is not None else []

    def series_names(self) -> list:
        with self._lock:
            rows = [{"name": s.name, "labels": dict(s.labels)}
                    for s in self._series.values()]
        rows.sort(key=lambda d: (d["name"], sorted(d["labels"].items())))
        return rows

    def doc(self) -> dict:
        """Summary for /healthz and GetTelemetry — counts only, never
        the point data (that's what `query` is for)."""
        with self._lock:
            return {"series": len(self._series),
                    "points_total": self._points_total,
                    "evictions_total": self._evictions_total,
                    "max_series": self.max_series,
                    "tiers": tier_table()}
