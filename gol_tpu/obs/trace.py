"""In-process span tracer with wire-propagated context.

Spans answer the question PR 1's aggregate counters cannot: *which*
controller action caused *which* RPC, handled by *which* server
dispatch, covering *which* engine chunks, when something stalls. The
pieces:

  * `Span` — name, 16-hex trace id shared by a whole causal chain,
    16-hex span id, optional parent span id, monotonic start/end, the
    recording pid/tid, and a small attrs dict.
  * `Tracer` — thread-safe recorder. Finished spans land in a bounded
    first-N buffer (cap `GOL_TRACE_SPANS_CAP`, default 16384; overflow
    increments `gol_trace_span_drops_total` — the START of a run is
    what the export is for, the flight recorder keeps the most recent
    tail) and in the flight-recorder ring. Open spans are tracked so a
    crash dump can show what was in flight.
  * Thread-local context stack — `span()`/`push()` make the innermost
    open span the implicit parent for the current thread, which is how
    an engine chunk span parents under the server handler span without
    either knowing about the other.
  * Wire propagation — `context()` renders the current span as the
    compact `{"t": trace_id, "s": span_id}` dict that `wire.send_msg`
    puts in the JSON header under `"tc"`; `parse_context()` validates
    it on the receiving side so a hostile peer cannot inject junk.
  * Chrome trace-event export — `export_chrome()` writes the
    `{"traceEvents": [...]}` JSON that Perfetto/`chrome://tracing`
    load directly: one "X" (complete) event per finished span, "B"
    (begin, never ended) events for spans still open at export time,
    and "M" metadata rows naming each process/thread. Timestamps are
    monotonic-clock readings shifted by a once-sampled wall-clock
    epoch, so controller and server exports line up on one timeline.

Timestamp model: `time.monotonic()` for durations (immune to NTP
steps), `epoch = time.time() - time.monotonic()` sampled once per
process for placement. Cross-process alignment is therefore as good as
the hosts' wall clocks — fine for eyeballing a timeline, and the parent
links stay exact regardless.

Overhead follows the obs budget: spans are created at RPC/chunk/keypress
boundaries on host threads (a lock + dict append each, ~µs), never
inside jitted code or per-cell paths.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Union

from gol_tpu.obs import catalog as obs
from gol_tpu.obs import flight as obs_flight

TRACE_SPANS_ENV = "GOL_TRACE_SPANS"      # export destination
TRACE_SPANS_CAP_ENV = "GOL_TRACE_SPANS_CAP"
TRACE_SPANS_CAP_DEFAULT = 16384

_HEX = set("0123456789abcdef")


def _new_id() -> str:
    return os.urandom(8).hex()


def _valid_id(v: Any) -> bool:
    return (isinstance(v, str) and len(v) == 16
            and all(c in _HEX for c in v))


class Span:
    """One timed operation. Mutate attrs freely until `finish`."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id",
                 "start", "end", "pid", "tid", "thread", "attrs")

    def __init__(self, name: str, trace_id: str,
                 parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]] = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start = time.monotonic()
        self.end: Optional[float] = None
        self.pid = os.getpid()
        self.tid = threading.get_ident()
        self.thread = threading.current_thread().name
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}

    def context(self) -> Dict[str, str]:
        """The compact wire form: what goes under `"tc"` in headers."""
        return {"t": self.trace_id, "s": self.span_id}

    def to_dict(self) -> dict:
        d = {"name": self.name, "trace": self.trace_id,
             "span": self.span_id, "parent": self.parent_id,
             "start": self.start, "end": self.end,
             "pid": self.pid, "tid": self.tid, "thread": self.thread}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


# What `start(parent=...)` accepts: a live Span, a parsed/raw wire
# context dict, or None (force a new root).
ParentLike = Union["Span", Dict[str, str], None]
_INHERIT = object()  # default: innermost open span on this thread


def parse_context(obj: Any) -> Optional[Dict[str, str]]:
    """Validate a wire `"tc"` value; None unless it is exactly a dict
    with well-formed 16-hex `t` and `s` (peer input is untrusted)."""
    if (isinstance(obj, dict) and _valid_id(obj.get("t"))
            and _valid_id(obj.get("s"))):
        return {"t": obj["t"], "s": obj["s"]}
    return None


class Tracer:
    def __init__(self, cap: Optional[int] = None) -> None:
        if cap is None:
            try:
                cap = int(os.environ.get(TRACE_SPANS_CAP_ENV,
                                         TRACE_SPANS_CAP_DEFAULT))
            except ValueError:
                cap = TRACE_SPANS_CAP_DEFAULT
        self._cap = max(int(cap), 1)
        self._lock = threading.Lock()
        self._finished: List[dict] = []
        self._dropped = 0
        self._open: Dict[str, Span] = {}
        self._tls = threading.local()
        # Sampled once: shifts monotonic readings onto the wall clock
        # for export, so two processes' spans share one timeline.
        self.epoch = time.time() - time.monotonic()
        self._process_name = f"gol-pid-{os.getpid()}"

    # ---- naming ---------------------------------------------------------

    def set_process_name(self, name: str) -> None:
        with self._lock:
            self._process_name = str(name)

    # ---- thread-local context stack -------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def context(self) -> Optional[Dict[str, str]]:
        cur = self.current()
        return cur.context() if cur is not None else None

    def push(self, span: Span) -> None:
        self._stack().append(span)

    def pop(self, span: Optional[Span] = None) -> None:
        st = self._stack()
        if not st:
            return
        if span is None or st[-1] is span:
            st.pop()
        elif span in st:  # misnested finish — drop it and everything above
            del st[st.index(span):]

    # ---- span lifecycle -------------------------------------------------

    def start(self, name: str, parent: Any = _INHERIT,
              attrs: Optional[Dict[str, Any]] = None) -> Span:
        """Open a span. `parent` defaults to this thread's innermost
        open span; pass a Span, a wire `tc` dict, or None for a root."""
        if parent is _INHERIT:
            parent = self.current()
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            ctx = parse_context(parent)
            if ctx is not None:
                trace_id, parent_id = ctx["t"], ctx["s"]
            else:
                trace_id, parent_id = _new_id(), None
        span = Span(name, trace_id, parent_id, attrs)
        with self._lock:
            self._open[span.span_id] = span
        return span

    def finish(self, span: Span,
               error: Optional[BaseException] = None) -> None:
        if span.end is not None:
            return  # idempotent: recovery paths may double-finish
        span.end = time.monotonic()
        if error is not None:
            span.attrs["error"] = f"{type(error).__name__}: {error}"
        rec = span.to_dict()
        with self._lock:
            self._open.pop(span.span_id, None)
            if len(self._finished) < self._cap:
                self._finished.append(rec)
            else:
                self._dropped += 1
                obs.TRACE_SPAN_DROPS_TOTAL.inc()
        obs.TRACE_SPANS_TOTAL.inc()
        obs_flight.FLIGHT.record_span(rec)

    class _SpanCtx:
        __slots__ = ("_tracer", "span")

        def __init__(self, tracer: "Tracer", span: Span) -> None:
            self._tracer, self.span = tracer, span

        def __enter__(self) -> Span:
            self._tracer.push(self.span)
            return self.span

        def __exit__(self, et, ev, tb) -> bool:
            self._tracer.pop(self.span)
            self._tracer.finish(self.span, error=ev)
            return False

    def span(self, name: str, parent: Any = _INHERIT,
             attrs: Optional[Dict[str, Any]] = None) -> "_SpanCtx":
        """`with TRACER.span("serve.Ping", parent=tc):` — pushes onto
        the thread's context stack so nested spans parent under it."""
        return Tracer._SpanCtx(self, self.start(name, parent, attrs))

    # ---- introspection / export -----------------------------------------

    def open_spans(self) -> List[dict]:
        """Dicts for spans not yet finished (flight-dump provider)."""
        with self._lock:
            return [s.to_dict() for s in self._open.values()]

    def finished_spans(self) -> List[dict]:
        with self._lock:
            return list(self._finished)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def reset(self) -> None:
        """Tests only: forget everything recorded so far."""
        with self._lock:
            self._finished.clear()
            self._open.clear()
            self._dropped = 0

    def _wall_us(self, mono: float) -> float:
        return round((mono + self.epoch) * 1e6, 1)

    def chrome_doc(self) -> dict:
        """The span set as a Chrome trace-event document (Perfetto)."""
        with self._lock:
            finished = list(self._finished)
            open_ = [s.to_dict() for s in self._open.values()]
            pname = self._process_name
            dropped = self._dropped
        events: List[dict] = []
        threads = {}  # (pid, tid) -> thread name
        for rec in finished + open_:
            threads.setdefault((rec["pid"], rec["tid"]), rec["thread"])
            args = {"trace_id": rec["trace"], "span_id": rec["span"]}
            if rec.get("parent"):
                args["parent_id"] = rec["parent"]
            args.update(rec.get("attrs") or {})
            ev = {"name": rec["name"],
                  "cat": rec["name"].split(".", 1)[0],
                  "ts": self._wall_us(rec["start"]),
                  "pid": rec["pid"], "tid": rec["tid"], "args": args}
            if rec["end"] is None:
                ev["ph"] = "B"  # still open: begin with no end — the
                # killed-mid-run case the flight recorder exists for
            else:
                ev["ph"] = "X"
                ev["dur"] = round((rec["end"] - rec["start"]) * 1e6, 1)
            events.append(ev)
        pids = sorted({pid for pid, _ in threads})
        meta: List[dict] = []
        for pid in pids:
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": pname}})
        for (pid, tid), tname in sorted(threads.items()):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "otherData": {"producer": "gol_tpu.obs.trace",
                              "run_id": obs_flight.RUN_ID,
                              "dropped_spans": dropped}}

    def export_chrome(self, path: str) -> str:
        """Write the Chrome trace JSON; a directory path (or trailing
        separator) gets one `gol-spans-<pid>.json` per process so the
        controller and a server can share one setting."""
        if os.path.isdir(path) or path.endswith(os.sep):
            path = os.path.join(path, f"gol-spans-{os.getpid()}.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.chrome_doc(), f, default=str)
            f.write("\n")
        os.replace(tmp, path)
        return path


# Process-wide tracer; its open spans feed every flight dump.
TRACER = Tracer()
obs_flight.FLIGHT.register_open_spans_provider(TRACER.open_spans)

# Module-level conveniences over the singleton.
start = TRACER.start
finish = TRACER.finish
span = TRACER.span
current = TRACER.current
context = TRACER.context
set_process_name = TRACER.set_process_name
export_chrome = TRACER.export_chrome


def hot_spans_enabled() -> bool:
    """Should per-chunk hot-path spans (engine.chunk / engine.flags) be
    recorded?  True when someone will actually consume them: a span
    export destination (GOL_TRACE_SPANS, what --trace-spans sets) or a
    flight-recorder dump path (GOL_FLIGHT) is configured.  Boundary
    spans (engine.run, serve.*, rpc.*) are always recorded — this only
    gates the spans whose cost scales with chunk count, which at small
    board sizes is pure per-chunk host overhead.  The engine reads it
    once per run, so flipping the env mid-run takes effect at the next
    submitted board."""
    return bool(os.environ.get(TRACE_SPANS_ENV, "").strip()
                or os.environ.get(obs_flight.FLIGHT_ENV, "").strip())


def export_from_env() -> Optional[str]:
    """Export to `GOL_TRACE_SPANS` if set (what `--trace-spans` sets);
    never raises — the shared obs.sink guard absorbs sink failures on
    shutdown paths."""
    from gol_tpu.obs.sink import guarded_export

    path = os.environ.get(TRACE_SPANS_ENV, "").strip()
    if not path:
        return None
    out: List[str] = []
    ok = guarded_export(lambda: out.append(TRACER.export_chrome(path)))
    return out[0] if ok and out else None


def validate_chrome(doc: dict) -> None:
    """Raise ValueError unless `doc` is structurally a Chrome
    trace-event document our exporter could have produced."""
    if not isinstance(doc, dict):
        raise ValueError(f"doc is {type(doc).__name__}, not object")
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        raise ValueError("traceEvents missing or empty")
    for ev in evs:
        if not isinstance(ev, dict):
            raise ValueError(f"event is not an object: {ev!r}")
        ph = ev.get("ph")
        if ph not in ("X", "B", "M"):
            raise ValueError(f"unexpected phase {ph!r}")
        if not isinstance(ev.get("name"), str):
            raise ValueError(f"event without name: {ev!r}")
        if not isinstance(ev.get("pid"), int) \
                or not isinstance(ev.get("tid"), int):
            raise ValueError(f"event without pid/tid: {ev!r}")
        if ph != "M":
            if not isinstance(ev.get("ts"), (int, float)):
                raise ValueError(f"event without ts: {ev!r}")
            args = ev.get("args")
            if not isinstance(args, dict) \
                    or not _valid_id(args.get("trace_id")) \
                    or not _valid_id(args.get("span_id")):
                raise ValueError(f"event without span ids: {ev!r}")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            raise ValueError(f"X event without dur: {ev!r}")
