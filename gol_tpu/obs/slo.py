"""Serving-tier SLO layer: bounded-memory latency quantiles.

The serving tier (PR 7's fleet engine behind `server.py`) needs latency
*percentiles*, not just counters — a p99 regression is invisible in a
request count and averaged away in a sum. Classic reservoir or
sample-retaining estimators are the wrong shape for a hot RPC path, so
this module provides a **log-bucket quantile estimator**: a fixed array
of log-spaced buckets covering [lo, hi] seconds. One observation is one
`log()` plus one integer increment; memory is O(buckets) forever; and
the reported quantile is provably within **one geometric bucket width**
of the exact sample quantile (the true rank-q sample lies inside the
bucket whose upper edge we report, so `true <= reported <= true*ratio`
for values inside [lo, hi]; out-of-range values clamp to the edge
buckets and are only ordered, not located).

Publication follows the PR-6 batched cadence: estimators are updated
per RPC (the per-call cost is one short lock, the same budget class as
the existing `.labels().inc()` metering on those paths), but the
derived `gol_rpc_latency_ms{kind,method,q}` gauges move only at
`FLUSH_SECONDS` intervals via lock-free `Gauge.set`. The fleet engine
loop never touches an estimator lock per quantum — it accumulates plain
local lists and feeds `observe_batch` from its own 0.5 s `_flush`, so
the `chunk_overhead_us` ceilings hold with the SLO layer enabled.

SLO objectives: `GOL_SLO_P99_MS` (float, default 0 = disabled) sets a
p99 objective in milliseconds across every (kind, method). When a flush
finds a method's p99 above the objective *and* new samples arrived in
the window, it increments `gol_slo_breaches_total{kind,method}` and
records a structured event into the flight-recorder ring — the black
box a post-mortem reads. Breaches never dump by themselves (dumps stay
operator-opted-in via GOL_FLIGHT).

Fleet health: the fleet loop publishes a cached health document here
(`set_fleet_health`) — aggregate staleness/queue percentiles plus a
top-K worst-runs table — which `/healthz` serves without ever taking an
engine lock or syncing a device.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from gol_tpu.obs import catalog as obs
from gol_tpu.obs import flight as obs_flight

# Default range: 50 µs (well under one loopback RPC) to 60 s (anything
# slower has long since failed its gate). 96 buckets over that span is
# a ratio of ~1.158 per bucket — the ~16% one-bucket-width error bound.
DEFAULT_LO = 50e-6
DEFAULT_HI = 60.0
DEFAULT_BUCKET_COUNT = 96

# Gauge publication cadence — matches the PR-6 batched metric flush.
FLUSH_SECONDS = 0.5

SLO_P99_ENV = "GOL_SLO_P99_MS"


class LogBucketEstimator:
    """Fixed log-spaced-bucket quantile estimator (no sample retention).

    `observe` computes the bucket index outside the lock and holds the
    lock only for three scalar updates; `observe_batch` amortises the
    lock over many samples (the batched-flush path). `percentile(q)`
    returns the upper edge of the bucket containing the rank-q sample:
    for samples inside [lo, hi], `true <= reported <= true * ratio`.
    Samples below `lo` clamp into the first bucket (reported as `lo`-
    edge), above `hi` into the last (reported as `hi`) — ordered
    correctly but located only to the range edge.
    """

    __slots__ = ("lo", "hi", "ratio", "_log_lo", "_inv_log_step",
                 "_n", "_counts", "_lock", "count", "sum")

    def __init__(self, lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                 buckets: int = DEFAULT_BUCKET_COUNT) -> None:
        if not (0.0 < lo < hi) or buckets < 1:
            raise ValueError(f"need 0 < lo < hi and buckets >= 1, got "
                             f"lo={lo} hi={hi} buckets={buckets}")
        self.lo = float(lo)
        self.hi = float(hi)
        self._n = int(buckets)
        span = math.log(self.hi / self.lo)
        self.ratio = math.exp(span / self._n)
        self._log_lo = math.log(self.lo)
        self._inv_log_step = self._n / span
        self._counts = [0] * self._n
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0

    def bucket_index(self, value: float) -> int:
        if not value > self.lo:  # also catches NaN / <=0 -> bucket 0
            return 0
        if value >= self.hi:
            return self._n - 1
        i = int((math.log(value) - self._log_lo) * self._inv_log_step)
        # float rounding at an exact edge can land one off either way
        return 0 if i < 0 else (self._n - 1 if i >= self._n else i)

    def bucket_upper(self, i: int) -> float:
        """Upper edge of bucket i (== hi for the last bucket)."""
        return self.hi if i >= self._n - 1 else \
            self.lo * self.ratio ** (i + 1)

    def observe(self, value: float) -> None:
        v = float(value)
        i = self.bucket_index(v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v

    def observe_batch(self, values: Iterable[float]) -> None:
        idx: List[int] = []
        total = 0.0
        for v in values:
            v = float(v)
            idx.append(self.bucket_index(v))
            total += v
        if not idx:
            return
        with self._lock:
            for i in idx:
                self._counts[i] += 1
            self.count += len(idx)
            self.sum += total

    def percentile(self, q: float) -> Optional[float]:
        (out,) = self.percentiles((q,))
        return out

    def percentiles(self, qs: Sequence[float]) -> Tuple[Optional[float],
                                                        ...]:
        """Quantile values for qs in [0, 1]; None while empty."""
        with self._lock:
            total = self.count
            counts = list(self._counts)
        if total <= 0:
            return tuple(None for _ in qs)
        out: List[Optional[float]] = []
        for q in qs:
            rank = min(total, max(1, math.ceil(float(q) * total)))
            cum = 0
            hit = self._n - 1
            for i, c in enumerate(counts):
                cum += c
                if cum >= rank:
                    hit = i
                    break
            out.append(self.bucket_upper(hit))
        return tuple(out)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * self._n
            self.count = 0
            self.sum = 0.0

    def snapshot(self) -> dict:
        p50, p95, p99 = self.percentiles((0.50, 0.95, 0.99))
        with self._lock:
            count, total = self.count, self.sum
        return {"count": count, "sum": round(total, 6),
                "p50": p50, "p95": p95, "p99": p99}


def exact_percentiles(values: Sequence[float],
                      qs: Sequence[float]) -> Tuple[Optional[float],
                                                    ...]:
    """Exact sample quantiles (rank = ceil(q*n), 1-indexed) — the
    oracle the estimator's error bound is stated against, and the
    aggregator for small in-memory populations (staleness across
    resident runs, bench sample lists)."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return tuple(None for _ in qs)
    n = len(vals)
    return tuple(vals[min(n, max(1, math.ceil(float(q) * n))) - 1]
                 for q in qs)


# ------------------------------------------------------- RPC instrumentation

_rpc_lock = threading.Lock()
_rpc: Dict[Tuple[str, str], LogBucketEstimator] = {}
# count already published per estimator, so a flush only re-derives and
# breach-checks methods that actually saw traffic in the window.
_published: Dict[Tuple[str, str], int] = {}
_flush_lock = threading.Lock()
_last_flush = 0.0


def _estimator(kind: str, method: str) -> LogBucketEstimator:
    key = (kind, method)
    est = _rpc.get(key)
    if est is None:
        with _rpc_lock:
            est = _rpc.setdefault(key, LogBucketEstimator())
    return est


def slo_p99_ms() -> float:
    """The configured p99 objective in ms (0 = disabled). Read per
    flush, not frozen at import, so tests and operators can retune a
    live process."""
    try:
        return float(os.environ.get(SLO_P99_ENV, "0") or 0.0)
    except ValueError:
        return 0.0


def observe_rpc(kind: str, method: str, seconds: float,
                now: Optional[float] = None) -> None:
    """One RPC latency sample. `kind` must be one of catalog.RPC_KINDS;
    `method` is clamped to the declared wire-method set so hostile
    headers can't mint label values. Cheap enough for every RPC: one
    estimator lock, plus at most one gauge flush per FLUSH_SECONDS
    across all callers."""
    m = obs.method_label(method)
    _estimator(kind, m).observe(seconds)
    maybe_flush(time.monotonic() if now is None else now)


def maybe_flush(now: float) -> None:
    if now - _last_flush < FLUSH_SECONDS:
        return
    flush(now)


def flush(now: Optional[float] = None) -> None:
    """Publish every active estimator's p50/p95/p99 to the
    gol_rpc_latency_ms gauges and run the breach check. Unconditional —
    tests, bench windows, and shutdown paths call this directly."""
    global _last_flush
    if now is None:
        now = time.monotonic()
    with _flush_lock:
        _last_flush = now
        with _rpc_lock:
            items = list(_rpc.items())
        objective = slo_p99_ms()
        for (kind, method), est in items:
            seen = est.count
            if seen == _published.get((kind, method)):
                continue
            _published[(kind, method)] = seen
            p50, p95, p99 = est.percentiles((0.50, 0.95, 0.99))
            if p50 is None:
                continue
            for q, v in (("p50", p50), ("p95", p95), ("p99", p99)):
                obs.RPC_LATENCY_MS.labels(
                    kind=kind, method=method, q=q).set(round(v * 1e3, 3))
            if objective > 0.0 and p99 * 1e3 > objective:
                obs.RPC_SLO_BREACHES.labels(kind=kind,
                                            method=method).inc()
                obs_flight.FLIGHT.record_event({
                    "ts": round(time.time(), 3), "level": "warning",
                    "event": "slo.breach", "kind": kind,
                    "method": method,
                    "p99_ms": round(p99 * 1e3, 3),
                    "objective_ms": objective,
                    "samples": seen})


def reset() -> None:
    """Drop all estimator state (bench windows / test isolation)."""
    global _last_flush
    with _flush_lock:
        with _rpc_lock:
            _rpc.clear()
            _published.clear()
        _last_flush = 0.0


def rpc_snapshot() -> dict:
    """{kind: {method: estimator snapshot}} for bench cross-checks."""
    with _rpc_lock:
        items = list(_rpc.items())
    out: Dict[str, dict] = {}
    for (kind, method), est in items:
        out.setdefault(kind, {})[method] = est.snapshot()
    return out


# ------------------------------------------------------- fleet health cache

# Written by the fleet loop's batched _flush, read by /healthz. A plain
# reference swap (atomic under the GIL) — readers never see a partial
# document and never contend with the serving loop.
_fleet_health: dict = {}


def set_fleet_health(doc: dict) -> None:
    global _fleet_health
    _fleet_health = doc


def fleet_health() -> dict:
    return _fleet_health
