"""The fleet audit log: who joined, who died, who adopted what, when.

Two halves, one schema (`gol-fleet-audit/1`):

  * `AuditLog` — the registry-tier durable stream. Append-only JSONL
    records with a monotonic per-log `seq`, size-capped rotation
    (GOL_AUDIT_MAX_BYTES per file, GOL_AUDIT_KEEP rotated siblings),
    plus an in-memory ring tail that serves `GetAudit` without
    touching disk. Durability is opt-in: construct with a path (the
    router wires GOL_AUDIT_DIR) or run memory-only.

  * module-level `note(kind, ...)` — the member-side event queue.
    Data-plane hook points (quarantine in fleet/engine.py, migration
    phases in migrate.py) drop a bounded record here; the heartbeat
    exporter (obs/export.py) drains it into the next snapshot with
    commit-on-ack semantics, so member events land in the ROUTER's
    durable log over connections we already pay for.

Record shape:

    {"schema": "gol-fleet-audit/1", "seq": 17, "ts": 1754500000.1,
     "kind": "member_death", "member": "127.0.0.1:4242", ...}

Kinds are a closed set (clamped, metered via
gol_audit_records_total{kind}); free-form detail rides as extra keys.
Stdlib-only, no jax import.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["SCHEMA", "AUDIT_KINDS", "AuditLog", "audit_kind_label",
           "note", "peek_pending", "commit_pending", "recent"]

SCHEMA = "gol-fleet-audit/1"

# The kind set is declared in the catalog (single source for the
# gol_audit_records_total label discipline).
from gol_tpu.obs.catalog import AUDIT_KINDS  # noqa: E402

ENV_MAX_BYTES = "GOL_AUDIT_MAX_BYTES"   # rotate when file exceeds this
ENV_KEEP = "GOL_AUDIT_KEEP"             # rotated siblings kept
DEFAULT_MAX_BYTES = 4 << 20
DEFAULT_KEEP = 2
RING = 512                              # in-memory tail per log
FILE_NAME = "audit.jsonl"


def audit_kind_label(kind: str) -> str:
    """Clamp arbitrary record kinds to the declared set (metric label
    discipline; the record itself keeps the raw kind)."""
    return kind if kind in AUDIT_KINDS else "other"


class AuditLog:
    """Append-only JSONL with rotation and an in-memory ring tail."""

    def __init__(self, path: Optional[str] = None,
                 max_bytes: Optional[int] = None,
                 keep: Optional[int] = None) -> None:
        self.path = path
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(ENV_MAX_BYTES, "")
                                or DEFAULT_MAX_BYTES)
            except ValueError:
                max_bytes = DEFAULT_MAX_BYTES
        if keep is None:
            try:
                keep = int(os.environ.get(ENV_KEEP, "") or DEFAULT_KEEP)
            except ValueError:
                keep = DEFAULT_KEEP
        self.max_bytes = max(int(max_bytes), 4096)
        self.keep = max(int(keep), 0)
        self._lock = threading.Lock()
        self._seq = 0
        self._ring: deque = deque(maxlen=RING)
        self._fh: Optional[io.TextIOBase] = None
        self._size = 0
        if path:
            os.makedirs(path, exist_ok=True)
            self._open()

    # ------------------------------------------------------------- disk

    def _file(self, n: int = 0) -> str:
        base = os.path.join(self.path, FILE_NAME)
        return base if n == 0 else f"{base}.{n}"

    def _open(self) -> None:
        self._fh = open(self._file(), "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _rotate_locked(self) -> None:
        self._fh.close()
        for n in range(self.keep, 0, -1):
            src = self._file(n - 1) if n > 1 else self._file()
            dst = self._file(n)
            if os.path.exists(src):
                os.replace(src, dst)
        if self.keep == 0:
            os.remove(self._file())
        self._open()

    # ----------------------------------------------------------- append

    def append(self, kind: str, **fields) -> dict:
        """One durable record; returns it (with schema/seq/ts)."""
        with self._lock:
            self._seq += 1
            rec = {"schema": SCHEMA, "seq": self._seq,
                   "ts": fields.pop("ts", None) or time.time(),
                   "kind": str(kind)}
            rec.update(fields)
            self._ring.append(rec)
            if self._fh is not None:
                try:
                    line = json.dumps(rec, sort_keys=True,
                                      default=str) + "\n"
                    self._fh.write(line)
                    self._fh.flush()
                    self._size += len(line)
                    if self._size > self.max_bytes:
                        self._rotate_locked()
                except OSError:
                    pass  # a full disk must not take the router down
        try:
            from gol_tpu.obs import catalog as obs
            obs.AUDIT_RECORDS.labels(
                kind=audit_kind_label(str(kind))).inc()
        except Exception:
            pass
        return rec

    # ------------------------------------------------------------ reads

    def tail(self, since_seq: int = 0, limit: int = 100) -> list:
        """Ring records with seq > since_seq, oldest first, capped."""
        limit = max(int(limit), 1)
        with self._lock:
            out = [r for r in self._ring if r["seq"] > since_seq]
        return out[:limit]

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# --------------------------------------------------- member-side events

# Bounded queues: `_pending` holds events awaiting a heartbeat ride
# (drained with commit-on-ack by obs/export.py — a lost beat re-ships
# them); `_recent` keeps a local tail with its own seq so a member can
# answer GetAudit about itself even though the durable log lives at the
# router. Overflow drops the OLDEST pending event — recency wins.
_PENDING_MAX = 256
_events_lock = threading.Lock()
_pending: deque = deque(maxlen=_PENDING_MAX)
_recent: deque = deque(maxlen=RING)
_local_seq = 0


def note(kind: str, **fields) -> dict:
    """Queue one member-side audit event for the next heartbeat
    snapshot. Cheap (a deque append) — safe from data-plane paths."""
    global _local_seq
    with _events_lock:
        _local_seq += 1
        rec = {"schema": SCHEMA, "seq": _local_seq, "ts": time.time(),
               "kind": str(kind)}
        rec.update(fields)
        _pending.append(rec)
        _recent.append(rec)
    try:
        from gol_tpu.obs import catalog as obs
        obs.AUDIT_RECORDS.labels(kind=audit_kind_label(str(kind))).inc()
    except Exception:
        pass
    return rec


def peek_pending(limit: int = 32) -> list:
    """Up to `limit` queued events, oldest first — NOT removed until
    `commit_pending` confirms the ack."""
    with _events_lock:
        return list(_pending)[:max(int(limit), 0)]


def commit_pending(n: int) -> None:
    """Drop the n oldest pending events (they reached the router)."""
    with _events_lock:
        for _ in range(min(max(int(n), 0), len(_pending))):
            _pending.popleft()


def recent(since_seq: int = 0, limit: int = 100) -> list:
    """The member-local event tail (serves GetAudit on members)."""
    limit = max(int(limit), 1)
    with _events_lock:
        out = [r for r in _recent if r["seq"] > since_seq]
    return out[:limit]
