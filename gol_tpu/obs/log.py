"""Structured logging: `GOL_LOG=json|text` (default text).

Replaces the ad-hoc `traceback.print_exc()` / bare `print` diagnostics
in gol.py / main.py with one-line events that a log pipeline can parse
(`json`) or a human can read on a terminal (`text`). Events go to
stderr so they never interleave with the SDL/stdout data paths or the
server banner that tests/server_harness.py greps from stdout.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback
from typing import Optional

from gol_tpu.obs import flight as _flight

LOG_ENV = "GOL_LOG"


def _mode() -> str:
    # Read per call, not at import: tests and long-lived processes may
    # flip GOL_LOG after gol_tpu is imported.
    mode = os.environ.get(LOG_ENV, "text").strip().lower()
    return mode if mode in ("json", "text") else "text"


def log(event: str, level: str = "info", stream=None, **fields) -> None:
    """Emit one structured event. `fields` must be JSON-serializable."""
    stream = stream if stream is not None else sys.stderr
    rec = {"ts": round(time.time(), 3), "level": level, "event": event}
    rec.update(fields)
    # Every event also lands in the flight-recorder ring, whatever the
    # stderr format — a crash dump should carry the recent log tail.
    try:
        _flight.FLIGHT.record_event(rec)
    except Exception:
        pass
    if _mode() == "json":
        line = json.dumps(rec, sort_keys=True, default=str)
    else:
        extras = " ".join(f"{k}={v}" for k, v in fields.items())
        line = f"[gol:{level}] {event}" + (f" {extras}" if extras else "")
    try:
        print(line, file=stream, flush=True)
    except (OSError, ValueError):
        pass  # a closed/broken stderr must never sink the run


def exception(event: str, exc: BaseException,
              stream=None, **fields) -> None:
    """`log` for a caught exception; carries type, message, and the
    formatted traceback (as a field in json mode, as the familiar
    multi-line block in text mode)."""
    tb = "".join(traceback.format_exception(type(exc), exc,
                                            exc.__traceback__))
    if _mode() == "json":
        log(event, level="error", stream=stream,
            error=f"{type(exc).__name__}: {exc}", traceback=tb, **fields)
    else:
        log(event, level="error", stream=stream,
            error=f"{type(exc).__name__}: {exc}", **fields)
        try:
            print(tb, file=stream if stream is not None else sys.stderr,
                  end="", flush=True)
        except (OSError, ValueError):
            pass
