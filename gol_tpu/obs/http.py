"""Optional `/metrics` endpoint: stdlib http.server, daemon thread.

`start_metrics_server(port)` binds (port 0 = OS-assigned ephemeral),
serves Prometheus text from the shared registry on GET /metrics, and
returns the running server object — `.port` tells callers (and the
obs-smoke harness) where an ephemeral bind landed. The thread is a
daemon: it dies with the process and never blocks shutdown.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from gol_tpu.obs.metrics import REGISTRY

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Most recent server started in this process, so in-process harnesses
# (tools/obs_smoke.py) can find the ephemeral port after main() returns.
_LAST: Optional["MetricsServer"] = None


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.split("?", 1)[0] in ("/metrics", "/"):
            body = REGISTRY.render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", PROM_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class MetricsServer:
    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"gol-metrics-:{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_metrics_server(port: int,
                         host: str = "127.0.0.1") -> MetricsServer:
    global _LAST
    srv = MetricsServer(port, host=host)
    _LAST = srv
    return srv


def last_server() -> Optional[MetricsServer]:
    return _LAST
