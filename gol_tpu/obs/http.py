"""Optional HTTP endpoints: stdlib http.server, daemon thread.

`start_metrics_server(port)` binds (port 0 = OS-assigned ephemeral) and
serves three read-only paths from in-process state:

  * `/metrics` (and `/`) — Prometheus text from the shared registry;
  * `/metrics.json` — the registry's dict snapshot, for tooling that
    would rather not parse exposition text;
  * `/healthz` — 200 + `{"run_id", "turn", "uptime_s", "runs", "slo",
    "device_kind", "live_bytes", "compile_count"}`, the liveness
    probe ("runs" summarizes fleet residency/admissions; "slo" is the
    fleet loop's cached health doc — staleness/queue-wait percentiles
    and the top-K worst-runs table, obs/slo.py): run_id
    identifies the process, turn proves the engine loop is advancing
    between polls, live_bytes/compile_count expose leak and
    recompile churn without a Prometheus scrape (both read the
    devstats cache — never a device sync);
  * `/profile` — GET returns the profile controller's status; POST
    (optionally `?turns=N`) arms an on-demand jax.profiler capture of
    the next N engine turns, 409 when no `--profile-dir` was given or
    a capture is already armed.

Returns the running server object — `.port` tells callers (and the
obs-smoke harness) where an ephemeral bind landed. The thread is a
daemon: it dies with the process and never blocks shutdown.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from gol_tpu.obs import catalog, devstats
from gol_tpu.obs import flight as obs_flight
from gol_tpu.obs import slo as obs_slo
from gol_tpu.obs.metrics import REGISTRY
from gol_tpu.obs.prof import PROFILER, ProfileUnavailable

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
JSON_CONTENT_TYPE = "application/json; charset=utf-8"

# Most recent server started in this process, so in-process harnesses
# (tools/obs_smoke.py) can find the ephemeral port after main() returns.
_LAST: Optional["MetricsServer"] = None


def healthz_doc() -> dict:
    """The /healthz body (also used by tests without a socket)."""
    doc = {"run_id": obs_flight.RUN_ID,
           "turn": catalog.ENGINE_TURN.value,
           "uptime_s": round(obs_flight.uptime_s(), 3),
           # Fleet summary (PR 7): resident/admitted/rejected run
           # counts from the registry — zeros on single-run engines.
           "runs": catalog.runs_doc()}
    # Fleet SLO health (PR 8): the document the fleet loop's batched
    # flush last published — staleness/queue-wait percentiles plus the
    # top-K worst-runs table. A cached reference read, so /healthz
    # still never takes an engine lock or syncs a device; empty dict on
    # single-run engines.
    doc["slo"] = obs_slo.fleet_health()
    doc.update(devstats.healthz_fields())
    # Federation member/peer table (PR 12): present only in a process
    # running a router registry. Lazy import + cached-reference read —
    # same no-lock discipline as the fleet health document.
    try:
        from gol_tpu.federation import registry as fed_registry
        fed = fed_registry.active_doc()
    except Exception:  # noqa: BLE001 — /healthz must never 500
        fed = None
    if fed is not None:
        doc["federation"] = fed
    # Fleet telemetry plane (PR 16): the registry tier's aggregated
    # rollups + alert states + tsdb summary — another reference-swapped
    # cached document, absent on processes that run no router.
    try:
        from gol_tpu.obs import export as obs_export
        telemetry = obs_export.active_telemetry_doc()
    except Exception:  # noqa: BLE001 — /healthz must never 500
        telemetry = None
    if telemetry:
        doc["telemetry"] = telemetry
    # Per-run usage & capacity attribution (PR 19): the meter's
    # reference-swapped doc — top-K talkers, attribution conservation,
    # capacity headroom rows. Absent while nothing has been metered.
    try:
        from gol_tpu.obs import usage as obs_usage
        usage = obs_usage.usage_doc()
    except Exception:  # noqa: BLE001 — /healthz must never 500
        usage = None
    if usage and (usage.get("runs_tracked") or usage.get("retired_runs")
                  or usage.get("capacity")):
        doc["usage"] = usage
    return doc


class _Handler(BaseHTTPRequestHandler):
    def _reply(self, body: bytes, ctype: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            self._reply(REGISTRY.render_prometheus().encode("utf-8"),
                        PROM_CONTENT_TYPE)
        elif path == "/metrics.json":
            self._reply(
                json.dumps(REGISTRY.snapshot(), sort_keys=True,
                           default=str).encode("utf-8"),
                JSON_CONTENT_TYPE)
        elif path == "/healthz":
            self._reply(
                json.dumps(healthz_doc(), sort_keys=True).encode("utf-8"),
                JSON_CONTENT_TYPE)
        elif path == "/profile":
            self._reply(
                json.dumps(PROFILER.status(), sort_keys=True)
                .encode("utf-8"), JSON_CONTENT_TYPE)
        else:
            self.send_error(404)

    def do_POST(self):  # noqa: N802 (http.server API)
        path, _, query = self.path.partition("?")
        if path != "/profile":
            self.send_error(404)
            return
        turns = 0
        for kv in query.split("&"):
            k, _, v = kv.partition("=")
            if k == "turns":
                try:
                    turns = int(v)
                except ValueError:
                    self.send_error(400, "bad turns value")
                    return
        try:
            body = PROFILER.request(turns=turns, source="http")
        except ProfileUnavailable as e:
            payload = json.dumps({"error": str(e)}).encode("utf-8")
            self.send_response(409)
            self.send_header("Content-Type", JSON_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        self._reply(json.dumps(body, sort_keys=True).encode("utf-8"),
                    JSON_CONTENT_TYPE)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class MetricsServer:
    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"gol-metrics-:{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_metrics_server(port: int,
                         host: str = "127.0.0.1") -> MetricsServer:
    global _LAST
    srv = MetricsServer(port, host=host)
    _LAST = srv
    return srv


def last_server() -> Optional[MetricsServer]:
    return _LAST
