"""Convolution/FFT stencil tier — large-radius neighborhood sums.

Every other kernel in the repo is radius-1 bitplane arithmetic
(`ops/bitpack.py`, `ops/fused.py`): per-cell work is O(1) there, but a
general radius-r neighborhood sum costs O(r²) shift-adds and the
bitplane tiers fall off a cliff the moment r grows past a few cells.
This module adds the two tiers that win beyond that point:

* **conv** — direct-space circular convolution, one fused XLA
  program. Separable kernels (the LtL Moore box) run as two 1-D
  shift-add accumulations (O(r) streaming adds per cell — each add is
  a full-board vectorized pass at memory bandwidth); general kernels
  go through `lax.conv_general_dilated` over a wrap-padded board.
  The conv tier wins the middle radii where the bitplane's
  bit-parallel advantage is gone but its O(r) per-cell work is still
  below the FFT's fixed O(log n).
* **fft** — circular convolution by the convolution theorem:
  `irfft2(rfft2(board) * Kspec)` with the kernel spectrum `Kspec`
  precomputed ONCE per (shape, kernel) and cached (the PR-4
  step-signature counter witnesses the reuse — stepping the same
  config twice must not mint a new signature). Per-cell cost is
  O(log n) independent of r: the large-radius regime ("Fast Stencil
  Computations using Fast Fourier Transforms", PAPERS.md).

Integer exactness through the FFT (the bit-identical parity gates):
the board's mean is subtracted before the forward transform and the
kernel-sum compensation is added back after the inverse —
conv(b, k) == conv(b - mean, k) + mean·sum(k) — which removes the DC
term that dominates float32 round-off on big boards (at 4096² the DC
bin holds ~4e6 while the AC bins hold ~2e3; without the split the
round-off at r=32 approaches 0.5 and rounding would flip counts).
With the split, `rint` recovers the exact integer neighborhood sums;
`bench.py --conv` gates that bit-identically against an independent
numpy summed-area-table oracle at every swept radius.

Tier selection (`GOL_KERNEL_TIER=auto|bitplane|fused|conv|fft`) is
policy, not mechanism: `select_tier` picks per (board, radius, dtype)
from the measured crossover table `CROSSOVER_FFT_RADIUS` (refreshed by
`bench.py --conv`, which gates that the policy picks the measured
winner at every swept radius). Callers that only implement a subset of
tiers (the engine's conv families have no bitplane form) pass
`allowed=` to clamp the answer.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.obs import catalog as obs
from gol_tpu.obs import devstats as obs_devstats

TIER_ENV = "GOL_KERNEL_TIER"
TIERS = ("bitplane", "fused", "conv", "fft")

# Measured crossover table (bench.py --conv, CPU host, box kernels,
# measured in the real dispatch form — the jitted scan the engine and
# bench both run): radius at or above which the FFT tier beats the
# direct-space conv tier, keyed by board area ceiling. The conv
# tier's cost grows as O(r) per cell (separable shift-add) while the
# FFT's is flat in r, so the table is a single threshold per area
# band. Measured per-turn anchors: 1024² r=8 conv 13.3 ms vs fft
# 15.5 ms (conv wins), r=16 conv 21.9 vs fft 12.8 (fft wins); 4096²
# r=8 conv 242 ms vs fft 352, r=16 conv 440 vs fft 353 — crossover
# ~12 across the mid/large bands. Tiny boards are dispatch-dominated
# (both tiers < 1 ms at 256²) and cross earlier.
# GOL_CONV_CROSSOVER=<radius> overrides (operators on different hosts
# re-measure via bench.py --conv and pin what they saw).
CROSSOVER_ENV = "GOL_CONV_CROSSOVER"
CROSSOVER_FFT_RADIUS = (
    # (max board area, fft wins at radius >=)
    (1 << 17, 7),    # <= ~256²: dispatch-latency floor, early cross
    (1 << 63, 12),   # 1024²..4096² anchors and beyond
)


def _crossover_radius(area: int) -> int:
    raw = os.environ.get(CROSSOVER_ENV, "").strip()
    if raw:
        try:
            return max(2, int(raw))
        except ValueError:
            pass  # fall through to the measured table
    for max_area, r in CROSSOVER_FFT_RADIUS:
        if area <= max_area:
            return r
    return CROSSOVER_FFT_RADIUS[-1][1]


def select_tier(h: int, w: int, radius: int, dtype: str = "uint8",
                allowed: Sequence[str] = TIERS) -> str:
    """The kernel tier for one (board, radius, dtype) — the ONE policy
    point every conv-family dispatch resolves through.

    `dtype` is the CELL dtype ("uint8" for binary boards, "float32"
    for continuous state); float boards have no bitplane form, so the
    binary-only tiers are never selected for them. `allowed` clamps to
    the tiers the caller actually implements (the engine's conv
    families run conv/fft only; bench sweeps all four)."""
    allowed = tuple(t for t in TIERS if t in allowed)
    if not allowed:
        raise ValueError("no kernel tiers allowed")
    forced = os.environ.get(TIER_ENV, "auto").strip().lower() or "auto"
    if forced != "auto":
        if forced not in TIERS:
            raise ValueError(
                f"bad {TIER_ENV}={forced!r}: want auto|" + "|".join(TIERS))
        if forced in allowed:
            return forced
        # A forced tier the caller can't run (bitplane on a float
        # board) falls through to auto rather than crashing the run —
        # loudly, mirroring the engine's mesh-fallback stance.
        import warnings

        warnings.warn(
            f"{TIER_ENV}={forced} unavailable here (allowed: "
            f"{allowed}); auto-selecting instead")
    binary = str(dtype) in ("uint8", "uint32", "bool")
    if binary and radius <= 1:
        from gol_tpu.ops.fused import configured_fuse_k

        if "fused" in allowed and configured_fuse_k() > 1:
            return "fused"
        if "bitplane" in allowed:
            return "bitplane"
    if "fft" not in allowed:
        return "conv"
    if "conv" not in allowed:
        return "fft"
    if not binary:
        # Float boards mean dense smooth kernels (Lenia): there is no
        # separable shift-add form, so the direct tier pays O(r²) taps
        # through conv_general_dilated and the FFT wins at every radius
        # Lenia admits (measured at 1024²: r=2 conv 163 ms vs fft
        # 18 ms, widening with r). The box-kernel crossover table below
        # does not apply; conv stays reachable via GOL_KERNEL_TIER=conv.
        return "fft"
    return "fft" if radius >= _crossover_radius(h * w) else "conv"


def note_dispatch(tier: str) -> None:
    """Meter one conv-family dispatch: the `gol_conv_dispatches_total`
    counter plus the one-hot `gol_kernel_tier` gauge (the active tier
    reads 1, every other reads 0 — a flat family a dashboard can
    legend without decoding an enum)."""
    obs.CONV_DISPATCHES.labels(tier=tier).inc()
    for t in TIERS:
        obs.KERNEL_TIER.labels(tier=t).set(1.0 if t == tier else 0.0)


# ------------------------------------------------------------- kernels


def neighborhood_kernel(radius: int, kind: str = "M",
                        middle: bool = False) -> np.ndarray:
    """(2r+1, 2r+1) float32 {0,1} mask of the neighborhood:
    'M' Moore box, 'N' von Neumann diamond (|dy|+|dx| <= r),
    'C' circular (dy² + dx² <= r²). `middle` includes the center cell
    (the LtL M1 convention: a cell counts itself for survival)."""
    r = int(radius)
    if r < 1:
        raise ValueError(f"radius must be >= 1, got {radius}")
    dy, dx = np.mgrid[-r:r + 1, -r:r + 1]
    if kind == "M":
        mask = np.ones((2 * r + 1, 2 * r + 1), dtype=bool)
    elif kind == "N":
        mask = (np.abs(dy) + np.abs(dx)) <= r
    elif kind == "C":
        mask = (dy * dy + dx * dx) <= r * r
    else:
        raise ValueError(f"unknown neighborhood kind {kind!r}")
    mask[r, r] = bool(middle)
    return mask.astype(np.float32)


def _embed_kernel(kernel: np.ndarray, h: int, w: int) -> np.ndarray:
    """Center a (2r+1, 2r+1) kernel into an (h, w) circular-convolution
    field: tap (dy, dx) lands at index (dy mod h, dx mod w), so
    out[y, x] = sum_k kernel[k] * board[y - dy_k, x - dx_k] on the
    torus matches the direct wrap-padded convolution exactly."""
    kh, kw = kernel.shape
    r = kh // 2
    if kh > h or kw > w:
        raise ValueError(
            f"kernel {kernel.shape} exceeds board {(h, w)} — a "
            f"neighborhood wider than the torus would self-overlap")
    field = np.zeros((h, w), dtype=np.float32)
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            v = kernel[dy + r, dx + r]
            if v:
                field[dy % h, dx % w] += v
    return field


# ------------------------------------------------------- conv tier


def _box_center_delta(kern: np.ndarray) -> Optional[float]:
    """If `kern` is an all-ones box apart from its center tap, return
    (center − 1) — the separable decomposition box + delta·δ₀. None
    when the kernel is not a box (disc/diamond/smooth kernels)."""
    k = np.asarray(kern, dtype=np.float32).copy()
    r = k.shape[0] // 2
    center = float(k[r, r])
    k[r, r] = 1.0
    if k.shape[0] == k.shape[1] and np.all(k == 1.0):
        return center - 1.0
    return None


@functools.partial(jax.jit, static_argnames=("kernel_key",))
def _conv_sum(board, kernel_key) -> jax.Array:
    """(H, W) float32 board -> float32 circular neighborhood sums as
    one fused direct-space program. `kernel_key` is the hashable
    kernel description (see `kernel_from_key`); the taps become
    compile-time constants of the traced program.

    Box kernels (the common LtL Moore case) take the separable
    shift-add path: (2r+1)-wide running sums along each axis via
    torus rolls — 4r+2 vectorized full-board adds total, each at
    memory bandwidth, bit-exact in any order for integer boards
    (float32 holds every partial below 2^24). General kernels go
    through `lax.conv_general_dilated` on a wrap-padded board (the
    circular boundary), which is the dense O(r²)-taps form."""
    kern = kernel_from_key(kernel_key)
    r = kern.shape[0] // 2
    delta = _box_center_delta(kern)
    if delta is not None:
        acc = board
        for d in range(1, r + 1):
            acc = acc + jnp.roll(board, d, 0) + jnp.roll(board, -d, 0)
        out = acc
        for d in range(1, r + 1):
            out = out + jnp.roll(acc, d, 1) + jnp.roll(acc, -d, 1)
        if delta:
            out = out + jnp.float32(delta) * board
        return out
    padded = jnp.pad(board, r, mode="wrap")
    # NCHW activations / OIHW taps: a single-feature 2-D convolution.
    out = lax.conv_general_dilated(
        padded[None, None, :, :].astype(jnp.float32),
        jnp.asarray(kern)[None, None, :, :],
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return out[0, 0]


def conv_neighbor_sum(board, kernel_key) -> jax.Array:
    """Neighborhood sums through the direct-space conv tier. Exact for
    integer-valued boards (float32 holds every sum below 2^24)."""
    return _conv_sum(jnp.asarray(board, dtype=jnp.float32), kernel_key)


# -------------------------------------------------------- fft tier


@functools.lru_cache(maxsize=64)
def _fft_spectrum_np(h: int, w: int, kernel_key) -> np.ndarray:
    """The cached kernel spectrum: rfft2 of the kernel embedded in the
    (h, w) circular field, computed ONCE per (shape, kernel) in float64
    and held as complex64 (the board transform is float32; a float64
    kernel spectrum would just upcast the product). This cache is what
    the "cached-spectrum reuse" tests witness — a second step of the
    same config re-uses both this host array and the jitted program
    below, so the step-signature counter must not move."""
    kern = kernel_from_key(kernel_key)
    field = _embed_kernel(kern, h, w)
    return np.fft.rfft2(field.astype(np.float64)).astype(np.complex64)


@functools.partial(jax.jit, static_argnames=("kernel_key",))
def _fft_sum(board, kernel_key) -> jax.Array:
    """(H, W) float32 board -> float32 circular neighborhood sums via
    rfft2/irfft2 with the cached kernel spectrum baked in as a
    compile-time constant. Mean-split for integer exactness (module
    docstring): the DC term never rides through the transform."""
    h, w = board.shape
    spec = jnp.asarray(_fft_spectrum_np(h, w, kernel_key))
    ksum = float(kernel_from_key(kernel_key).sum())
    mean = jnp.mean(board)
    ac = jnp.fft.irfft2(jnp.fft.rfft2(board - mean) * spec, s=(h, w))
    return ac + mean * ksum


def fft_neighbor_sum(board, kernel_key) -> jax.Array:
    """Neighborhood sums through the FFT tier (float32 result; callers
    needing exact integer counts `rint` it — see `_counts`)."""
    return _fft_sum(jnp.asarray(board, dtype=jnp.float32), kernel_key)


# ------------------------------------------------- kernel-key registry
#
# jit static args and lru_cache keys must be hashable, so kernels are
# passed BY DESCRIPTION, not by array: a kernel key is a tuple whose
# head names the builder. The registry is the one decode point.


def kernel_from_key(kernel_key) -> np.ndarray:
    """Decode a hashable kernel description into its float32 taps.

    Keys:
      ("ltl", radius, kind, middle)            — {0,1} neighborhood mask
      ("lenia", radius, "%.6g" % peak_count…)  — see models/lenia.py
    """
    head = kernel_key[0]
    if head == "ltl":
        _, radius, kind, middle = kernel_key
        return neighborhood_kernel(radius, kind, middle)
    if head == "lenia":
        from gol_tpu.models.lenia import lenia_kernel_from_key

        return lenia_kernel_from_key(kernel_key)
    raise ValueError(f"unknown kernel key {kernel_key!r}")


def neighbor_sum(board, kernel_key, tier: str) -> jax.Array:
    """Dispatch one neighborhood sum through the named tier."""
    if tier == "conv":
        return conv_neighbor_sum(board, kernel_key)
    if tier == "fft":
        return fft_neighbor_sum(board, kernel_key)
    raise ValueError(
        f"tier {tier!r} has no general-radius neighbor_sum (the "
        f"bitplane/fused tiers are radius-1 life-like only)")


# --------------------------------------------- engine-facing run fns
#
# The engine's `_tokened_run` wraps a callable `run(cells, k, mesh,
# rule)` in the chunk program; these builders return such callables
# with the tier baked into the FUNCTION IDENTITY (module-level
# lru_cache), so the engine's jit caches key correctly on the tier —
# the same stable-identity pattern as `ops/fused.configured run fns`.


def _ltl_counts(cells_f32, rule, tier: str) -> jax.Array:
    """Exact int32 neighborhood counts for a {0,1} board."""
    s = neighbor_sum(cells_f32, rule.kernel_key, tier)
    # conv sums are exact already; fft sums carry <0.5 round-off.
    return jnp.rint(s).astype(jnp.int32)


def _ltl_step(cells, rule, tier: str) -> jax.Array:
    """One Larger-than-Life turn on {0,1} uint8 cells: neighborhood
    count (center included iff the rule says so) -> interval tests
    against the rule's survive/born count ranges.

    Interval compares, NOT the uint8 LUT gather the numpy oracle uses:
    XLA's CPU gather lowers to a generic scalar loop that ran ~40x
    slower than the whole separable conv sum it consumed (and a rule
    has a handful of ranges at most, so the compare chain is a few
    fused vector passes). The oracle keeps the gather form — disjoint
    mechanisms is exactly what a parity gate wants."""
    counts = _ltl_counts(cells.astype(jnp.float32), rule, tier)

    def in_ranges(spans):
        ok = jnp.zeros(counts.shape, dtype=jnp.bool_)
        for lo, hi in spans:
            ok = ok | ((counts >= lo) & (counts <= min(hi, 1 << 30)))
        return ok

    alive = jnp.where(cells == 1, in_ranges(rule.survive_ranges),
                      in_ranges(rule.born_ranges))
    return alive.astype(jnp.uint8)


@functools.lru_cache(maxsize=8)
def ltl_run_fn(tier: str):
    """Engine run fn for the Larger-than-Life family on the given tier
    (uint8 {0,1} cells, single-shard: `mesh` is accepted for signature
    parity and must be 1-way — conv families have no halo machinery)."""

    def run(cells, k, mesh, rule):
        if k == 0:
            return cells

        def body(c, _):
            return _ltl_step(c, rule, tier), None

        out, _ = lax.scan(body, cells, None, length=k)
        return out

    # jit here, not just in the engine's chunk program: standalone
    # callers (bench/tests) would otherwise re-trace the scan on every
    # call — ~100 ms of flat overhead that swamps small-board timings.
    # k/mesh/rule are static (ints, None, hashable frozen dataclass).
    return jax.jit(run, static_argnums=(1, 2, 3))


@functools.lru_cache(maxsize=8)
def lenia_run_fn(tier: str):
    """Engine run fn for the Lenia family: float32 state in [0, 1],
    smooth-kernel neighborhood sum -> growth -> clipped Euler step
    (models/lenia.py owns the math; this wires it to the tier)."""
    from gol_tpu.models.lenia import lenia_step

    def run(cells, k, mesh, rule):
        if k == 0:
            return cells

        def body(c, _):
            return lenia_step(c, rule, tier), None

        out, _ = lax.scan(body, cells, None, length=k)
        return out

    return jax.jit(run, static_argnums=(1, 2, 3))


def run_turns(cells, num_turns: int, rule, tier: Optional[str] = None):
    """Standalone conv-tier turn loop (bench/tests): advance
    `num_turns` turns of an LtL or Lenia rule on the given tier
    (auto-selected from the board when None)."""
    cells = jnp.asarray(cells)
    h, w = cells.shape[-2], cells.shape[-1]
    if tier is None:
        tier = select_tier(h, w, rule.radius, str(cells.dtype),
                           allowed=("conv", "fft"))
    from gol_tpu.models.lenia import LeniaRule

    fn = (lenia_run_fn(tier) if isinstance(rule, LeniaRule)
          else ltl_run_fn(tier))
    note_dispatch(tier)
    obs_devstats.note_signature(
        ("conv", tier, (h, w), str(cells.dtype), rule.rulestring))
    return fn(cells, num_turns, None, rule)


# ---------------------------------------------------- numpy oracle


def box_counts_np(board: np.ndarray, radius: int,
                  middle: bool = False) -> np.ndarray:
    """Independent O(H·W) oracle for Moore-box neighborhood counts on
    the torus: wrap-pad + summed-area table, no convolution and no FFT
    anywhere near it — the bench parity gate's reference even at
    4096²/r=32 (a direct tap loop there would be 7e10 adds)."""
    r = int(radius)
    b = np.pad(np.asarray(board, dtype=np.int64), r, mode="wrap")
    s = np.zeros((b.shape[0] + 1, b.shape[1] + 1), dtype=np.int64)
    s[1:, 1:] = b.cumsum(axis=0).cumsum(axis=1)
    k = 2 * r + 1
    h, w = board.shape
    counts = (s[k:k + h, k:k + w] - s[0:h, k:k + w]
              - s[k:k + h, 0:w] + s[0:h, 0:w])
    if not middle:
        counts = counts - np.asarray(board, dtype=np.int64)
    return counts


def counts_np(board: np.ndarray, kernel: np.ndarray) -> np.ndarray:
    """General-kernel oracle: direct tap accumulation over np.roll
    shifts. O(H·W·r²) — small boards/radii only (tests)."""
    kh, kw = kernel.shape
    r = kh // 2
    out = np.zeros(board.shape, dtype=np.float64)
    b = np.asarray(board, dtype=np.float64)
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            v = float(kernel[dy + r, dx + r])
            if v:
                out += v * np.roll(np.roll(b, dy, axis=0), dx, axis=1)
    return out
