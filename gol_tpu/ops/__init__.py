from gol_tpu.ops.stencil import (
    alive_count,
    from_pixels,
    neighbour_counts,
    run_turns,
    step,
    to_pixels,
)

__all__ = [
    "alive_count",
    "from_pixels",
    "neighbour_counts",
    "run_turns",
    "step",
    "to_pixels",
]
