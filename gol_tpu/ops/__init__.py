from gol_tpu.ops.stencil import (
    alive_count,
    from_pixels,
    neighbour_counts,
    run_turns,
    step,
    to_pixels,
)

# The conv/FFT kernel tier (`gol_tpu.ops.conv`) is intentionally NOT
# imported here: it pulls in the obs catalogue and jit machinery, and
# every consumer (engine family branches, bench, fleet) imports it
# lazily at the dispatch site.

__all__ = [
    "alive_count",
    "from_pixels",
    "neighbour_counts",
    "run_turns",
    "step",
    "to_pixels",
]
