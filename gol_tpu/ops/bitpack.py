"""Bit-parallel GoL stencil: 32 cells per uint32 lane.

The reference updates one cell at a time with 8 branchy compares
(`SubServer/distributor.go:119-208`). The uint8 kernel in `ops/stencil.py`
already vectorizes that; this module goes one level further down the
hardware: the board is packed 32 cells to a uint32 word, and the
8-neighbour sum is computed for all 32 cells of a word at once with a
carry-save adder network of ~40 bitwise ops — ~1.3 ops *per cell* instead
of ~10, and 1/8th the HBM traffic. On a VPU whose lanes are 32-bit this is
the densest representation a life-like CA admits.

Layout: a (H, W) board packs to (H, W/32) uint32, LSB-first — column
c = 32*w + j lives in bit j of word w of its row. Horizontal torus wrap is
a word-roll along the row; vertical wrap is a row-roll. The rule is
evaluated bit-sliced on the 4-bit neighbour count (n0..n3), so Conway costs
5 extra ops and any life-like rule a handful more — results are bit-exact
with the unpacked kernel for every rule.

The packed path requires W % 32 == 0 (the engine falls back to the uint8
kernel otherwise, e.g. the 16x16 test board).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule

WORD_BITS = 32
_U = jnp.uint32


@jax.jit
def pack(cells: jax.Array) -> jax.Array:
    """{0,1} uint8 (..., H, W) → uint32 (..., H, W/32), LSB-first.

    Jitted so XLA fuses the 32-bit-per-cell broadcast/multiply into the
    reduction — eager, the (…, W/32, 32) uint32 intermediates would
    transiently cost ~16x the board (64 GiB for the 65536² flagship)."""
    w = cells.shape[-1]
    if w % WORD_BITS != 0:
        raise ValueError(f"width {w} not a multiple of {WORD_BITS}")
    lanes = cells.reshape(*cells.shape[:-1], w // WORD_BITS, WORD_BITS)
    weights = (jnp.uint32(1) << jnp.arange(WORD_BITS, dtype=_U))
    return jnp.sum(lanes.astype(_U) * weights, axis=-1, dtype=_U)


@jax.jit
def unpack(packed: jax.Array) -> jax.Array:
    """uint32 (..., H, Wp) → {0,1} uint8 (..., H, Wp*32)."""
    shifts = jnp.arange(WORD_BITS, dtype=_U)
    bits = (packed[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*packed.shape[:-1], packed.shape[-1] * WORD_BITS
                        ).astype(jnp.uint8)


def _shift_west(row: jax.Array) -> jax.Array:
    """Bitboard of each cell's west (col-1) neighbour, torus wrap."""
    return (row << 1) | (jnp.roll(row, 1, axis=-1) >> (WORD_BITS - 1))


def _shift_east(row: jax.Array) -> jax.Array:
    """Bitboard of each cell's east (col+1) neighbour, torus wrap."""
    return (row >> 1) | (jnp.roll(row, -1, axis=-1) << (WORD_BITS - 1))


def _full_add(x, y, z):
    """Bitwise full adder: per-bit x+y+z as (sum, carry)."""
    xy = x ^ y
    return xy ^ z, (x & y) | (z & xy)


def neighbour_count_bits(above, mid, below):
    """4-bit bit-sliced 8-neighbour counts (n0, n1, n2, n3) for the cells of
    `mid`, given the packed rows above and below (already torus-resolved).

    Carry-save network: horizontal triples per row via full adders, then the
    column sums of the partial bits."""
    s0a, s1a = _full_add(_shift_west(above), above, _shift_east(above))
    s0c, s1c = _full_add(_shift_west(below), below, _shift_east(below))
    w_mid, e_mid = _shift_west(mid), _shift_east(mid)
    s0b, s1b = w_mid ^ e_mid, w_mid & e_mid

    u0, u1 = _full_add(s0a, s0b, s0c)      # ones column (0..3)
    v0, v1 = _full_add(s1a, s1b, s1c)      # twos column (0..3)
    return combine_count_columns(u0, u1, v0, v1)


def combine_count_columns(u0, u1, v0, v1):
    """(ones-sum bits, twos-sum bits) → 4 bit-planes of
    n = u0 + 2*(u1 + v0) + 4*v1. Shared by the jnp and pallas kernels."""
    n1 = u1 ^ v0
    carry2 = u1 & v0
    n2 = v1 ^ carry2
    n3 = v1 & carry2
    return u0, n1, n2, n3


def _rule_from_count_bits(
    mid, n0, n1, n2, n3, rule: LifeLikeRule, count_offset: int = 0
):
    """Apply a life-like rule to bit-sliced neighbour counts.

    `count_offset=0`: (n0..n3) is the plain 8-neighbour count.
    `count_offset=1`: the count is self-inclusive (neighbours + the cell
    itself, 0..9, as the pallas kernel's shared-horizontal-sum network
    produces) — Conway becomes `(n9==3) | (alive & n9==4)` and the survive
    LUT shifts by one."""
    if rule.is_conway:
        if count_offset == 0:
            # next = n==3 | (alive & n==2)  ⇒  n1 & ~n2 & ~n3 & (n0 | alive)
            return n1 & ~n2 & ~n3 & (n0 | mid)
        # next = n9==3 | (alive & n9==4)
        return ~n3 & ((~n2 & n1 & n0) | (mid & n2 & ~n1 & ~n0))
    born, survive = rule_masks(
        n0, n1, n2, n3, rule.born, rule.survive, count_offset)
    return (~mid & born) | (mid & survive)


def rule_masks(n0, n1, n2, n3, born_set, survive_set,
               count_offset: int = 0):
    """(born_mask, survive_mask) from bit-sliced neighbour counts: bit i
    of born_mask is set iff cell i's count ∈ born_set (likewise survive,
    shifted by `count_offset` for self-inclusive counts). Shared by the
    life-like rule application above and the multi-state Generations
    packed kernel (`models/generations.py`), which combines the masks
    with its own state planes."""
    bits = (n0, n1, n2, n3)
    ones = jnp.uint32(0xFFFFFFFF)

    def eq(k: int) -> jax.Array:
        m = ones
        for i, b in enumerate(bits):
            m &= b if (k >> i) & 1 else ~b
        return m

    zero = jnp.zeros_like(n0)
    born = functools.reduce(
        lambda a, k: a | eq(k), sorted(born_set), zero)
    survive = functools.reduce(
        lambda a, k: a | eq(k + count_offset), sorted(survive_set), zero)
    return born, survive


def gen3_transition(a, d, born, surv):
    """The 3-state (alive, dying) plane transition given born/survive
    masks — the ONE copy of the algebra (r5), shared by the scan step,
    the transposed VMEM kernel, and the sharded halo step:
    a' = (~a & ~d & born) | (a & surv);  d' = a & ~surv."""
    return (~a & ~d & born) | (a & surv), a & ~surv


def gen4_transition(b0, b1, born, surv):
    """The 4-state binary-encoded transition (states 0=00, 1=01 alive,
    2=10, 3=11; dying chain 2 -> 3 -> 0 as pure bit logic) — likewise
    the single copy shared by the scan and kernel steps."""
    a = b0 & ~b1
    dying1 = ~b0 & b1
    return ((~b0 & ~b1 & born) | (a & surv) | dying1,
            (a & ~surv) | dying1)


def pack_np(cells: np.ndarray) -> np.ndarray:
    """Host-side mirror of `pack` for the wire data plane: uint8 (H, W)
    board → LSB-first packed bytes (H, ceil(W/32)*4), byte-identical to
    the little-endian word bytes of the device `pack` output. Any nonzero
    input counts as alive (so both {0,1} cells and {0,255} pixels pack to
    the same bits). Pure numpy — no device dispatch — and W need not be
    word-aligned: trailing columns are zero-padded to the word boundary."""
    if cells.ndim != 2:
        raise ValueError("pack_np expects a 2-D board")
    h, w = cells.shape
    wp = -(-w // WORD_BITS)
    if w != wp * WORD_BITS:
        padded = np.zeros((h, wp * WORD_BITS), dtype=np.uint8)
        padded[:, :w] = cells != 0
        cells = padded
    return np.packbits(np.ascontiguousarray(cells), axis=1,
                       bitorder="little")


def unpack_np(payload, h: int, w: int) -> np.ndarray:
    """Inverse host-side decode: a buffer of h*ceil(w/32)*4 LSB-first
    packed bytes → {0,1} uint8 (h, w). Accepts bytes/memoryview or a
    uint8 ndarray; always returns a fresh writable array."""
    wp = -(-w // WORD_BITS)
    if isinstance(payload, np.ndarray):
        raw = np.ascontiguousarray(payload, dtype=np.uint8).reshape(-1)
    else:
        raw = np.frombuffer(payload, dtype=np.uint8)
    if raw.size != h * wp * 4:
        raise ValueError(
            f"packed payload is {raw.size} bytes, want {h * wp * 4} "
            f"for a {h}x{w} board")
    return np.unpackbits(raw.reshape(h, wp * 4), axis=1, count=w,
                         bitorder="little")


def words_bytes_np(words: np.ndarray) -> np.ndarray:
    """(rows, Wp) uint32 host words → their wire bytes (rows, Wp*4),
    little-endian regardless of host endianness — the zero-copy (on LE
    hosts) bridge from a device_get of the packed representation to the
    `packed` wire codec."""
    a = np.ascontiguousarray(words.astype("<u4", copy=False))
    return a.view(np.uint8)


def packed_step(packed: jax.Array, rule: LifeLikeRule = CONWAY) -> jax.Array:
    """One whole-board torus turn on a (H, Wp) uint32 packed board."""
    above = jnp.roll(packed, 1, axis=-2)
    below = jnp.roll(packed, -1, axis=-2)
    n0, n1, n2, n3 = neighbour_count_bits(above, packed, below)
    return _rule_from_count_bits(packed, n0, n1, n2, n3, rule)


@functools.partial(jax.jit, static_argnames=("num_turns", "rule"))
def packed_run_turns(
    packed: jax.Array, num_turns: int, rule: LifeLikeRule = CONWAY
) -> jax.Array:
    """Advance `num_turns` turns, fully on-device."""
    if num_turns == 0:
        return packed
    def body(p, _):
        return packed_step(p, rule), None
    out, _ = lax.scan(body, packed, None, length=num_turns)
    return out


@jax.jit
def _row_popcounts(packed: jax.Array) -> jax.Array:
    return jnp.sum(lax.population_count(packed), axis=-1, dtype=jnp.int32)


def packed_alive_count(packed: jax.Array) -> int:
    """Exact alive count of a packed board (per-row popcount reduced
    on-device, summed in unbounded Python ints — same overflow story as
    `stencil.alive_count_exact`)."""
    return int(np.asarray(jax.device_get(_row_popcounts(packed)),
                          dtype=np.int64).sum())
