"""Temporally fused macro-steps: k generations per materialized HBM state.

The packed kernel is memory-bound (ROADMAP: 85.9% of measured roofline
at 131072²), so the remaining lever is arithmetic intensity: advance k
turns per HBM round trip instead of one. This module is the portable
tier of that lever — the board is tiled into row blocks, each block is
widened by a k-row halo margin on both sides, the (block + 2k)-row
window is stepped k times as its own vertical torus, and the exact
middle `block` rows are written back:

        global rows      [start - k .............. start + B + k)
        window           |<-- k -->|<---- B ---->|<-- k -->|
        after t steps     t dirty rows eat inward from each edge
        after k steps     the middle B rows are exact; trim the margins

Correctness is the deep-halo corruption-front argument
(`parallel/halo.py`): the window's vertical wrap feeds wrong rows to
its edges, but the corruption advances exactly one row per turn, so
after k turns it has consumed precisely the 2k margin rows and the
block itself is bit-identical to k applications of the radius-1 torus
step. The full board width is kept in every window, so the horizontal
torus wrap needs no margin at all. Blocks are processed sequentially
(`lax.map`), so the k inner updates of one window run against a
working set of (block + 2k)·Wp words — sized to stay cache/VMEM
resident — instead of re-streaming the whole board per turn.

On TPU the same schedule runs as the banded pallas kernel at depth k
(`ops/pallas_stencil.fused_banded_run_turns`) whenever Mosaic's
8-sublane alignment admits it (k % 8 == 0); elsewhere this jnp tier is
the one program, bit-identical by construction for every life-like rule
and for the packed Generations plane pairs.

Depth selection: `GOL_FUSE_K` pins k explicitly (0/unset = auto, which
keeps each dispatch tier's native adaptive depth — the banded kernel's
BAND_T, the mesh deep-halo T — i.e. legacy behavior). A turn count not
divisible by k finishes with single radius-1 steps (the trim scan), so
chunk clamps and checkpoint-turn exactness hold for any k.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule
from gol_tpu.ops.bitpack import packed_run_turns, packed_step
from gol_tpu.utils.envcfg import env_int

FUSE_K_ENV = "GOL_FUSE_K"
# A fuse depth past this is all margin: redundant-compute overhead
# (2k / block) grows while the HBM saving (1/k of the passes) has long
# flattened — and the engine's chunk sizes rarely exceed it anyway.
MAX_FUSE_K = 64

# Per-window byte budget for the jnp tier: block + 2k rows of packed
# words must fit a cache-resident working set. Overridable for tuning
# (read when a fused program is first traced for a shape).
FUSE_BLOCK_BYTES_ENV = "GOL_FUSE_BLOCK_BYTES"
DEFAULT_FUSE_BLOCK_BYTES = 8 * 1024 * 1024


def configured_fuse_k() -> int:
    """The pinned fuse depth from GOL_FUSE_K, clamped to [0, MAX_FUSE_K].
    0 (or unset/garbage) means auto: dispatch tiers keep their native
    adaptive depths and no explicit macro-stepping is forced."""
    return min(env_int(FUSE_K_ENV, 0, minimum=0), MAX_FUSE_K)


def fuse_block_rows(height: int, wp: int, fuse: int,
                    budget: Optional[int] = None) -> int:
    """Largest divisor B of `height` whose (B + 2·fuse)-row window fits
    the byte budget, subject to B >= 2·fuse (margin recompute bounded at
    2x useful work). Returns 0 when no divisor qualifies and `height`
    when only the whole board does — both cases mean the caller should
    run the plain scan (no tiling is possible or profitable)."""
    if budget is None:
        budget = env_int(FUSE_BLOCK_BYTES_ENV, DEFAULT_FUSE_BLOCK_BYTES)
    row_bytes = wp * 4
    best = 0
    for cand in range(max(1, 2 * fuse), height + 1):
        if height % cand:
            continue
        if (cand + 2 * fuse) * row_bytes > budget:
            break  # windows only grow with the block size
        best = cand
    return best


def _window_index(height: int, block: int, fuse: int) -> np.ndarray:
    """(n_blocks, block + 2·fuse) modular row indices: row j of window i
    is global row (i·block + j - fuse) mod height."""
    nb = height // block
    return ((np.arange(-fuse, block + fuse)[None, :]
             + np.arange(nb)[:, None] * block) % height).astype(np.int32)


@functools.partial(
    jax.jit, static_argnames=("num_turns", "fuse", "block", "rule")
)
def _fused_packed_scan(
    packed: jax.Array, num_turns: int, fuse: int, block: int,
    rule: LifeLikeRule,
) -> jax.Array:
    """The jnp windowed fused program for a (H, Wp) packed board:
    full k-deep macro-steps, then the single-step trim scan for the
    `num_turns % fuse` remainder."""
    height = packed.shape[-2]
    idx = jnp.asarray(_window_index(height, block, fuse))
    macros, rem = divmod(num_turns, fuse)

    def macro(w, _):
        def one(rows):
            win = jnp.take(w, rows, axis=-2)

            def body(x, _):
                return packed_step(x, rule), None

            out, _ = lax.scan(body, win, None, length=fuse)
            return lax.slice_in_dim(out, fuse, fuse + block, axis=-2)

        return lax.map(one, idx).reshape(w.shape), None

    out, _ = lax.scan(macro, packed, None, length=macros)
    if rem:
        def single(x, _):
            return packed_step(x, rule), None

        out, _ = lax.scan(single, out, None, length=rem)
    return out


def _platform_of(arr, platform: Optional[str]) -> str:
    if platform is not None:
        return platform
    devices = getattr(arr, "devices", None)
    dev = next(iter(devices())) if devices else jax.devices()[0]
    return dev.platform


def fused_packed_run_turns(
    packed: jax.Array,
    num_turns: int,
    rule: LifeLikeRule = CONWAY,
    fuse: int = 0,
    platform: Optional[str] = None,
) -> jax.Array:
    """Advance a (H, Wp) packed board `num_turns` turns at fuse depth
    `fuse` — bit-identical to `packed_run_turns` for every life-like
    rule by the margin-trim construction. `fuse <= 1` IS the plain scan
    (the k=1 replay control the benches gate against). `platform` must
    be supplied when `packed` may be a tracer (callers composing this
    inside their own jit), same convention as the halo dispatchers."""
    if num_turns <= 0:
        return packed
    if fuse <= 1:
        return packed_run_turns(packed, num_turns, rule)
    fuse = min(fuse, MAX_FUSE_K)
    height, wp = packed.shape[-2], packed.shape[-1]
    platform = _platform_of(packed, platform)
    if platform == "tpu" and wp >= 2:
        from gol_tpu.ops.pallas_stencil import fused_banded_supported

        if fused_banded_supported(packed.shape, fuse):
            from gol_tpu.ops.pallas_stencil import fused_banded_run_turns

            return fused_banded_run_turns(packed, num_turns, fuse, rule)
    block = fuse_block_rows(height, wp, fuse)
    if block == 0 or block >= height:
        # No divisor tiles profitably (tiny board, prime height, or the
        # whole board already fits the window budget): the plain scan is
        # the same bits without pointless margin recompute.
        return packed_run_turns(packed, num_turns, rule)
    return _fused_packed_scan(packed, num_turns, fuse, block, rule)


# ------------------------------------------------- Generations planes
#
# The packed multi-state families ride the identical window schedule:
# the stacked planes (2, H, Wp) are gathered per block along the row
# axis (both planes — the window steps need the dying/encoding plane's
# margins too, unlike the depth-1 halo exchange which only ships the
# alive plane), stepped k times with the family's plane transition, and
# trimmed. The corruption-front argument is family-independent: every
# transition is radius-1 in the alive plane and radius-0 in the rest.


@functools.partial(
    jax.jit,
    static_argnames=("num_turns", "fuse", "block", "rule", "family"),
)
def _fused_planes_scan(
    stacked: jax.Array, num_turns: int, fuse: int, block: int, rule,
    family: str,
) -> jax.Array:
    from gol_tpu.models.generations import _packed_step3, _packed_step4

    step2p = _packed_step3 if family == "gen3" else _packed_step4
    height = stacked.shape[-2]
    idx = jnp.asarray(_window_index(height, block, fuse))
    macros, rem = divmod(num_turns, fuse)

    def plane_step(s):
        p0, p1 = step2p(s[0], s[1], rule)
        return jnp.stack([p0, p1])

    def macro(s, _):
        def one(rows):
            win = jnp.take(s, rows, axis=-2)

            def body(x, _):
                return plane_step(x), None

            out, _ = lax.scan(body, win, None, length=fuse)
            return lax.slice_in_dim(out, fuse, fuse + block, axis=-2)

        centers = lax.map(one, idx)  # (nb, 2, block, Wp)
        return jnp.moveaxis(centers, 0, 1).reshape(s.shape), None

    out, _ = lax.scan(macro, stacked, None, length=macros)
    if rem:
        def single(x, _):
            return plane_step(x), None

        out, _ = lax.scan(single, out, None, length=rem)
    return out


def _fused_planes_run(stacked, num_turns, rule, fuse, platform, family,
                      dispatch_fn):
    """Shared gen3/gen4 routing: `dispatch_fn` is the family's native
    engine dispatcher (which already runs the k-deep VMEM kernel on TPU
    where the planes fit — the windowed tier must never shadow it)."""
    if num_turns <= 0:
        return stacked
    platform = _platform_of(stacked, platform)
    if fuse <= 1 or platform == "tpu":
        return dispatch_fn(stacked, num_turns, rule, platform)
    fuse = min(fuse, MAX_FUSE_K)
    height, wp = stacked.shape[-2], stacked.shape[-1]
    # Half the packed budget: BOTH planes ride each window.
    block = fuse_block_rows(height, wp, fuse,
                            budget=env_int(FUSE_BLOCK_BYTES_ENV,
                                           DEFAULT_FUSE_BLOCK_BYTES) // 2)
    if block == 0 or block >= height:
        return dispatch_fn(stacked, num_turns, rule, platform)
    return _fused_planes_scan(stacked, num_turns, fuse, block, rule,
                              family)


def fused_gen3_run_turns(
    stacked: jax.Array, num_turns: int, rule, fuse: int = 0,
    platform: Optional[str] = None,
) -> jax.Array:
    """Advance stacked packed (alive, dying) 3-state planes `num_turns`
    turns at fuse depth `fuse`; `fuse <= 1` is the native dispatcher.
    Returns the stacked planes (the engine's single-array convention)."""
    from gol_tpu.models.generations import packed_run_turns3

    def dispatch_fn(s, n, r, plat):
        a, d = packed_run_turns3(s[0], s[1], n, r, platform=plat)
        return jnp.stack([a, d])

    return _fused_planes_run(stacked, num_turns, rule, fuse, platform,
                             "gen3", dispatch_fn)


def fused_gen4_run_turns(
    stacked: jax.Array, num_turns: int, rule, fuse: int = 0,
    platform: Optional[str] = None,
) -> jax.Array:
    """The C=4 sibling: stacked binary-encoded (b0, b1) planes."""
    from gol_tpu.models.generations import packed_run_turns4

    def dispatch_fn(s, n, r, plat):
        o0, o1 = packed_run_turns4(s[0], s[1], n, r, platform=plat)
        return jnp.stack([o0, o1])

    return _fused_planes_run(stacked, num_turns, rule, fuse, platform,
                             "gen4", dispatch_fn)
