"""Single-device GoL stencil — the compute kernel of the framework.

Replaces the reference's per-cell branchy Go loop
(`SubServer/distributor.go:119-208`: wrap-around index arithmetic per cell,
8 compares against 255, fresh allocation per strip per turn) with a
vectorized torus stencil in pure array ops, traced once under jit:

* neighbour counts via the separable two-pass roll-sum (3 vertical adds then
  3 horizontal adds, minus self) — 6 adds/cell instead of 8,
* the rule as a branch-free lane-wise select
  (`alive' = (n == 3) | (alive & (n == 2))` for Conway; LUT gather for any
  life-like rule),
* the turn loop as `lax.scan`, entirely on-device — zero host round trips
  per turn (the reference moves the whole board through the broker twice
  per turn, `Server/gol/distributor.go:118-129`).

Boards are uint8 {0,1} ("cells") internally; {0,255} ("pixels") only at the
I/O boundary (`from_pixels`/`to_pixels`), matching the reference's strict
{0,255} encoding (`io.go:109-111`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule


def from_pixels(pixels) -> jax.Array:
    """{0,255} uint8 pixels → {0,1} uint8 cells."""
    return (jnp.asarray(pixels, dtype=jnp.uint8) != 0).astype(jnp.uint8)


def to_pixels(cells) -> jax.Array:
    """{0,1} uint8 cells → {0,255} uint8 pixels."""
    return jnp.asarray(cells, dtype=jnp.uint8) * jnp.uint8(255)


def neighbour_counts(cells: jax.Array) -> jax.Array:
    """8-neighbour live counts on the torus, separable roll-sum.

    Works on any array whose last two dims are (rows, cols)."""
    vert = (
        cells
        + jnp.roll(cells, 1, axis=-2)
        + jnp.roll(cells, -1, axis=-2)
    )
    return (
        vert
        + jnp.roll(vert, 1, axis=-1)
        + jnp.roll(vert, -1, axis=-1)
        - cells
    )


def apply_rule(
    cells: jax.Array, counts: jax.Array, rule: LifeLikeRule = CONWAY
) -> jax.Array:
    """Branch-free life-like rule application on {0,1} cells."""
    if rule.is_conway:
        three = counts == 3
        two = counts == 2
        return (three | ((cells == 1) & two)).astype(jnp.uint8)
    born_lut, survive_lut = rule.luts()
    born = jnp.asarray(born_lut, dtype=jnp.uint8)[counts]
    survive = jnp.asarray(survive_lut, dtype=jnp.uint8)[counts]
    return jnp.where(cells == 1, survive, born)


def step(cells: jax.Array, rule: LifeLikeRule = CONWAY) -> jax.Array:
    """One whole-board torus turn on {0,1} uint8 cells."""
    return apply_rule(cells, neighbour_counts(cells), rule)


@functools.partial(jax.jit, static_argnames=("num_turns", "rule"))
def run_turns(
    cells: jax.Array, num_turns: int, rule: LifeLikeRule = CONWAY
) -> jax.Array:
    """Advance `num_turns` turns in one compiled on-device loop."""
    if num_turns == 0:
        return cells
    def body(c, _):
        return step(c, rule), None
    out, _ = lax.scan(body, cells, None, length=num_turns)
    return out


@jax.jit
def alive_count(cells: jax.Array) -> jax.Array:
    """Total live cells — the reference's O(H·W) broker rescan
    (`Server/gol/distributor.go:173-183`) as one on-device reduction."""
    return jnp.sum(cells, dtype=jnp.int32)


@jax.jit
def _row_alive_counts(cells: jax.Array) -> jax.Array:
    return jnp.sum(cells, axis=-1, dtype=jnp.int32)


def alive_count_exact(cells: jax.Array) -> int:
    """Overflow-proof alive count: int32 saturates at 2^31-1 but boards up
    to 65536² have 2^32 cells, so reduce rows on-device (each row ≤ W ≤
    int32 range) and finish the sum in Python's unbounded ints."""
    return int(np.asarray(jax.device_get(_row_alive_counts(cells)),
                          dtype=np.int64).sum())


