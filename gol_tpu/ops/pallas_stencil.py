"""Pallas TPU kernel: multi-turn bit-packed stepping with the board
resident in VMEM.

The jnp packed kernel (`ops/bitpack.py`) is already bit-parallel, but under
`lax.scan` XLA materialises the rolled neighbour bitboards in HBM every
turn — the measured ceiling is HBM traffic on intermediates, not compute.
This kernel runs K turns inside one `pallas_call`: the packed board is
loaded into VMEM once, the adder network runs on VMEM-resident values for
all K turns (`lax.fori_loop`), and HBM sees exactly one read and one write
of the board per K turns.

Two kernel-only optimisations on top of the bitpack math (results stay
bit-identical; tests cross-check against the jnp path):

* **Shared horizontal sums.** The 3-cell horizontal sums of the rows above
  and below a cell are just row-rolls of the same per-row sums, so the
  (west, self, east) full-adder runs once per board, not three times per
  row-triple — the 8-neighbour network drops from ~54 to ~39 bitwise ops
  per word. The count becomes self-inclusive (0..9); the rule translates to
  `alive' = (n9 == 3) | (alive & n9 == 4)` for Conway, and born/survive
  LUTs shift by one for the general life-like case.

* **Transposed compute layout.** TPU tiles are (8 sublanes, 128 lanes); a
  packed board (H, W/32) puts the short word axis on lanes (W/32 = 160 for
  a 5120-wide board → 40% lane padding). The kernel transposes once to
  (W/32, H) so the long H axis rides the lanes, loops K turns there, and
  transposes back — two transposes per K turns.

Eligibility: the whole packed board (plus the ~16x working set of the adder
network) must fit in VMEM — `fits_in_vmem` gates it — and the kernel is
currently dispatched on the single-shard path only
(`parallel/halo.py:_single_device_packed_run`). Larger boards and
multi-shard meshes use the jnp packed path; composing this kernel
per-shard under a deep-halo exchange is planned, not implemented.

Used on TPU; `interpret=True` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule
from gol_tpu.ops.bitpack import (
    WORD_BITS,
    _full_add,
    _rule_from_count_bits,
    combine_count_columns,
)

# The compiled kernel holds the loop carry plus ~14 same-size temporaries
# of the adder network live at once (measured: a 3.3 MB board allocates
# ~49 MB scoped VMEM), so the board budget is VMEM_LIMIT / 16.
VMEM_LIMIT_BYTES = 64 * 1024 * 1024
VMEM_BOARD_BYTES = VMEM_LIMIT_BYTES // 16


def fits_in_vmem(shape, itemsize: int = 4) -> bool:
    h, wp = shape[-2], shape[-1]
    return h * wp * itemsize <= VMEM_BOARD_BYTES


def _step_transposed(t: jax.Array, rule: LifeLikeRule) -> jax.Array:
    """One torus turn on a transposed packed board t of shape (Wp, H):
    axis 0 = words of a row (horizontal), axis 1 = board rows (vertical).

    Self-inclusive 9-cell count: hs = west + self + east per cell (bit pair
    hs0/hs1), then the vertical full-adder over (row-1, row, row+1) of hs
    gives n9 = n8 + self in 4 bit-planes."""
    shift = WORD_BITS - 1
    west = (t << 1) | (jnp.roll(t, 1, axis=0) >> shift)
    east = (t >> 1) | (jnp.roll(t, -1, axis=0) << shift)
    hs0, hs1 = _full_add(west, t, east)

    u0, u1 = _full_add(jnp.roll(hs0, 1, axis=1), hs0,
                       jnp.roll(hs0, -1, axis=1))
    v0, v1 = _full_add(jnp.roll(hs1, 1, axis=1), hs1,
                       jnp.roll(hs1, -1, axis=1))
    n0, n1, n2, n3 = combine_count_columns(u0, u1, v0, v1)
    return _rule_from_count_bits(t, n0, n1, n2, n3, rule, count_offset=1)


def _make_kernel(num_turns: int, rule: LifeLikeRule):
    def kernel(in_ref, out_ref):
        def body(_, t):
            return _step_transposed(t, rule)
        out_ref[:] = lax.fori_loop(
            0, num_turns, body, in_ref[:].T
        ).T
    return kernel


@functools.partial(
    jax.jit, static_argnames=("num_turns", "rule", "interpret")
)
def pallas_packed_run_turns(
    packed: jax.Array,
    num_turns: int,
    rule: LifeLikeRule = CONWAY,
    interpret: bool = False,
) -> jax.Array:
    """Advance a packed (H, Wp) board `num_turns` turns in one kernel."""
    if num_turns == 0:
        return packed
    return pl.pallas_call(
        _make_kernel(num_turns, rule),
        out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )(packed)
