"""Pallas TPU kernel: multi-turn bit-packed stepping with the board
resident in VMEM.

The jnp packed kernel (`ops/bitpack.py`) is already bit-parallel, but under
`lax.scan` XLA materialises the rolled neighbour bitboards in HBM every
turn — the measured ceiling is HBM traffic on intermediates, not compute.
This kernel runs K turns inside one `pallas_call`: the packed board is
loaded into VMEM once, the adder network runs on VMEM-resident values for
all K turns (`lax.fori_loop`), and HBM sees exactly one read and one write
of the board per K turns.

Two kernel-only optimisations on top of the bitpack math (results stay
bit-identical; tests cross-check against the jnp path):

* **Shared horizontal sums.** The 3-cell horizontal sums of the rows above
  and below a cell are just row-rolls of the same per-row sums, so the
  (west, self, east) full-adder runs once per board, not three times per
  row-triple — the 8-neighbour network drops from ~54 to ~39 bitwise ops
  per word. The count becomes self-inclusive (0..9); the rule translates to
  `alive' = (n9 == 3) | (alive & n9 == 4)` for Conway, and born/survive
  LUTs shift by one for the general life-like case.

* **Transposed compute layout.** TPU tiles are (8 sublanes, 128 lanes); a
  packed board (H, W/32) puts the short word axis on lanes (W/32 = 160 for
  a 5120-wide board → 40% lane padding). The kernel transposes once to
  (W/32, H) so the long H axis rides the lanes, loops K turns there, and
  transposes back — two transposes per K turns.

Eligibility: the whole packed board (plus the ~16x working set of the adder
network) must fit in VMEM — `fits_in_vmem` gates it. Dispatch sites: the
single-shard path (`parallel/halo.py:_single_device_packed_run`, which
prefers the banded kernel when it applies), and per-shard under deep-halo
macro-stepping on multi-shard meshes (`parallel/halo.py:inner_kind`
composes this kernel on each shard's haloed window when it fits VMEM).
Boards too big for either kernel use the jnp packed scan.

Used on TPU; `interpret=True` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.models.lifelike import CONWAY, LifeLikeRule
from gol_tpu.ops.bitpack import (
    WORD_BITS,
    _full_add,
    _rule_from_count_bits,
    combine_count_columns,
)

# The compiled whole-board kernel holds the loop carry plus ~14 same-size
# temporaries of the adder network live at once (measured: a 3.3 MB board
# allocates ~49 MB scoped VMEM), so its board budget is VMEM_LIMIT / 16.
VMEM_LIMIT_BYTES = 64 * 1024 * 1024

# jax renamed pltpu.TPUCompilerParams to pltpu.CompilerParams across
# releases; resolve whichever this jax ships so the kernels (and their
# interpret-mode tests) work on both sides of the rename. None means the
# installed pallas has neither spelling — `interpret_supported()` then
# reports the capability as absent and the tier-1 pallas tests skip with
# that reason instead of erroring (docs/PARITY.md).
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams", None
)

_INTERPRET_PROBE: "tuple[bool, str] | None" = None


def interpret_supported() -> "tuple[bool, str]":
    """Capability probe: can this jax run these kernels in interpret
    mode on the current backend?  Returns (ok, reason); the reason is
    the skip message tests surface when ok is False. Probes once per
    process with a trivial copy kernel — cheap, and exercises the same
    pallas_call + CompilerParams surface the real kernels use."""
    global _INTERPRET_PROBE
    if _INTERPRET_PROBE is None:
        if _COMPILER_PARAMS_CLS is None:
            _INTERPRET_PROBE = (
                False,
                "pallas.tpu has neither CompilerParams nor "
                "TPUCompilerParams (jax rename drift)",
            )
        else:
            def _copy(x_ref, o_ref):
                o_ref[...] = x_ref[...]

            try:
                x = jnp.zeros((8, 128), jnp.uint32)
                pl.pallas_call(
                    _copy,
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    compiler_params=_COMPILER_PARAMS_CLS(
                        vmem_limit_bytes=VMEM_LIMIT_BYTES
                    ),
                    interpret=True,
                )(x)
                _INTERPRET_PROBE = (True, "")
            except Exception as e:  # noqa: BLE001 — any failure = skip
                _INTERPRET_PROBE = (
                    False, f"pallas interpret probe failed: "
                           f"{type(e).__name__}: {e}")
    return _INTERPRET_PROBE
VMEM_BOARD_BYTES = VMEM_LIMIT_BYTES // 16

# The banded kernel's working set is different: the scratch window is
# explicit and Mosaic keeps the (H, Wp)-layout adder network fused, so
# windows far beyond VMEM_BOARD_BYTES compile and run inside the same
# 64 MB limit. Budget measured on the real chip (r3 sweep): an 8.9 MB
# window (band 1024 + 2x32 halo rows at 65536 wide) is fastest
# (2.24e12 cups, +12% over the old 4 MB/band-256 config); 2048-row bands
# (17 MB) regress. 10 MB keeps the winner with guard room.
BANDED_WINDOW_BYTES = 10 * 1024 * 1024


def fits_in_vmem(shape, itemsize: int = 4) -> bool:
    h, wp = shape[-2], shape[-1]
    return h * wp * itemsize <= VMEM_BOARD_BYTES


def _self_inclusive_count_bits(
    p: jax.Array, word_axis: int, row_axis: int
):
    """The shared-horizontal-sum network: 4 bit-planes of the
    self-inclusive 9-cell count n9 = n8 + self, for any placement of
    the word (horizontal) and row (vertical) axes. hs = west + self +
    east per cell (bit pair hs0/hs1), then the vertical full-adder over
    (row-1, row, row+1) of hs. The ONE copy of this network, shared by
    the life-like and the two-plane Generations kernels."""
    shift = WORD_BITS - 1
    west = (p << 1) | (jnp.roll(p, 1, axis=word_axis) >> shift)
    east = (p >> 1) | (jnp.roll(p, -1, axis=word_axis) << shift)
    hs0, hs1 = _full_add(west, p, east)
    u0, u1 = _full_add(jnp.roll(hs0, 1, axis=row_axis), hs0,
                       jnp.roll(hs0, -1, axis=row_axis))
    v0, v1 = _full_add(jnp.roll(hs1, 1, axis=row_axis), hs1,
                       jnp.roll(hs1, -1, axis=row_axis))
    return combine_count_columns(u0, u1, v0, v1)


def _step_shared_sums(
    p: jax.Array, rule: LifeLikeRule, word_axis: int, row_axis: int
) -> jax.Array:
    """One life-like torus turn with the shared-horizontal-sum
    network."""
    n0, n1, n2, n3 = _self_inclusive_count_bits(p, word_axis, row_axis)
    return _rule_from_count_bits(p, n0, n1, n2, n3, rule, count_offset=1)


def _step_transposed(t: jax.Array, rule: LifeLikeRule) -> jax.Array:
    """One turn on a transposed (Wp, H) board — words on sublanes, rows on
    lanes (VMEM-resident kernel for narrow boards)."""
    return _step_shared_sums(t, rule, word_axis=0, row_axis=1)


def _step_transposed3(a: jax.Array, d: jax.Array, rule):
    """One Generations C=3 turn on transposed (Wp, H) packed planes.
    The shared self-inclusive count network applies to the ALIVE plane
    unchanged (neighbour counts are of alive cells only); the rule
    masks take count_offset=1 — for a dead cell n9 == n8 so the born
    LUT needs no shift, for an alive cell n9 == n8 + 1 so survive
    shifts by one, exactly the life-like translation."""
    from gol_tpu.ops.bitpack import gen3_transition, rule_masks

    n0, n1, n2, n3 = _self_inclusive_count_bits(
        a, word_axis=0, row_axis=1)
    born, surv = rule_masks(n0, n1, n2, n3, rule.born, rule.survive,
                            count_offset=1)
    return gen3_transition(a, d, born, surv)


def _step_rows_cols(p: jax.Array, rule: LifeLikeRule) -> jax.Array:
    """One turn in the natural (H, Wp) layout, used where the word axis is
    already wide enough to fill the 128 vector lanes (banded kernel)."""
    return _step_shared_sums(p, rule, word_axis=-1, row_axis=-2)


# Turn-loop unrolling for the whole-board VMEM kernel: small boards are
# fori-loop-overhead-bound, and 8 turns per loop body is +11% on the 512²
# north-star (3.43 -> 3.80 M turns/s, r3 sweep). The banded kernel does
# NOT unroll — its big windows make the loop overhead negligible and the
# fatter body regresses it (~-18% measured).
VMEM_KERNEL_UNROLL = 8


def _make_kernel(num_turns: int, rule: LifeLikeRule):
    main, rem = divmod(num_turns, VMEM_KERNEL_UNROLL)

    def kernel(in_ref, out_ref):
        t = in_ref[:].T
        if main:
            def body(_, t):
                for _ in range(VMEM_KERNEL_UNROLL):
                    t = _step_transposed(t, rule)
                return t
            t = lax.fori_loop(0, main, body, t)
        for _ in range(rem):
            t = _step_transposed(t, rule)
        out_ref[:] = t.T
    return kernel


def _step_transposed4(b0: jax.Array, b1: jax.Array, rule):
    """One 4-state turn on transposed (Wp, H) binary-encoded planes
    (encoding + transition algebra: `models/generations.py` module
    note). Same self-inclusive count translation as the C=3 kernel —
    born unshifted (dead cells have n9 == n8), survive shifted by 1."""
    from gol_tpu.ops.bitpack import gen4_transition, rule_masks

    a = b0 & ~b1
    n0, n1, n2, n3 = _self_inclusive_count_bits(
        a, word_axis=0, row_axis=1)
    born, surv = rule_masks(n0, n1, n2, n3, rule.born, rule.survive,
                            count_offset=1)
    return gen4_transition(b0, b1, born, surv)


def _make_kernel2p(num_turns: int, rule, step):
    """Two-plane variant of `_make_kernel`: stacked (2, H, Wp) planes
    in VMEM, transposed compute layout, same unroll. `step` is the
    per-turn transposed two-plane function (gen3's alive/dying planes
    or gen4's binary-encoded planes)."""
    main, rem = divmod(num_turns, VMEM_KERNEL_UNROLL)

    def kernel(in_ref, out_ref):
        a, d = in_ref[0].T, in_ref[1].T
        if main:
            def body(_, planes):
                a, d = planes
                for _ in range(VMEM_KERNEL_UNROLL):
                    a, d = step(a, d, rule)
                return a, d
            a, d = lax.fori_loop(0, main, body, (a, d))
        for _ in range(rem):
            a, d = step(a, d, rule)
        out_ref[0] = a.T
        out_ref[1] = d.T
    return kernel


def fits_in_vmem3(shape, itemsize: int = 4) -> bool:
    """Eligibility of the stacked (2, H, Wp) two-plane board for the
    VMEM kernel: both planes plus the adder working set must fit, so
    the per-plane budget halves."""
    h, wp = shape[-2], shape[-1]
    return 2 * h * wp * itemsize <= VMEM_BOARD_BYTES


def _vmem_pallas_call(kernel, operand, interpret: bool):
    """The ONE whole-board VMEM pallas_call configuration (shared by
    the life-like and two-plane kernels): board in, same-shape board
    out, both VMEM-resident."""
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(operand.shape, operand.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        compiler_params=_COMPILER_PARAMS_CLS(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        ),
        interpret=interpret,
    )(operand)


@functools.partial(
    jax.jit, static_argnames=("num_turns", "rule", "interpret")
)
def pallas_packed_run_turns3(
    stacked: jax.Array,
    num_turns: int,
    rule,
    interpret: bool = False,
) -> jax.Array:
    """Advance stacked packed (alive, dying) planes `num_turns` turns in
    one VMEM-resident kernel. r5: with the transposed layout + shared
    self-inclusive sums + 8x unroll this beats the two-plane XLA scan
    2.2x on the real chip (4096² Brian's Brain, interleaved A/B:
    1.52-1.59e12 vs 0.71-0.74e12 cups) — the r4 note that a pallas
    variant was slower predates those three optimisations."""
    if num_turns == 0:
        return stacked
    return _vmem_pallas_call(
        _make_kernel2p(num_turns, rule, _step_transposed3),
        stacked, interpret)


@functools.partial(
    jax.jit, static_argnames=("num_turns", "rule", "interpret")
)
def pallas_packed_run_turns4(
    stacked: jax.Array,
    num_turns: int,
    rule,
    interpret: bool = False,
) -> jax.Array:
    """Advance stacked binary-encoded 4-state planes (b0, b1)
    `num_turns` turns in one VMEM-resident kernel — the C=4 sibling of
    `pallas_packed_run_turns3` (r5; Star Wars at bit-parallel rates)."""
    if num_turns == 0:
        return stacked
    return _vmem_pallas_call(
        _make_kernel2p(num_turns, rule, _step_transposed4),
        stacked, interpret)


# ------------------------------------------------------------------ banded
#
# Boards too big for VMEM: grid over row-bands. Each grid program DMAs its
# band plus a T-row halo on each side from HBM into VMEM scratch, advances
# the window T turns locally (the window's vertical wrap corrupts one edge
# row per turn — after T turns the corruption has consumed exactly the
# halos and the band itself is exact), and writes the band. HBM traffic per
# T turns: (1 + 2T/B) reads + 1 write of the board, instead of ~10
# materialised intermediates per single turn on the jnp path. All programs
# read the unchanged input board, so bands race-freely share it.

BAND_T = 32  # turns per banded pass == halo depth (r3 sweep: beats 8/16)


def _band_rows(height: int, wp: int, halo_t: int = BAND_T) -> int:
    """Largest 8-aligned divisor of `height` whose (B + 2*halo_t, wp)
    window fits the banded window budget; 0 if none exists or if the word
    axis is not 128-lane aligned (a Mosaic DMA slice requirement).
    `halo_t` is the sweep depth — BAND_T for the native passes, the fuse
    depth k for the temporally fused tier — and must itself be a multiple
    of 8 (the same DMA alignment constraint as the band).

    Bands must be at least `halo_t` rows: a shorter band would let a halo
    piece wrap around the torus INSIDE one DMA (the kernel's three-piece
    copy assumes wraps only happen at piece boundaries) and read out of
    bounds."""
    if wp % 128 != 0 or halo_t <= 0 or halo_t % 8 != 0:
        return 0
    max_b = BANDED_WINDOW_BYTES // (wp * 4) - 2 * halo_t
    b = 0
    for cand in range(halo_t, max_b + 1, 8):
        if height % cand == 0:
            b = cand
    return b


def _make_banded_kernel(
    band: int, halo_t: int, height: int, rule: LifeLikeRule
):
    def kernel(in_hbm, out_ref, scratch, sems):
        i = pl.program_id(0)
        # band and halo_t are multiples of 8; rem() obscures that from the
        # Mosaic alignment prover, so re-assert it.
        start = pl.multiple_of(i * band, 8)
        top = pl.multiple_of(
            lax.rem(start - halo_t + height, height), 8)
        bot = pl.multiple_of(lax.rem(start + band, height), 8)
        # Three contiguous pieces: the wrap only ever happens at a piece
        # boundary (first band's top halo = last rows; last band's bottom
        # halo = first rows), never inside a piece.
        copies = [
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(top, halo_t)],
                scratch.at[pl.ds(0, halo_t)], sems.at[0]),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(start, band)],
                scratch.at[pl.ds(halo_t, band)], sems.at[1]),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(bot, halo_t)],
                scratch.at[pl.ds(halo_t + band, halo_t)], sems.at[2]),
        ]
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

        def body(_, w):
            return _step_rows_cols(w, rule)
        w = lax.fori_loop(0, halo_t, body, scratch[:])
        out_ref[:] = w[halo_t:halo_t + band]
    return kernel


@functools.partial(
    jax.jit, static_argnames=("halo_t", "rule", "interpret")
)
def _banded_pass(
    packed: jax.Array,
    halo_t: int,
    rule: LifeLikeRule = CONWAY,
    interpret: bool = False,
) -> jax.Array:
    """Advance a big packed board `halo_t` turns in one banded sweep."""
    height, wp = packed.shape
    band = _band_rows(height, wp, halo_t)
    if band == 0:
        raise ValueError(
            f"no viable band size for board {packed.shape}")
    return pl.pallas_call(
        _make_banded_kernel(band, halo_t, height, rule),
        grid=(height // band,),
        out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (band, wp), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((band + 2 * halo_t, wp), jnp.uint32),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        # Bands are independent (they all read the unchanged input), so
        # the grid is parallel — Mosaic splits it across TensorCores on
        # multi-core chips (free on the 1-core v5e this was tuned on).
        compiler_params=_COMPILER_PARAMS_CLS(
            vmem_limit_bytes=VMEM_LIMIT_BYTES,
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(packed)


def banded_supported(shape) -> bool:
    return _band_rows(shape[-2], shape[-1]) > 0


@functools.partial(
    jax.jit, static_argnames=("num_turns", "rule", "interpret")
)
def banded_packed_run_turns(
    packed: jax.Array,
    num_turns: int,
    rule: LifeLikeRule = CONWAY,
    interpret: bool = False,
) -> jax.Array:
    """Advance a VMEM-oversized packed board `num_turns` turns by repeated
    banded BAND_T-turn sweeps. A remainder that is itself a multiple of 8
    runs as one shallower banded sweep; any other remainder falls back to
    the jnp packed scan (Mosaic DMA offsets must stay 8-sublane-aligned,
    so halo depths that are not multiples of 8 cannot be swept)."""
    from gol_tpu.ops.bitpack import packed_run_turns

    full, rem = divmod(num_turns, BAND_T)
    p = packed
    if full:
        def body(c, _):
            return _banded_pass(c, BAND_T, rule, interpret), None
        p, _ = lax.scan(body, p, None, length=full)
    if rem:
        if rem % 8 == 0:
            p = _banded_pass(p, rem, rule, interpret)
        elif fits_in_vmem(p.shape):
            # Small turn counts on VMEM-fitting boards (e.g. the engine's
            # 1/2/4-turn starting chunks) use the whole-board VMEM kernel
            # rather than regressing to the HBM-bound jnp scan.
            p = pallas_packed_run_turns(p, rem, rule, interpret)
        else:
            p = packed_run_turns(p, rem, rule)
    return p


def fused_banded_supported(shape, fuse: int) -> bool:
    """Whether the banded kernel can sweep `fuse` turns per HBM pass on
    this board: the fuse depth must satisfy Mosaic's 8-sublane DMA
    alignment and some 8-aligned divisor band must fit the window budget
    with 2·fuse margin rows."""
    return (fuse > 0 and fuse % 8 == 0
            and _band_rows(shape[-2], shape[-1], fuse) > 0)


@functools.partial(
    jax.jit, static_argnames=("num_turns", "fuse", "rule", "interpret")
)
def fused_banded_run_turns(
    packed: jax.Array,
    num_turns: int,
    fuse: int,
    rule: LifeLikeRule = CONWAY,
    interpret: bool = False,
) -> jax.Array:
    """Advance a packed board `num_turns` turns by `fuse`-deep banded
    sweeps — the TPU tier of the temporal-fusion lever (`ops/fused.py`):
    each sweep reads the board (plus 2·fuse/band margin) from HBM once
    and advances `fuse` turns in VMEM. The `num_turns % fuse` remainder
    reuses the native banded remainder policy (shallower 8-aligned
    sweep, else the VMEM kernel, else the jnp trim scan)."""
    from gol_tpu.ops.bitpack import packed_run_turns

    full, rem = divmod(num_turns, fuse)
    p = packed
    if full:
        def body(c, _):
            return _banded_pass(c, fuse, rule, interpret), None
        p, _ = lax.scan(body, p, None, length=full)
    if rem:
        if rem % 8 == 0 and _band_rows(p.shape[-2], p.shape[-1], rem):
            p = _banded_pass(p, rem, rule, interpret)
        elif fits_in_vmem(p.shape):
            p = pallas_packed_run_turns(p, rem, rule, interpret)
        else:
            p = packed_run_turns(p, rem, rule)
    return p


@functools.partial(
    jax.jit, static_argnames=("num_turns", "rule", "interpret")
)
def pallas_packed_run_turns(
    packed: jax.Array,
    num_turns: int,
    rule: LifeLikeRule = CONWAY,
    interpret: bool = False,
) -> jax.Array:
    """Advance a packed (H, Wp) board `num_turns` turns in one kernel."""
    if num_turns == 0:
        return packed
    return _vmem_pallas_call(
        _make_kernel(num_turns, rule), packed, interpret)
