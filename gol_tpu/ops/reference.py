"""Independent numpy oracle used only to generate/verify golden fixtures.

Deliberately structured differently from the JAX kernel (explicit padded
window slicing rather than roll-sums) so a bug in one is unlikely to hide in
the other. Golden boards/counts produced by this module play the role of the
reference's committed `Local/check/` fixtures (SURVEY §4 notes they are
regenerable — GoL is deterministic).
"""

from __future__ import annotations

import numpy as np


def step_np(board01: np.ndarray) -> np.ndarray:
    """One torus turn on an (H, W) uint8 {0,1} board."""
    p = np.pad(board01, 1, mode="wrap")
    h, w = board01.shape
    counts = np.zeros((h, w), dtype=np.int32)
    for dy in (0, 1, 2):
        for dx in (0, 1, 2):
            if dy == 1 and dx == 1:
                continue
            counts += p[dy : dy + h, dx : dx + w]
    alive = board01 == 1
    nxt = np.where(
        alive, (counts == 2) | (counts == 3), counts == 3
    )
    return nxt.astype(np.uint8)


def run_turns_np(board01: np.ndarray, num_turns: int) -> np.ndarray:
    if board01.size and board01.max() > 1:
        # Passing the {0,255} PIXEL format here would sum 255s into the
        # neighbour counts and silently produce an all-dead "golden" —
        # the oracle must fail loudly, never fabricate fixtures.
        raise ValueError(
            f"oracle wants a {{0,1}} board, got max {board01.max()}")
    b = board01.copy()
    for _ in range(num_turns):
        b = step_np(b)
    return b
