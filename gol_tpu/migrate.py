"""Live run migration: the Rescale coordinator (PR 15).

Moves ONE fleet run from this member to another federation member with
no caller-visible errors, via a failure-atomic two-phase cutover run
inside the SOURCE member's server process:

    quiesce    - park the run (coherent device readback to the handle),
                 mark it migrating: control flags defer, destroy is
                 refused, reads keep serving from the frozen board
    checkpoint - synchronous durable per-run checkpoint (belt under the
                 transfer: if BOTH processes die, the federation's
                 adoption path resumes from this exact turn)
    transfer   - stream the board to the target over the ordinary wire
                 codec (CAP_PACKED frame) as ReceiveRun; the target
                 STAGES it — registered, hidden from ListRuns, never
                 auto-resumed
    resume     - CommitRun flips the staged copy live on the target
                 (queued for placement, or parked if the source run was
                 parked)
    redirect   - PinRun atomically re-points the router placement at
                 the target; the source retires its copy, relays any
                 deferred control flags to the new owner, and re-keys
                 viewers (broadcast end sentinel -> subscribers
                 reconnect through the router's new pin)

Rollback: any failure BEFORE the redirect pin lands unwinds in reverse
— destroy the staged/committed target copy (best effort, retried),
restore the source run to the exact state quiesce recorded — and the
migration reports status="rolled_back". Authority lives in the router
placement map and flips exactly once, at PinRun: before it, the source
copy is the one listed, routable copy (the target's is staged-hidden);
after it, the target's is, and the source answers stragglers with a
retryable "moved:" error until its dedupe-window peers drain. At no
instant are there zero or two routable copies.

GOL_CHAOS `migrate_fail=<phase>` injects a one-shot failure at any
phase boundary; `kill_member=<addr>@migrating` lets a harness SIGKILL
the source mid-migration (chaos.take_kill_member polls True while a
Rescale is in flight). docs/ARCHITECTURE.md "Live migration & elastic
resharding" is the narrative version with the phase diagram.

Env:

    GOL_MIGRATE_DEADLINE   total wall budget in seconds (default 30);
                           each member/router RPC gets the remainder
"""

from __future__ import annotations

import socket
import threading
import time
import uuid
from collections import deque
from typing import Optional

import numpy as np

from gol_tpu import chaos, wire
from gol_tpu.obs import audit as obs_audit
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import trace
from gol_tpu.obs.log import exception as obs_exception
from gol_tpu.obs.log import log as obs_log
from gol_tpu.utils.envcfg import env_float

DEADLINE_ENV = "GOL_MIGRATE_DEADLINE"
DEADLINE_DEFAULT_S = 30.0

# The cutover's phase names, in order — chaos specs and trace spans use
# them verbatim (GOL_CHAOS=migrate_fail=transfer).
PHASES = ("quiesce", "checkpoint", "transfer", "resume", "redirect")

# Client-visible cutover wall samples (resume+redirect slice) feeding
# the gol_migration_downtime_ms{q} gauges: bounded, process-wide.
_DOWNTIME_S: "deque[float]" = deque(maxlen=256)
_DOWNTIME_LOCK = threading.Lock()

# True while any Rescale is in flight on this process — the harness's
# kill_member@migrating poll (bench chaos leg) keys off the "migrating"
# field in Stats/healthz surfaces built from this.
_IN_FLIGHT = 0
_IN_FLIGHT_LOCK = threading.Lock()


class MigrationFailed(RuntimeError):
    """A phase failed; the cutover was rolled back to the source."""


def in_flight() -> int:
    """How many Rescale cutovers this process is coordinating now."""
    with _IN_FLIGHT_LOCK:
        return _IN_FLIGHT


def _publish_downtime(seconds: float) -> None:
    with _DOWNTIME_LOCK:
        _DOWNTIME_S.append(seconds)
        samples = sorted(_DOWNTIME_S)
    n = len(samples)
    for q, frac in zip(obs.SLO_QUANTILES, (0.50, 0.95, 0.99)):
        v = samples[min(n - 1, int(frac * n))]
        obs.MIGRATION_DOWNTIME_MS.labels(q=q).set(round(v * 1e3, 3))


def _rpc(addr: str, header: dict, frame=None,
         timeout: Optional[float] = None) -> dict:
    """One direct wire round trip to `addr` ("host:port"). Raises on
    transport failure or an error reply — phase code treats any raise
    as that phase failing."""
    host, _, port = addr.rpartition(":")
    sock = socket.create_connection((host or "127.0.0.1", int(port)),
                                    timeout=min(timeout or 5.0, 5.0))
    try:
        wire.enable_nodelay(sock)
        sock.settimeout(timeout)
        wire.send_msg(sock, header, frame=frame)
        resp, _ = wire.recv_msg(sock)
    finally:
        try:
            sock.close()
        except OSError:
            pass
    if resp.get("error") or not resp.get("ok", True):
        raise RuntimeError(
            f"{header.get('method')} to {addr}: "
            f"{resp.get('error', 'refused')}")
    return resp


def _chaos_gate(phase: str) -> None:
    if chaos.take_migrate_fail(phase):
        raise RuntimeError(f"chaos: migrate_fail at phase {phase!r}")


def _audit_phase(rid: str, target: str, phase: str) -> None:
    """One fleet-audit event per migration phase (PR 16) — queued for
    the next heartbeat snapshot, so phase history lands in the registry
    tier's durable gol-fleet-audit/1 log."""
    obs_audit.note("migrate", run_id=rid, target=target, phase=phase)


def rescale(server, run_id: str, target: str) -> dict:
    """Coordinate one live migration of `run_id` from `server`'s engine
    to the member advertised at `target` ("host:port" — also its
    federation member_id). Returns a summary record; raises
    MigrationFailed after a rollback, or the underlying error if even
    the rollback could not restore the source."""
    engine = server.engine
    if getattr(engine, "migrate_quiesce", None) is None:
        from gol_tpu.fleet.handles import FleetUnsupported

        raise FleetUnsupported(
            f"{type(engine).__name__} serves a single run; start the "
            "server with --fleet for Rescale")
    rid = str(run_id or "")
    target = str(target or "")
    if not target or ":" not in target:
        raise ValueError(f"Rescale needs a target member host:port, "
                         f"got {target!r}")
    # Unknown run BEFORE the self-target check: "run X is already on
    # me" about a run this member has never heard of would mask the
    # real problem (and the server's unknown-run branch is what turns
    # this KeyError into the retryable moved:/unknown answer).
    engine.resolve_run(rid)
    self_addr = getattr(server, "_self_addr", "") or ""
    if target == self_addr:
        raise ValueError(f"run {rid} is already on {target}")
    deadline = time.monotonic() + env_float(DEADLINE_ENV,
                                            DEADLINE_DEFAULT_S)

    def remaining() -> float:
        left = deadline - time.monotonic()
        if left <= 0:
            raise RuntimeError(
                f"migrate deadline ({DEADLINE_ENV}) exceeded")
        return left

    global _IN_FLIGHT
    with _IN_FLIGHT_LOCK:
        _IN_FLIGHT += 1
    # Every RPC this ATTEMPT sends carries a nonce in its req_id. The
    # ids must be unique per attempt, not per run: a run that migrates
    # to a member it has visited before (ping-pong, retry after a
    # coordinator death) would otherwise have its ReceiveRun/CommitRun
    # answered from that member's dedupe window — "ok" replayed, no
    # copy actually staged — and the source would then retire the only
    # real copy.
    nonce = uuid.uuid4().hex[:12]
    staged_on_target = False
    quiesced = None
    try:
        with trace.span("migrate", attrs={"run_id": rid,
                                          "target": target}) as root:
            # -- quiesce ------------------------------------------------
            with trace.span("migrate.quiesce"):
                _chaos_gate("quiesce")
                _audit_phase(rid, target, "quiesce")
                quiesced = engine.migrate_quiesce(rid)
            # -- checkpoint ---------------------------------------------
            with trace.span("migrate.checkpoint"):
                _chaos_gate("checkpoint")
                _audit_phase(rid, target, "checkpoint")
                engine.migrate_checkpoint(rid)
            # -- transfer -----------------------------------------------
            with trace.span("migrate.transfer"):
                _chaos_gate("transfer")
                _audit_phase(rid, target, "transfer")
                px = (quiesced["board"] *
                      np.uint8(255)).astype(np.uint8)
                frame = wire.encode_board(
                    px, frozenset({wire.CAP_PACKED}), binary=True)
                header = {
                    "method": "ReceiveRun", "run_id": rid,
                    "turn": quiesced["turn"],
                    "rule": quiesced["rule"],
                    "ckpt_every": quiesced["ckpt_every"],
                    "state": quiesced["state"],
                    "req_id": f"mig-{rid}-{nonce}-recv",
                    "caps": sorted(wire.local_caps()),
                }
                if quiesced["target_turn"] is not None:
                    header["target_turn"] = quiesced["target_turn"]
                if quiesced.get("journal_head"):
                    header["journal_head"] = quiesced["journal_head"]
                _rpc(target, header, frame=frame, timeout=remaining())
                staged_on_target = True
            # -- resume -------------------------------------------------
            t_cut = time.monotonic()
            with trace.span("migrate.resume"):
                _chaos_gate("resume")
                _audit_phase(rid, target, "resume")
                _rpc(target, {"method": "CommitRun", "run_id": rid,
                              "req_id": f"mig-{rid}-{nonce}-commit"},
                     timeout=remaining())
            # -- redirect -----------------------------------------------
            with trace.span("migrate.redirect"):
                _chaos_gate("redirect")
                _audit_phase(rid, target, "redirect")
                # Stragglers relayed to us before the pin flips get a
                # RETRYABLE "moved:" answer once our copy retires —
                # registered before anything can observe the removal.
                server.note_moved(rid, target)
                router = getattr(server, "_fed_router", "") or ""
                if router:
                    pin = {"method": "PinRun", "run_id": rid,
                           "member_id": target,
                           "ckpt_every": quiesced["ckpt_every"],
                           "req_id": f"mig-{rid}-{nonce}-pin"}
                    if quiesced["target_turn"] is not None:
                        pin["target_turn"] = quiesced["target_turn"]
                    _rpc(router, pin, timeout=remaining())
            downtime_s = time.monotonic() - t_cut
            # -- commit (source retire; past the point of no return) ----
            flags = engine.migrate_commit(rid)
            for flag in flags:
                try:
                    _rpc(target, {"method": "CFput", "flag": int(flag),
                                  "run_id": rid,
                                  "req_id": f"mig-{rid}-{nonce}-cf-{flag}",
                                  }, timeout=5.0)
                except Exception as e:
                    obs_exception("migrate.flag_relay_failed", e,
                                  run_id=rid, flag=flag)
            # Viewer re-key: per-viewer xrle bases die with the source
            # copy; broadcast subscribers get the end sentinel and
            # reconnect through the router's new pin (epoch-bump
            # keyframe on the target's stream).
            server.drop_run_viewers(
                rid, f"killed: run {rid} migrated to {target}")
            _publish_downtime(downtime_s)
            root.attrs["downtime_ms"] = round(downtime_s * 1e3, 3)
        obs.MIGRATIONS.labels(status="ok").inc()
        _audit_phase(rid, target, "ok")
        obs_log("migrate.ok", run_id=rid, target=target,
                turn=quiesced["turn"],
                downtime_ms=round(downtime_s * 1e3, 3),
                relayed_flags=len(flags))
        return {"run_id": rid, "target": target,
                "turn": int(quiesced["turn"]),
                "downtime_ms": round(downtime_s * 1e3, 3),
                "status": "ok"}
    except Exception as e:
        failed_phase = _rollback(engine, rid, target, staged_on_target,
                                 quiesced, e, nonce)
        raise MigrationFailed(
            f"migration of run {rid} to {target} rolled back "
            f"({failed_phase}): {e}") from e
    finally:
        with _IN_FLIGHT_LOCK:
            _IN_FLIGHT -= 1


def _rollback(engine, rid: str, target: str, staged_on_target: bool,
              quiesced, cause: Exception, nonce: str) -> str:
    """Unwind in reverse: destroy the target's staged/committed copy,
    then restore the source run to its pre-quiesce state. Meters
    rolled_back on success, error when the source could not be
    restored (the invariant breach worth paging on)."""
    obs_exception("migrate.failed", cause, run_id=rid, target=target)
    if staged_on_target:
        for attempt in (1, 2):
            try:
                _rpc(target, {"method": "DestroyRun", "run_id": rid,
                              "req_id": f"mig-{rid}-{nonce}-undo-"
                                        f"{attempt}"},
                     timeout=5.0)
                break
            except Exception as e:
                obs_exception("migrate.rollback_destroy_failed", e,
                              run_id=rid, target=target,
                              attempt=attempt)
                # Unknown-run means the copy never landed (or already
                # expired) — nothing to destroy.
                if "unknown run" in str(e):
                    break
                time.sleep(0.05)
    status = "rolled_back"
    try:
        if quiesced is not None:
            rec = engine.migrate_rollback(rid)
            if not rec.get("restored"):
                # Source handle gone mid-rollback (operator destroy is
                # refused while migrating, so this means the engine
                # died) — nothing to restore into.
                status = "error"
    except Exception as e:
        status = "error"
        obs_exception("migrate.rollback_failed", e, run_id=rid)
    obs.MIGRATIONS.labels(status=status).inc()
    _audit_phase(rid, target, status)
    obs_log("migrate.rolled_back", level="warning", run_id=rid,
            target=target, status=status,
            cause=f"{type(cause).__name__}: {cause}")
    return status
