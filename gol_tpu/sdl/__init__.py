from gol_tpu.sdl.loop import start
from gol_tpu.sdl.window import Window, sdl_available

__all__ = ["start", "Window", "sdl_available"]
