"""View/event loop — counterpart of reference `Local/sdl/loop.go:9-49`.

Consumes the events queue (CellFlipped/CellsFlipped → pixel flips,
TurnComplete → render, anything with a non-empty str() → printed as
`Completed Turns <n>  <event>`, matching `loop.go:42-44`), forwards
keyboard input (s/p/q/k) to the key_presses queue, and returns when the
event stream closes (`loop.go:31-34`).
"""

from __future__ import annotations

import queue
import sys
import threading
from typing import Optional

from gol_tpu import events as ev
from gol_tpu.params import Params
from gol_tpu.sdl.window import Window


def _stdin_key_reader(key_presses: "queue.Queue", stop: threading.Event):
    """Stdin reader thread: forwards s/p/q/k/c keystrokes. Terminal mode is
    owned by `start()` (set + restored there). select() gates every
    read(1) so the thread actually exits when `stop` is set — a reader
    parked in a blocking read would outlive its run and steal the user's
    next keystroke (or race a later `start()`'s reader for stdin)."""
    import select

    while not stop.is_set():
        try:
            ready, _, _ = select.select([sys.stdin], [], [], 0.2)
        except (OSError, ValueError):  # stdin closed under us
            return
        if not ready:
            continue
        ch = sys.stdin.read(1)
        if not ch:
            return
        if ch in ("s", "p", "q", "k", "c"):
            key_presses.put(ch)
        if ch in ("q", "k"):
            return


class _RawTerminal:
    """cbreak-mode guard: restores the user's terminal settings on exit
    even if the reader thread is still parked in read(1)."""

    def __init__(self) -> None:
        self._old = None
        self._fd = None

    def __enter__(self):
        if sys.stdin.isatty():
            import termios
            import tty

            self._fd = sys.stdin.fileno()
            self._old = termios.tcgetattr(self._fd)
            tty.setcbreak(self._fd)
        return self

    def __exit__(self, *exc):
        if self._old is not None:
            import termios

            termios.tcsetattr(self._fd, termios.TCSADRAIN, self._old)
        return False


def start(
    p: Params,
    events_q: "queue.Queue",
    key_presses: Optional["queue.Queue"] = None,
    window: Optional[Window] = None,
    headless: bool = False,
) -> None:
    """Blocks until the event stream closes (reference `sdl.Start`)."""
    win = None
    if not headless:
        win = window or Window(p.image_width, p.image_height)

    stop = threading.Event()
    term = _RawTerminal()
    if key_presses is not None and sys.stdin.isatty():
        term.__enter__()
        threading.Thread(
            target=_stdin_key_reader, args=(key_presses, stop), daemon=True
        ).start()

    try:
        while True:
            if win is not None:
                key = win.poll_event()
                if key == "quit":
                    key = "q"
                if key and key_presses is not None:
                    key_presses.put(key)
            try:
                e = events_q.get(timeout=0.05)
            except queue.Empty:
                continue
            if e is ev.CLOSE:
                return
            if isinstance(e, ev.CellFlipped) and win is not None:
                win.flip_pixel(*e.cell)
            elif isinstance(e, ev.CellsFlipped) and win is not None:
                for cell in e.cells:
                    win.flip_pixel(*cell)
            elif isinstance(e, ev.TurnComplete) and win is not None:
                win.render_frame(f"Completed Turns {e.completed_turns}")
            else:
                text = str(e)
                if text:
                    print(f"Completed Turns {e.completed_turns:<8}{text}")
    finally:
        stop.set()
        term.__exit__()
        if win is not None:
            win.close()
