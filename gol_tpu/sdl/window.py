"""Board window: SDL2 when the native library is present, ANSI terminal
renderer otherwise.

Counterpart of reference `Local/sdl/window.go:20-82` (cgo → libSDL2: window,
ARGB texture, SetPixel/FlipPixel/RenderFrame/PollEvent). The native path
binds libSDL2 directly via ctypes — same C library, no cgo shim needed. The
fallback renders two board rows per character line with Unicode half-blocks,
giving a live view on any terminal (parity with the reference's ASCII
renderer role, `Local/util/visualise.go`).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import sys
from typing import Optional

import numpy as np

_SDL_INIT_VIDEO = 0x20
_SDL_WINDOWPOS_CENTERED = 0x2FFF0000
_SDL_PIXELFORMAT_ARGB8888 = 0x16362004
_SDL_TEXTUREACCESS_STREAMING = 1
_SDL_QUIT = 0x100
_SDL_KEYDOWN = 0x300


class _SDL_Keysym(ctypes.Structure):
    """SDL_Keysym (SDL_keyboard.h): scancode, sym, mod, unused."""

    _fields_ = [
        ("scancode", ctypes.c_int32),
        ("sym", ctypes.c_int32),
        ("mod", ctypes.c_uint16),
        ("unused", ctypes.c_uint32),
    ]


class _SDL_KeyboardEvent(ctypes.Structure):
    """SDL_KeyboardEvent (SDL_events.h). Declared field-by-field so the
    compiler-computed offsets come from the ABI rules, not a hardcoded
    byte offset (r5 — VERDICT r4 #4: the old code cast offset 20 of an
    opaque buffer, which any struct-layout change or non-x86 platform
    would silently break)."""

    _fields_ = [
        ("type", ctypes.c_uint32),
        ("timestamp", ctypes.c_uint32),
        ("windowID", ctypes.c_uint32),
        ("state", ctypes.c_uint8),
        ("repeat", ctypes.c_uint8),
        ("padding2", ctypes.c_uint8),
        ("padding3", ctypes.c_uint8),
        ("keysym", _SDL_Keysym),
    ]


class _SDL_Event(ctypes.Union):
    """SDL_Event: the tag + the one member we decode, padded to 64 bytes
    (SDL2's union is 56; the extra headroom is harmless — SDL writes at
    most sizeof(SDL_Event) into the buffer we hand it)."""

    _fields_ = [
        ("type", ctypes.c_uint32),
        ("key", _SDL_KeyboardEvent),
        ("padding", ctypes.c_uint8 * 64),
    ]


def _load_sdl():
    name = ctypes.util.find_library("SDL2")
    if not name:
        return None
    try:
        return ctypes.CDLL(name)
    except OSError:
        return None


_SDL = _load_sdl()


def sdl_available() -> bool:
    return _SDL is not None


class Window:
    """FlipPixel/RenderFrame/PollEvent surface over SDL2 or a terminal."""

    def __init__(self, width: int, height: int, scale: int = 1) -> None:
        self.width, self.height = width, height
        self._pixels = np.zeros((height, width), dtype=bool)
        self._sdl = None
        if _SDL is not None and os.environ.get("GOL_HEADLESS", "") != "1":
            self._init_sdl(scale)

    # --- pixel ops (reference `window.go:68-82`) --------------------------

    def set_pixel(self, x: int, y: int, alive: bool) -> None:
        self._pixels[y % self.height, x % self.width] = alive

    def flip_pixel(self, x: int, y: int) -> None:
        self._pixels[y % self.height, x % self.width] ^= True

    def set_board(self, board01: np.ndarray) -> None:
        if board01.shape != (self.height, self.width):
            # The SDL render path hands SDL a buffer sized from
            # (height, width); a mismatched board would be a native
            # out-of-bounds read.
            raise ValueError(
                f"board {board01.shape} != window "
                f"({self.height}, {self.width})")
        self._pixels = board01.astype(bool)

    # --- rendering --------------------------------------------------------

    def render_frame(self, status: str = "") -> None:
        if self._sdl is not None:
            self._render_sdl()
        elif sys.stdout.isatty():
            self._render_ansi(status)

    def _render_ansi(self, status: str) -> None:
        from gol_tpu import native

        max_rows = 48 * 2
        max_cols = 160
        p = self._pixels[:max_rows, :max_cols]
        frame = native.render_halfblocks(p.astype(np.uint8) * 255)
        if frame is None:  # no native library: numpy glyph mapping
            if p.shape[0] % 2:
                p = np.vstack([p, np.zeros((1, p.shape[1]), dtype=bool)])
            top, bot = p[0::2], p[1::2]
            glyphs = np.array([" ", "▀", "▄", "█"])
            frame = "\n".join(
                "".join(row)
                for row in glyphs[top.astype(int) | (bot.astype(int) << 1)]
            ) + "\n"
        sys.stdout.write(
            "\x1b[H\x1b[2J" + frame + status + "\n"
        )
        sys.stdout.flush()

    def poll_event(self) -> Optional[str]:
        """Returns 'q'/'p'/'s'/'k' on keydown, 'quit' on window close.
        Decodes via the declared `_SDL_Event` union — field access, no
        hand-computed offsets (ref contract `Local/sdl/window.go:54-66`)."""
        if self._sdl is None:
            return None
        event = _SDL_Event()
        while _SDL.SDL_PollEvent(ctypes.byref(event)):
            if event.type == _SDL_QUIT:
                return "quit"
            if event.type == _SDL_KEYDOWN:
                sym = event.key.keysym.sym
                ch = chr(sym) if 0 < sym < 128 else ""
                if ch in "spqk":
                    return ch
        return None

    def close(self) -> None:
        if self._sdl is not None:
            _SDL.SDL_DestroyWindow(self._win)
            _SDL.SDL_Quit()
            self._sdl = None

    # --- SDL internals ----------------------------------------------------

    def _init_sdl(self, scale: int) -> None:
        """Bring up window + renderer + texture, or tear everything down
        and leave `_sdl` unset so the ANSI fallback takes over — a
        half-initialized chain (e.g. no usable render driver on a remote
        X display) must not leave a black frozen window."""
        if _SDL.SDL_Init(_SDL_INIT_VIDEO) != 0:
            return
        _SDL.SDL_CreateWindow.restype = ctypes.c_void_p
        self._win = _SDL.SDL_CreateWindow(
            b"gol_tpu",
            _SDL_WINDOWPOS_CENTERED,
            _SDL_WINDOWPOS_CENTERED,
            self.width * scale,
            self.height * scale,
            0,
        )
        if not self._win:
            _SDL.SDL_Quit()
            return
        _SDL.SDL_CreateRenderer.restype = ctypes.c_void_p
        self._ren = _SDL.SDL_CreateRenderer(
            ctypes.c_void_p(self._win), -1, 0
        )
        _SDL.SDL_CreateTexture.restype = ctypes.c_void_p
        self._tex = _SDL.SDL_CreateTexture(
            ctypes.c_void_p(self._ren),
            _SDL_PIXELFORMAT_ARGB8888,
            _SDL_TEXTUREACCESS_STREAMING,
            self.width,
            self.height,
        ) if self._ren else None
        if not self._ren or not self._tex:
            _SDL.SDL_DestroyWindow(ctypes.c_void_p(self._win))
            _SDL.SDL_Quit()
            self._win = self._ren = self._tex = None
            return
        self._sdl = _SDL

    def _render_sdl(self) -> None:
        argb = np.where(
            self._pixels, np.uint32(0xFFFFFFFF), np.uint32(0xFF000000)
        ).astype(np.uint32)
        buf = argb.tobytes()
        self._sdl.SDL_UpdateTexture(
            ctypes.c_void_p(self._tex), None, buf, self.width * 4
        )
        self._sdl.SDL_RenderClear(ctypes.c_void_p(self._ren))
        self._sdl.SDL_RenderCopy(
            ctypes.c_void_p(self._ren), ctypes.c_void_p(self._tex),
            None, None,
        )
        self._sdl.SDL_RenderPresent(ctypes.c_void_p(self._ren))
