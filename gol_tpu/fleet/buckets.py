"""Size buckets: how thousands of runs share a handful of programs.

A fleet that compiled one program per run would drown in trace/compile
churn (PR 4's `gol_compile_step_signatures_total` exists precisely to
catch that). Instead runs are binned into a few PADDED SIZE CLASSES
(default 512², 1024², 2048²): each bucket owns ONE batched device array
of packed words, shape (cap, hb, wb/32) uint32, and one jitted program
steps every slot of the batch in a single dispatch — the bit-plane
stencil of `ops/bitpack.py` operates on the trailing two axes, so the
leading slot axis batches for free.

Placement is the PERIODIC-TILING trick (`handles.fits_bucket`): a board
whose sides divide the bucket's is stamped as `np.tile` copies filling
the slot. GoL commutes with translations and a periodic state stays
periodic, so the bucket-torus evolution restricted to any board-sized
window is bit-identical to the board's own torus evolution — padding
costs capacity, never correctness (the parity test in test_fleet.py
asserts exactly this). Per-run alive = slot popcount / tiles, exact.

Boards that divide no configured class (e.g. the reference's 24×24 test
board arriving from a legacy client) get a PRIVATE bucket shaped
(h, lcm(w, 32)) — the legacy-parity guarantee outranks the shared-class
economy, and such buckets cost one extra signature each. Fleet-created
runs are instead rejected with reason "shape", keeping the compiled-
program set bounded by the configured classes.

Batch-shape stability: slot capacity is allocated in powers of two
(`slot_base`, growing ×2), so admitting a run into existing free
capacity reuses the compiled (cap, hb, wpb) program — zero new step
signatures, the no-recompile-churn witness. Growth retraces once per
doubling: O(log runs) signatures per bucket over the fleet's lifetime.

Mesh placement (PR 11): on a multi-device engine each bucket is placed
per `choose_placement` — BATCH-AXIS by default (the slot axis split over
a 1-D 'slots' mesh; embarrassingly parallel, zero halo traffic, the
per-slot popcount reduction stays shard-local), falling back to SPATIAL
row sharding (the `parallel/halo.py` ppermute machinery, batched over
slots) for big-board classes whose expected occupancy (`slot_base`)
can't put even one slot on every device. Batch placement keeps slot
capacity a power of two PER SHARD by flooring cap at the device count
(both pow2), so admission into free capacity still compiles nothing;
the device count and placement ride `signature_key`, keeping the
signature witness honest across placements. A slot gather
(`read_board`/`slot_words`/`evict`) indexes the slot axis away, so
checkpoints and quarantine readbacks stay bit-identical to the
single-device fleet.
"""

from __future__ import annotations

import functools
import math
import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu.fleet.handles import RunHandle, fits_bucket, tile_board
from gol_tpu.obs import devstats
from gol_tpu.ops.bitpack import (
    WORD_BITS,
    pack_np,
    packed_step,
    unpack_np,
    words_bytes_np,
)
from gol_tpu.parallel.mesh import ROWS_AXIS, batch_sharding
from gol_tpu.parallel.shmap import shard_map

# Bucket side lengths (square, word-aligned). Overridable per engine
# (GOL_FLEET_BUCKETS / constructor) — these are the paper-bench classes.
DEFAULT_BUCKET_SIZES = (512, 1024, 2048)

# Initial slot capacity per bucket; rounded up to a power of two.
DEFAULT_SLOT_BASE = 8

# Batch-axis placement needs at least this many EXPECTED slots per device
# (slot_base / devices) to be worth splitting the slot axis — below it the
# pow2 capacity floor would pad the batch with garbage slots just to feed
# the mesh. Overridable for policy tests via GOL_FLEET_MIN_SLOTS_PER_DEV.
DEFAULT_MIN_SLOTS_PER_DEVICE = 1


def min_slots_per_device() -> float:
    raw = os.environ.get("GOL_FLEET_MIN_SLOTS_PER_DEV")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_MIN_SLOTS_PER_DEVICE


def choose_placement(hb: int, wb: int, slot_base: int,
                     devices: int) -> str:
    """Per-bucket-class placement policy: 'single' | 'batch' | 'spatial'.

    'batch' (slot axis over the mesh) whenever the class's expected
    occupancy puts >= min_slots_per_device slots on every device —
    zero-halo-traffic, the near-linear regime. Big-board classes below
    that occupancy fall back to 'spatial' row sharding (halo exchange
    per turn) when the board's rows divide the mesh; classes that can
    do neither (occupancy too low AND rows indivisible — only private
    odd-shape buckets) keep 'batch', paying the capacity pad."""
    if devices <= 1:
        return "single"
    if slot_base / devices >= min_slots_per_device():
        return "batch"
    if hb % devices == 0:
        return "spatial"
    return "batch"


def cell_dtype(rule) -> str:
    """Logical cell dtype of a rule family — the bucket key's fourth
    element (PR 20). Binary families ("bit": life-like today; LtL would
    pack the same way) share the packed-words bucket machinery; a
    float-state (Lenia) board is "float32" and may NEVER land in a
    packed bucket, even if two families someday collide on rulestring
    text. Keying on dtype makes that a structural impossibility rather
    than a convention."""
    from gol_tpu.models.lenia import LeniaRule

    return "float32" if isinstance(rule, LeniaRule) else "bit"


def choose_bucket_size(h: int, w: int,
                       sizes: Sequence[int]) -> Optional[int]:
    """Smallest configured bucket class the (h, w) board tiles exactly,
    or None — fleet admission rejects with reason "shape"."""
    for size in sorted(sizes):
        if fits_bucket(h, w, size, size):
            return size
    return None


def private_shape(h: int, w: int) -> Tuple[int, int]:
    """The minimal word-aligned bucket an arbitrary board tiles: its
    own height, width padded to lcm(w, 32) — the legacy-parity escape
    hatch for boards outside every configured class."""
    return h, math.lcm(w, WORD_BITS)


@functools.lru_cache(maxsize=None)
def step_program(rule, turns: int):
    """The one compiled program per (rule, quantum): advance every slot
    of a (cap, hb, wpb) packed batch `turns` turns and return
    (words', per-slot popcount int32 (cap,)).

    jit retraces per distinct batch shape — which is exactly the
    signature economy the bucket layer engineers: shapes only change on
    a pow2 capacity growth. The popcount rides the same dispatch, so
    per-run alive telemetry costs no extra program launch."""

    def prog(words):
        def body(p, _):
            return packed_step(p, rule), None

        out, _ = lax.scan(body, words, None, length=turns)
        # int32 is exact: a full 2048² slot popcounts to 4.2M << 2³¹.
        alive = jnp.sum(lax.population_count(out), axis=(-1, -2),
                        dtype=jnp.int32)
        return out, alive

    return jax.jit(prog)


@functools.lru_cache(maxsize=None)
def spatial_step_program(rule, turns: int, mesh: Mesh):
    """The spatial-fallback program for big-board bucket classes: every
    slot's ROWS split over the mesh, one `shard_map` + ppermute ring halo
    exchange per turn (`parallel/halo.batched_packed_local_step` — the
    PR-9 machinery batched over slots), per-slot popcounts as a
    shard-local partial sum + `lax.psum` sharded reduction riding the
    same dispatch. Same (words) -> (words', alive) contract as
    `step_program`."""
    from gol_tpu.parallel.halo import batched_packed_local_step

    n_shards = mesh.shape[ROWS_AXIS]
    spec = P(None, ROWS_AXIS, None)

    def prog(words):
        @functools.partial(
            shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, P(None)),
        )
        def run_local(local):
            def body(p, _):
                return batched_packed_local_step(p, n_shards, rule), None

            out, _ = lax.scan(body, local, None, length=turns)
            alive = jnp.sum(lax.population_count(out), axis=(-1, -2),
                            dtype=jnp.int32)
            return out, lax.psum(alive, ROWS_AXIS)

        return run_local(words)

    return jax.jit(prog)


def board_to_words(board01: np.ndarray) -> np.ndarray:
    """{0,1} (h, w) board -> host packed words (h, ceil(w/32)) '<u4'."""
    return pack_np(board01).view("<u4")


def words_to_board(words: np.ndarray, h: int, w: int) -> np.ndarray:
    """Inverse of board_to_words (host-side)."""
    return unpack_np(words_bytes_np(np.asarray(words)), h, w)


class Bucket:
    """One padded size class: a batched device array plus its slot map.

    Mutated only under the fleet engine's scheduling lock (placement,
    stamping, dispatch all happen there), so no lock lives here."""

    def __init__(self, hb: int, wb: int, rule,
                 slot_base: int = DEFAULT_SLOT_BASE,
                 mesh: Optional[Mesh] = None,
                 placement: str = "single") -> None:
        if wb % WORD_BITS:
            raise ValueError(f"bucket width {wb} not word-aligned")
        self.hb = int(hb)
        self.wb = int(wb)
        self.wpb = self.wb // WORD_BITS
        self.rule = rule
        # A 1-device mesh is the single-device fleet: no sharding, the
        # exact PR-7 array/program path (protects the gated single-device
        # baselines by construction).
        if mesh is None or int(mesh.size) <= 1:
            mesh, placement = None, "single"
        self.mesh = mesh
        self.placement = placement
        self.devices = int(mesh.size) if mesh is not None else 1
        if placement == "batch":
            self._sharding: Optional[NamedSharding] = batch_sharding(mesh)
        elif placement == "spatial":
            if self.hb % self.devices:
                raise ValueError(
                    f"spatial bucket: {self.hb} rows not divisible by "
                    f"{self.devices} devices")
            self._sharding = NamedSharding(mesh, P(None, ROWS_AXIS, None))
        else:
            self._sharding = None
        cap = 1
        # Batch placement floors capacity at the device count so every
        # shard holds a pow2 slot count (cap and devices are both pow2).
        floor = max(1, slot_base,
                    self.devices if placement == "batch" else 1)
        while cap < floor:
            cap *= 2
        self.words = self._shard(
            jnp.zeros((cap, self.hb, self.wpb), dtype=jnp.uint32))
        self.slots: List[Optional[RunHandle]] = [None] * cap
        self.free: List[int] = list(range(cap - 1, -1, -1))
        # Round-robin bookkeeping the fairness test reads.
        self.dispatches = 0
        self.turns_served = 0

    def _shard(self, arr: jax.Array) -> jax.Array:
        """Pin `arr` to this bucket's placement (a no-op handle reuse
        when it already matches, and the identity on a single device)."""
        if self._sharding is None:
            return arr
        return jax.device_put(arr, self._sharding)

    # ------------------------------------------------------------ slots

    @property
    def cap(self) -> int:
        return len(self.slots)

    @property
    def occupied(self) -> int:
        return self.cap - len(self.free)

    def handles(self) -> List[RunHandle]:
        return [h for h in self.slots if h is not None]

    def active_count(self) -> int:
        return sum(1 for h in self.slots if h is not None and h.active)

    def _grow(self) -> None:
        """Double capacity, preserving resident slots. One retrace per
        doubling — the bounded, deliberate kind of signature churn."""
        new_cap = self.cap * 2
        grown = self._shard(
            jnp.zeros((new_cap, self.hb, self.wpb), dtype=jnp.uint32))
        self.words = self._shard(grown.at[: self.cap].set(self.words))
        self.free.extend(range(new_cap - 1, self.cap - 1, -1))
        self.slots.extend([None] * self.cap)

    def place(self, handle: RunHandle, board01: np.ndarray) -> int:
        """Stamp a board into a free slot (tiled to fill it); returns
        the slot index. Grows capacity ×2 when full."""
        if not self.free:
            self._grow()
        slot = self.free.pop()
        self.slots[slot] = handle
        self.stamp(slot, board01)
        return slot

    def stamp(self, slot: int, board01: np.ndarray) -> None:
        """(Re)write a slot's device words from a host {0,1} board —
        placement, reseed, and pause-resume all land here."""
        tiled = tile_board(np.asarray(board01, dtype=np.uint8),
                           self.hb, self.wb)
        host = np.ascontiguousarray(board_to_words(tiled))
        self.words = self._shard(self.words.at[slot].set(jnp.asarray(host)))

    def read_board(self, slot: int, h: int, w: int) -> np.ndarray:
        """Host {0,1} board of a slot: device readback of the slot's
        words, cropped to the run's own (h, w) window of the tiling."""
        words = np.asarray(self.words[slot])  # (hb, wpb) — device sync
        cells = words_to_board(words, self.hb, self.wb)
        return np.ascontiguousarray(cells[:h, :w])

    def slot_words(self, slot: int):
        """The slot's packed device words (hb, wpb) — a cheap device
        slice handle for async checkpoint submission."""
        return self.words[slot]

    def evict(self, slot: int, h: int, w: int) -> np.ndarray:
        """Free a slot, returning its final cropped board. The words
        stay in the device array (stepping garbage is harmless and
        keeps the batch shape); the slot just becomes placeable."""
        board = self.read_board(slot, h, w)
        self.slots[slot] = None
        self.free.append(slot)
        return board

    def release(self, slot: int) -> None:
        """Free a slot WITHOUT reading its board back — the quarantine
        eviction: a faulted slot's contents are untrusted by definition
        (and after a step exception the device array itself may be
        unusable), so nothing is salvaged from it."""
        self.slots[slot] = None
        if slot not in self.free:
            self.free.append(slot)

    def rebuild(self) -> None:
        """Recreate the device array from scratch and restamp every
        still-placed handle from its host `frozen` board. The step-
        exception recovery path: after a dispatch raised, the old
        `self.words` may hold a poisoned or unusable buffer; paused/
        parked residents have authoritative host copies, and faulted
        actives were released before this call."""
        self.words = self._shard(
            jnp.zeros((self.cap, self.hb, self.wpb), dtype=jnp.uint32))
        for slot, h in enumerate(self.slots):
            if h is not None and h.frozen is not None:
                self.stamp(slot, h.frozen)

    # --------------------------------------------------------- dispatch

    def signature_key(self, turns: int, fuse: int = 1) -> tuple:
        # Placement and device count are part of the compiled-program
        # identity: jit caches per input sharding, so a 4-way batch
        # program is a different executable than the 1-device one and
        # must count as a different signature for the witness to stay
        # honest. The fuse depth rides the key the same way — a fused
        # fleet runs a (turns × fuse)-deep scan, a different executable
        # — while admit-into-capacity still compiles nothing: slot
        # admission changes neither the batch shape nor (turns, fuse).
        return ("fleet", self.cap, self.hb, self.wpb, turns,
                self.rule.rulestring, self.placement, self.devices, fuse)

    def dispatch(self, turns: int, fuse: int = 1):
        """One serving quantum: advance every slot `turns × fuse` turns
        in a single device dispatch. Returns the per-slot popcount
        DEVICE array — the caller decides when to sync (that sync is
        the fleet's device-wait measurement point).

        Batch placement needs no bespoke program: `self.words` carries
        the slots-axis NamedSharding, and jit (pjit) propagates it
        through the same `step_program` — the scan stays elementwise on
        each device's slot block and the popcount reduction is over
        unsharded trailing axes, so the compiled SPMD program moves zero
        bytes between devices. Spatial placement dispatches the
        shard_map halo program instead.

        Temporal fusion at this tier is dispatch-granularity: the
        bucket scan already keeps the batch device-resident for the
        whole quantum, so fuse-k multiplies the turns one program
        advances per dispatch — k× fewer popcount round trips and
        program launches per turn, per-slot popcounts still riding the
        (now deeper) dispatch."""
        total = turns * max(1, fuse)
        devstats.note_signature(self.signature_key(turns, max(1, fuse)))
        if self.placement == "spatial":
            prog = spatial_step_program(self.rule, total, self.mesh)
        else:
            prog = step_program(self.rule, total)
        self.words, alive = prog(self.words)
        self.dispatches += 1
        self.turns_served += total
        return alive
