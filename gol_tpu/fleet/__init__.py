"""Fleet serving: batched multi-run engine (PR 7).

Layout:

  * `handles`   — RunHandle + the run-surface contract every engine
                  implements (`SingleRunSurface` for the single-run
                  engines); pure data, imports nothing heavy.
  * `admission` — device-memory budgeting: admit / queue / reject.
  * `buckets`   — padded size classes; one batched packed-stencil
                  program per (shape, rule, quantum).
  * `engine`    — FleetEngine (the scheduler loop) + RunView.

`FleetEngine` is exported LAZILY: `gol_tpu.engine` imports
`gol_tpu.fleet.handles` (for the SingleRunSurface mixin), which
triggers this package __init__ — an eager `from .engine import
FleetEngine` here would import `fleet.engine`, which imports
`gol_tpu.engine` right back, mid-initialization. The module
__getattr__ defers that edge until someone actually asks for the
fleet engine, by which point `gol_tpu.engine` is fully loaded.
"""

from gol_tpu.fleet.admission import AdmissionController, run_cost
from gol_tpu.fleet.handles import (
    LEGACY_RUN_ID,
    RUN_STATES,
    FleetUnsupported,
    RunHandle,
    SingleRunSurface,
    fits_bucket,
    valid_run_id,
)

__all__ = [
    "AdmissionController",
    "run_cost",
    "LEGACY_RUN_ID",
    "RUN_STATES",
    "FleetUnsupported",
    "RunHandle",
    "SingleRunSurface",
    "fits_bucket",
    "valid_run_id",
    "FleetEngine",
    "RunView",
]


def __getattr__(name: str):
    if name in ("FleetEngine", "RunView"):
        from gol_tpu.fleet import engine as _engine

        return getattr(_engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
