"""Admission control: how many runs the fleet may hold resident.

Capacity is a DEVICE-MEMORY budget, not a run count: a 2048² board
costs 16× a 512² one, so counting runs would let a handful of large
boards OOM the device while rejecting thousands of small ones. The
budget resolves, in order:

    GOL_FLEET_MEM_BUDGET          explicit byte budget (tests, ops)
    devstats.poll_device_memory() GOL_FLEET_MEM_FRACTION (default 0.5)
                                  of the summed per-device limit_bytes
    DEFAULT_BUDGET_BYTES          256 MiB × placement devices — backends
                                  that report no memory stats (CPU hosts)

Each resident run is charged `run_cost(hb, wpb)` = its bucket slot's
packed bytes × COST_FACTOR (3: resident state + the stepped copy jax
materializes per dispatch + compiler scratch; deliberately
conservative). GOL_FLEET_MAX_RUNS (default 4096) additionally bounds
the run COUNT — per-run host bookkeeping (handles, flag queues,
checkpoint writers) is not free even when boards are tiny.

Beyond capacity a CreateRun is REJECTED by default (the caller gets a
diagnosable error naming the reason) or, with `queue=true`, parked in
a bounded FIFO (GOL_FLEET_QUEUE_MAX, default 1024) that drains as
resident runs are removed. Every decision is metered:
`gol_runs_admitted_total`, `gol_runs_rejected_total{reason}`,
`gol_runs_resident`.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from gol_tpu.obs import catalog as obs
from gol_tpu.utils.envcfg import env_float, env_int

MEM_BUDGET_ENV = "GOL_FLEET_MEM_BUDGET"      # bytes; overrides probing
MEM_FRACTION_ENV = "GOL_FLEET_MEM_FRACTION"  # of device limit_bytes
MAX_RUNS_ENV = "GOL_FLEET_MAX_RUNS"
QUEUE_MAX_ENV = "GOL_FLEET_QUEUE_MAX"

DEFAULT_BUDGET_BYTES = 256 << 20
DEFAULT_MEM_FRACTION = 0.5
DEFAULT_MAX_RUNS = 4096
DEFAULT_QUEUE_MAX = 1024

# Bytes charged per packed state byte: the resident bucket slot, the
# stepped output array alive during each dispatch, and headroom for
# XLA scratch/fusion temporaries.
COST_FACTOR = 3


def run_cost(hb: int, wpb: int) -> int:
    """Admission charge in bytes for one slot of an (hb, wpb) bucket."""
    return int(hb) * int(wpb) * 4 * COST_FACTOR


class AdmissionController:
    """Tracks committed bytes/runs and decides admit/queue/reject."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 max_runs: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 devices: int = 1) -> None:
        self._lock = threading.Lock()
        self._budget = budget_bytes
        # Placement-mesh width (PR 11): the DEFAULT budget scales as
        # devices × per-device bytes — a bucket sharded over 8 devices
        # holds 1/8th of its batch on each. Explicit budgets (arg or
        # GOL_FLEET_MEM_BUDGET) stay ABSOLUTE: tests and operators that
        # pin a byte count mean that byte count; and the probed path
        # already sums per-device limit_bytes, so it needs no scaling.
        self.devices = max(1, int(devices))
        self.max_runs = (max_runs if max_runs is not None
                         else env_int(MAX_RUNS_ENV, DEFAULT_MAX_RUNS,
                                      minimum=1))
        self.queue_max = (queue_max if queue_max is not None
                          else env_int(QUEUE_MAX_ENV, DEFAULT_QUEUE_MAX,
                                       minimum=0))
        self.committed_bytes = 0
        self.resident_runs = 0
        self.queued_runs = 0
        # Promotion waits (seconds) since the last drain — bounded by
        # the queue itself (every entry was a queued run).
        self._queue_waits: list = []

    # ----------------------------------------------------------- budget

    def budget_bytes(self) -> int:
        """Resolve (and cache) the byte budget."""
        if self._budget is not None:
            return self._budget
        env_budget = env_int(MEM_BUDGET_ENV, 0, minimum=0)
        if env_budget:
            self._budget = env_budget
            return self._budget
        budget = DEFAULT_BUDGET_BYTES * self.devices
        try:
            from gol_tpu.obs import devstats

            snap = devstats.poll_device_memory()
            if snap and snap.get("supported"):
                limits = [d.get("limit_bytes") or 0
                          for d in (snap.get("per_device") or {}).values()]
                total = sum(int(x) for x in limits if x)
                if total > 0:
                    frac = env_float(MEM_FRACTION_ENV,
                                     DEFAULT_MEM_FRACTION)
                    budget = int(total * min(max(frac, 0.01), 1.0))
        except Exception:
            pass  # no device runtime: keep the conservative default
        self._budget = budget
        return budget

    # -------------------------------------------------------- decisions

    def try_admit(self, cost: int) -> Tuple[bool, Optional[str]]:
        """Charge `cost` bytes for a new resident run; (ok, reason)."""
        with self._lock:
            if self.resident_runs >= self.max_runs:
                return False, "max_runs"
            if self.committed_bytes + cost > self.budget_bytes():
                return False, "memory"
            self.committed_bytes += cost
            self.resident_runs += 1
        obs.RUNS_ADMITTED.inc()
        obs.RUNS_RESIDENT.set(self.resident_runs)
        return True, None

    def try_enqueue(self) -> Tuple[bool, Optional[str]]:
        with self._lock:
            if self.queued_runs >= self.queue_max:
                return False, "queue_full"
            self.queued_runs += 1
        return True, None

    def dequeue(self, waited_s: Optional[float] = None) -> None:
        """A run left the wait queue. `waited_s` (enqueue -> now) feeds
        the queue-wait SLO aggregates; abort/removal dequeues pass
        None — only real promotions measure the wait."""
        with self._lock:
            self.queued_runs = max(0, self.queued_runs - 1)
            if waited_s is not None:
                self._queue_waits.append(float(waited_s))

    def drain_queue_waits(self) -> list:
        """Hand the accumulated promotion waits (seconds) to the caller
        and clear them — the fleet loop's batched flush feeds these to
        the gol_fleet_queue_wait_ms estimator, so the hot path never
        touches an estimator lock."""
        with self._lock:
            out, self._queue_waits = self._queue_waits, []
        return out

    def release(self, cost: int) -> None:
        """Return a removed run's charge to the budget."""
        with self._lock:
            self.committed_bytes = max(0, self.committed_bytes - cost)
            self.resident_runs = max(0, self.resident_runs - 1)
        obs.RUNS_RESIDENT.set(self.resident_runs)

    def reject(self, reason: str) -> None:
        """Meter a rejection (shape/rule checks call this too, so the
        counter covers every CreateRun that did not admit)."""
        obs.RUNS_REJECTED.labels(reason=obs.run_reject_label(reason)).inc()

    def summary(self) -> dict:
        with self._lock:
            return {
                "resident": self.resident_runs,
                "queued": self.queued_runs,
                "committed_bytes": self.committed_bytes,
                "budget_bytes": self.budget_bytes(),
                "max_runs": self.max_runs,
                "devices": self.devices,
            }
