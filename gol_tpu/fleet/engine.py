"""Fleet engine: thousands of resident runs, one device dispatch.

The reference broker serves exactly one board; the ROADMAP's "millions
of users" north star is hordes of SMALL boards. Per the Casper/SARIS
lesson (PAPERS.md — stencil cost is data movement and dispatch
overhead, not FLOPs), the win is amortizing the per-quantum serving
cost (dispatch, sync, flag service, telemetry) across many boards:
runs are binned into padded size buckets (`fleet/buckets.py`), each
stepped as ONE batched packed-stencil program per serving quantum.

Scheduling: a single loop thread round-robins the non-empty buckets,
dispatching a fixed quantum of GOL_FLEET_CHUNK turns per visit. The
quantum is deliberately SMALL (default 8): it bounds every run's flag
latency to one rotation and keeps the serving fair — throughput comes
from the batch axis, not from long chunks. Between dispatches the loop
services per-run control flags (the same pause/quit/kill semantics as
`ControlFlagProtocol`, per handle), admissions, reseeds, and target
completions; a run whose remaining turns are smaller than the quantum
is trimmed with a single-slot scan so targets are hit EXACTLY.

Pause/park freeze mechanics: the batch steps every slot every quantum
(masking individual slots would change the compiled program), so a
paused or parked run's authoritative board is COPIED to the handle
(`frozen`) and the slot steps on as garbage; resume restamps the slot.
State never leaks: readers always prefer `frozen` when set.

Legacy contract: the engine itself is the run surface for run "run0" —
capability-less peers that never send a run_id get bit-identical
single-run behaviour (`server_distributor`/`get_world`/flags/ckpt all
route to the legacy handle), and the legacy run bypasses admission (it
predates the quota; fleet-created runs are the ones policed).
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gol_tpu import journal as journal_mod
from gol_tpu.engine import (
    CKPT_ENV,
    ControlFlagProtocol,
    EngineBusy,
    EngineKilled,
    FLAG_KILL,
    FLAG_PAUSE,
    FLAG_QUIT,
    view_factor,
)
from gol_tpu.fleet.admission import AdmissionController, run_cost
from gol_tpu.fleet.buckets import (
    Bucket,
    DEFAULT_BUCKET_SIZES,
    DEFAULT_SLOT_BASE,
    board_to_words,
    cell_dtype,
    choose_bucket_size,
    choose_placement,
    private_shape,
    words_to_board,
)
from gol_tpu.fleet.handles import (
    LEGACY_RUN_ID,
    FleetUnsupported,
    RunHandle,
    crop_alive,
    tiles_for,
    valid_run_id,
)
from gol_tpu.models.lifelike import CONWAY, LifeLikeRule
from gol_tpu.obs import audit as obs_audit
from gol_tpu.obs import catalog as obs
from gol_tpu.obs import devstats as obs_devstats
from gol_tpu.obs import slo as obs_slo
from gol_tpu.obs import timeline as obs_timeline
from gol_tpu.obs import usage as obs_usage
from gol_tpu.obs.log import exception as obs_exception
from gol_tpu.obs.log import log as obs_log
from gol_tpu.ops.bitpack import WORD_BITS, packed_run_turns
from gol_tpu.parallel.mesh import make_batch_mesh, make_mesh, mesh_geometry
from gol_tpu.utils.envcfg import env_float, env_int

BUCKETS_ENV = "GOL_FLEET_BUCKETS"     # csv of square class sides
CHUNK_ENV = "GOL_FLEET_CHUNK"         # serving quantum in turns
SLOT_BASE_ENV = "GOL_FLEET_SLOT_BASE"  # initial slots per bucket
MESH_DEVICES_ENV = "GOL_FLEET_MESH_DEVICES"  # placement mesh width
DEFAULT_CHUNK = 8

METRICS_FLUSH_SECONDS = 0.5  # same batched-flush cadence as engine.py

# How long create_run/load_checkpoint wait for the loop to place a run.
_PLACE_TIMEOUT_S = 60.0

# Quarantine auto-restore policy: a faulted run is re-seeded from its
# last per-run checkpoint at most TRIES times, spaced by exponential
# backoff starting at BACKOFF seconds. Past the cap the run stays
# "quarantined" until an operator destroys (or reseeds) it — a board
# that faults repeatedly must never oscillate back into the shared
# batched dispatch.
QUARANTINE_TRIES_ENV = "GOL_QUARANTINE_TRIES"
QUARANTINE_TRIES_DEFAULT = 3
QUARANTINE_BACKOFF_ENV = "GOL_QUARANTINE_BACKOFF"
QUARANTINE_BACKOFF_DEFAULT_S = 0.5

# A staged migration import whose coordinator died (source process
# killed mid-cutover) must not hold admission budget forever: the
# fleet loop expires it after this long. Any live cutover commits or
# rolls it back within GOL_MIGRATE_DEADLINE, well before this fires.
IMPORT_STALE_ENV = "GOL_MIGRATE_STALE"
IMPORT_STALE_DEFAULT_S = 60.0


def _parse_sizes(raw: str) -> Tuple[int, ...]:
    sizes = tuple(int(s) for s in raw.split(",") if s.strip())
    if not sizes or any(s <= 0 or s % WORD_BITS for s in sizes):
        raise ValueError(f"bad {BUCKETS_ENV} value {raw!r}")
    return sizes


def _soup(run_id: str, h: int, w: int) -> np.ndarray:
    """Deterministic random seed board for seedless CreateRun: keyed by
    run_id via crc32 (hash() is salted per process and would break
    create-then-reattach reproducibility)."""
    rng = np.random.default_rng(zlib.crc32(run_id.encode("utf-8")))
    return (rng.random((h, w)) < 0.3).astype(np.uint8)


class FleetEngine(ControlFlagProtocol):
    """Batched multi-run engine behind the single-run engine surface."""

    frames_diffable = True
    binary_pixels = True  # life-like only: snapshots are strict {0,255}
    run_id = LEGACY_RUN_ID

    def __init__(
        self,
        rule=CONWAY,
        bucket_sizes: Optional[Sequence[int]] = None,
        chunk_turns: Optional[int] = None,
        slot_base: Optional[int] = None,
        admission: Optional[AdmissionController] = None,
        devices=None,
    ) -> None:
        if not isinstance(rule, LifeLikeRule):
            raise ValueError(
                "fleet engine batches the packed life-like stencil; "
                f"rule {getattr(rule, 'rulestring', rule)!r} unsupported")
        import jax

        self._rule = rule
        # Placement devices (PR 11): the mesh the fleet actually shards
        # buckets over — NOT jax.device_count() (an unsharded dispatch
        # runs on ONE device no matter how many exist; stats and bench
        # records must stamp the real placement). Explicit `devices`
        # wins; else GOL_FLEET_MESH_DEVICES takes the first N visible
        # devices; else the fleet stays single-device.
        if devices is not None:
            self._devices = list(devices)
        else:
            want = env_int(MESH_DEVICES_ENV, 0, minimum=0)
            self._devices = list(jax.devices())[: want or 1]
        if not self._devices:
            raise ValueError("fleet engine needs at least one device")
        self._mesh_batch = (make_batch_mesh(devices=self._devices)
                            if len(self._devices) > 1 else None)
        if self._mesh_batch is not None:
            obs_devstats.note_mesh(mesh_geometry(self._mesh_batch))
        obs.FLEET_MESH_DEVICES.set(len(self._devices))
        if bucket_sizes is not None:
            sizes = tuple(int(s) for s in bucket_sizes)
        else:
            sizes = _parse_sizes(
                os.environ.get(BUCKETS_ENV, "") or
                ",".join(str(s) for s in DEFAULT_BUCKET_SIZES))
        if any(s <= 0 or s % WORD_BITS for s in sizes):
            raise ValueError(f"bucket sizes must be word-aligned: {sizes}")
        self.bucket_sizes = tuple(sorted(sizes))
        self.chunk_turns = int(chunk_turns) if chunk_turns else env_int(
            CHUNK_ENV, DEFAULT_CHUNK, minimum=1)
        # Temporal fusion (GOL_FUSE_K): every serving quantum advances
        # chunk_turns × fuse_k turns in ONE bucket dispatch — k× fewer
        # popcount syncs and program launches per turn. Resolved once at
        # construction so the compiled-program set stays fixed for the
        # engine's lifetime (auto/0 means unfused here: the bucket scan
        # has no adaptive depth to keep).
        from gol_tpu.ops.fused import configured_fuse_k

        self.fuse_k = max(1, configured_fuse_k())
        obs_devstats.note_fuse(self.fuse_k)
        self.slot_base = int(slot_base) if slot_base else env_int(
            SLOT_BASE_ENV, DEFAULT_SLOT_BASE, minimum=1)
        self.admission = admission or AdmissionController(
            devices=len(self._devices))
        # Shared checkpoint writer pool (PR 11): a bounded worker set
        # serves every run's cadence checkpoints round-robin instead of
        # one lazy thread per resident run. Created on first use.
        self._ckpt_pool = None

        # ControlFlagProtocol state (legacy flags stash until run0
        # exists; then flags go straight to the handle's queue).
        self._flags: "queue_mod.Queue[int]" = queue_mod.Queue()
        self._killed = False
        self._abort = threading.Event()
        self._state_lock = threading.RLock()
        self._running = False
        self._run_token: Optional[str] = None
        self._turn = 0
        self._alive_pub: Optional[Tuple[int, int]] = None

        # Fleet scheduling state: one lock guards runs/buckets/queues;
        # the loop condition-waits on it when idle.
        self._fleet_lock = threading.RLock()
        self._wake = threading.Condition(self._fleet_lock)
        self._runs: Dict[str, RunHandle] = {}
        self._buckets: Dict[tuple, Bucket] = {}
        self._rr: deque = deque()          # bucket keys, rotation order
        self._placeq: List[RunHandle] = []  # admitted, awaiting a slot
        self._waitq: deque = deque()        # beyond capacity, queued
        self._run_seq = 0
        self._loop_thread: Optional[threading.Thread] = None
        # Broadcast publish hook: poked once per serving quantum (same
        # contract as Engine._bcast_notify — cheap, never raises).
        self._bcast_notify = None

        # Telemetry (legacy stats keys + the fleet bench counters).
        self._turns_per_s = 0.0
        self._chunk_overhead_us = 0.0
        self._board_turns = 0        # per-run turns retired, summed
        self._cell_updates = 0       # board cells x turns retired
        self._dispatches = 0
        self._latency_samples: deque = deque(maxlen=8192)
        # SLO aggregates (PR 8): bounded-memory log-bucket estimators,
        # touched ONLY at the batched flush cadence (never per quantum).
        self._quantum_est: Dict[str, obs_slo.LogBucketEstimator] = {}
        self._queue_wait_est = obs_slo.LogBucketEstimator()

    # ------------------------------------------------------ run surface

    def resolve_run(self, run_id: Optional[str] = None):
        """None/""/"run0" -> the engine itself (the legacy surface);
        a fleet run_id -> a RunView bound to that handle."""
        if run_id in (None, "", LEGACY_RUN_ID):
            return self
        with self._fleet_lock:
            handle = self._runs.get(run_id)
        if handle is None:
            raise KeyError(f"unknown run {run_id!r}")
        return RunView(self, handle)

    def list_runs(self) -> list:
        # Staged (un-committed) migration imports are hidden: until
        # CommitRun flips authority, the SOURCE member's copy is the
        # one listed copy fleet-wide — never zero, never two.
        with self._fleet_lock:
            return [h.describe() for h in sorted(
                self._runs.values(), key=lambda h: h.created_s)
                if h.migrating is None
                or not h.migrating.startswith("staged")]

    def runs_summary(self) -> dict:
        with self._fleet_lock:
            by_state: Dict[str, int] = {}
            for h in self._runs.values():
                by_state[h.state] = by_state.get(h.state, 0) + 1
            return {
                "resident": by_state.get("resident", 0),
                "queued": by_state.get("queued", 0),
                "parked": by_state.get("parked", 0),
                "quarantined": by_state.get("quarantined", 0),
                "total": len(self._runs),
                "engine": "FleetEngine",
            }

    def describe_run(self) -> dict:
        with self._fleet_lock:
            h = self._runs.get(LEGACY_RUN_ID)
            if h is not None:
                return h.describe()
        return {"run_id": LEGACY_RUN_ID, "state": "queued", "board": None,
                "rule": self._rule.rulestring, "turn": 0, "alive": None,
                "alive_turn": None, "paused": False, "bucket": None,
                "viewers": 0, "ckpt_every": 0, "target_turn": None}

    # -------------------------------------------------------- admission

    def create_run(self, h: int, w: int, board: Optional[np.ndarray] = None,
                   run_id: Optional[str] = None, rule=None,
                   ckpt_every: int = 0,
                   target_turn: Optional[int] = None,
                   queue: bool = False, wait: bool = True) -> dict:
        """Admit + place a new run; returns its describe() record.

        Rejections raise RuntimeError("admission rejected: <reason>")
        after metering `gol_runs_rejected_total{reason}`; with
        `queue=True` a capacity rejection parks the request in the
        bounded wait queue instead (state "queued" in the returned
        record — it becomes resident when capacity frees).
        `wait=False` skips blocking on device placement (the record
        comes back "queued"; the loop drains the whole placement queue
        in one service pass — how a bench admits hundreds of runs
        without serializing on the serving quantum)."""
        self._check_alive()
        run_rule = self._resolve_rule(rule)
        h, w = int(h), int(w)
        if run_id is None:
            run_id = self._next_run_id()
        elif not valid_run_id(run_id) or run_id == LEGACY_RUN_ID:
            self.admission.reject("run_id")
            raise RuntimeError("admission rejected: run_id")
        size = choose_bucket_size(h, w, self.bucket_sizes)
        if size is None:
            self.admission.reject("shape")
            raise RuntimeError(
                "admission rejected: shape (board sides must divide a "
                f"bucket class {self.bucket_sizes})")
        derived = board is None
        if board is None:
            board = _soup(run_id, h, w)
        board01 = self._board01(board, h, w)
        cost = run_cost(size, size // WORD_BITS)

        handle = RunHandle(run_id, run_rule, h, w, ckpt_every=ckpt_every,
                           target_turn=target_turn)
        handle.bucket_key = (size, size, run_rule.rulestring,
                             cell_dtype(run_rule))
        handle.frozen = board01
        handle.admitted_cost = cost
        with self._fleet_lock:
            if run_id in self._runs:
                self.admission.reject("run_id")
                raise RuntimeError("admission rejected: run_id")
            ok, reason = self.admission.try_admit(cost)
            if not ok:
                if queue:
                    qok, qreason = self.admission.try_enqueue()
                    if qok:
                        handle.enqueued_s = time.monotonic()
                        self._runs[run_id] = handle
                        obs_usage.METER.track(run_id)
                        self._waitq.append(handle)
                        self._journal_create(handle, board01, derived)
                        self._wake.notify_all()
                        return handle.describe()
                    reason = qreason
                self.admission.reject(reason or "unknown")
                raise RuntimeError(f"admission rejected: {reason}")
            self._runs[run_id] = handle
            obs_usage.METER.track(run_id)
            self._placeq.append(handle)
            self._journal_create(handle, board01, derived)
            self._wake.notify_all()
        self._ensure_loop()
        if wait:
            self._await_placement(handle)
        with self._fleet_lock:
            return handle.describe()

    def destroy_run(self, run_id: str) -> dict:
        """Explicitly retire a fleet run: frees its bucket slot, returns
        the admission charge (so a queued waiter can promote on the next
        service pass), and drops the handle. Returns the run's final
        describe() record. The legacy run0 is refused — it IS the
        engine's single-run surface, not a fleet-created run; stop it
        with control flags instead."""
        self._check_alive()
        rid = str(run_id or "")
        with self._fleet_lock:
            if rid in ("", LEGACY_RUN_ID):
                raise PermissionError(
                    f"run {LEGACY_RUN_ID!r} is the legacy engine "
                    "surface; send QUIT/KILL control flags to stop it")
            h = self._runs.get(rid)
            if h is None:
                raise KeyError(f"unknown run {rid!r}")
            if h.migrating and not h.migrating.startswith("staged"):
                # A quiesced source copy is the rollback anchor; only
                # the migration coordinator may retire it (a staged
                # TARGET copy is destroyable — that IS the rollback).
                raise EngineBusy(f"run {rid} is migrating")
            rec = h.describe()
            self._remove_locked(h)
            rec["state"] = h.state
            # Capacity just freed: poke the loop so promotion/placement
            # happens now, not at the next natural wakeup.
            self._wake.notify_all()
        obs.RUNS_DESTROYED.inc()
        return rec

    def adopt_run(self, run_id: str, ckpt_every: int = 0,
                  target_turn: Optional[int] = None) -> dict:
        """Adopt a dead federation member's run from its per-run
        checkpoint directory under the shared GOL_CKPT root.

        Only the newest durable MANIFEST is read here — geometry, rule,
        and turn; the payload itself stays untrusted. The run is
        admitted against the local budget and registered directly in
        state "quarantined", so the PR-10 quarantine machinery performs
        the capped-backoff, integrity-verified restore and re-queues it
        for placement — adoption moves only checkpoint bytes and route
        state, never live board traffic."""
        from gol_tpu.ckpt import manifest as mf

        self._check_alive()
        rid = str(run_id or "")
        if not valid_run_id(rid) or rid == LEGACY_RUN_ID:
            self.admission.reject("run_id")
            raise RuntimeError("admission rejected: run_id")
        with self._fleet_lock:
            existing = self._runs.get(rid)
            staged = (existing is not None and
                      (existing.migrating or "").startswith("staged"))
        if staged:
            # This member already holds the run's board as a staged
            # migration import (the source died mid-cutover, after the
            # transfer): promote it instead of restoring from the
            # checkpoint — its board is at the quiesce turn, at least
            # as new as any durable manifest.
            obs_log("fleet.adopt_staged", run_id=rid)
            rec = self.activate_imported(rid)
            obs.FED_ADOPTED_RUNS.labels(status="ok").inc()
            return rec
        base = os.environ.get(CKPT_ENV, "")
        if not base:
            raise RuntimeError(
                "checkpointing not configured (set GOL_CKPT)")
        directory = self._ckpt_dir(rid, base)
        latest = mf.latest_checkpoint(directory)
        if latest is None:
            raise FileNotFoundError(
                f"no durable checkpoint for run {rid} in {directory}")
        m = latest[2]
        board_meta = m.get("board")
        if not board_meta:
            raise RuntimeError(
                f"manifest for run {rid} carries no board dims")
        h_, w_ = int(board_meta["h"]), int(board_meta["w"])
        run_rule = self._resolve_rule(m.get("rule"))
        size = choose_bucket_size(h_, w_, self.bucket_sizes)
        if size is None:
            self.admission.reject("shape")
            raise RuntimeError(
                "admission rejected: shape (board sides must divide a "
                f"bucket class {self.bucket_sizes})")
        cost = run_cost(size, size // WORD_BITS)
        handle = RunHandle(rid, run_rule, h_, w_,
                           ckpt_every=int(ckpt_every),
                           target_turn=target_turn,
                           start_turn=int(m["turn"]))
        handle.bucket_key = (size, size, run_rule.rulestring,
                             cell_dtype(run_rule))
        handle.admitted_cost = cost
        # Born quarantined: no trusted board yet. The fleet loop's
        # restore path verifies + loads the checkpoint and queues the
        # run for placement; h.adopted routes the outcome to
        # gol_fed_adopted_runs_total.
        handle.state = "quarantined"
        handle.quarantine_reason = "restore"
        handle.adopted = True
        with self._fleet_lock:
            if rid in self._runs:
                self.admission.reject("run_id")
                raise RuntimeError("admission rejected: run_id")
            ok, reason = self.admission.try_admit(cost)
            if not ok:
                self.admission.reject(reason or "unknown")
                raise RuntimeError(f"admission rejected: {reason}")
            self._runs[rid] = handle
            obs_usage.METER.track(rid)
            self._wake.notify_all()
        obs_log("fleet.adopt", run_id=rid, turn=handle.turn,
                rule=run_rule.rulestring, board=f"{h_}x{w_}")
        # Lineage link: if the predecessor journaled, reference its
        # chain head so verify_segments can stitch across the failover
        # (the manifest's journal stamp is the durable carrier).
        jstamp = m.get("journal") or {}
        self._journal_event(
            rid, "link", turn=int(m["turn"]),
            prev_head=jstamp.get("head"), prev_seq=jstamp.get("seq"),
            reason="adopt")
        self._ensure_loop()
        return handle.describe()

    # ------------------------------------------- live migration (PR 15)
    #
    # Engine-side halves of the Rescale cutover (gol_tpu/migrate.py is
    # the coordinator). Authority discipline: quiesce marks the SOURCE
    # copy migrating (frozen board authoritative, flags deferred,
    # destroy refused) but it stays the listed owner; import_run stages
    # a hidden TARGET copy; only commit/rollback resolve which one
    # survives — at every instant exactly one copy is authoritative.

    def migrate_quiesce(self, run_id: str) -> dict:
        """Phase 1: freeze a run for transfer. Parks a resident run (a
        coherent device readback to `frozen` — a mid-drive run pauses
        exactly as the QUIT flag would; the recorded target_turn rides
        the transfer so the drive continues on the new owner), pulls a
        queued run out of the placement queue, and marks the handle
        migrating with its PRIOR state so rollback restores exactly
        what quiesce saw. Returns the transfer record (board copy +
        identity)."""
        self._check_alive()
        rid = str(run_id or "")
        with self._fleet_lock:
            if rid in ("", LEGACY_RUN_ID):
                raise PermissionError(
                    f"run {LEGACY_RUN_ID!r} is the legacy engine "
                    "surface; it cannot be live-migrated")
            h = self._runs.get(rid)
            if h is None:
                raise KeyError(f"unknown run {rid!r}")
            if h.migrating is not None:
                raise EngineBusy(f"run {rid} is already migrating")
            if h.state == "quarantined" or h in self._waitq:
                raise EngineBusy(
                    f"run {rid} is {h.state} (no trusted board to "
                    "transfer); retry once it is placed")
            prior = h.state
            if h.state == "resident":
                self._park_locked(self._buckets[h.bucket_key], h)
            elif h.state == "queued":
                if h in self._placeq:
                    self._placeq.remove(h)
                h.state = "parked"
            if h.frozen is None:
                raise RuntimeError(
                    f"run {rid} has no board to transfer")
            h.migrating = prior
            self._wake.notify_all()
            jw = journal_mod.get(rid)
            return {
                "run_id": rid,
                "board": h.frozen.copy(),
                "turn": int(h.turn),
                "rule": h.rule.rulestring,
                "h": int(h.h), "w": int(h.w),
                "ckpt_every": int(h.ckpt_every),
                "target_turn": h.target_turn,
                "state": prior,
                # Chain head rides the transfer so the target's link
                # event can reference this segment's lineage.
                "journal_head": (jw.head_info() if jw is not None
                                 else None),
            }

    def migrate_checkpoint(self, run_id: str,
                           trigger: str = "migrate"):
        """Phase 2: durable belt under the transfer — if GOL_CKPT is
        configured, write a synchronous verified checkpoint of the
        quiesced board so a crash of BOTH processes mid-migration still
        resumes from this exact turn (adoption path). No-op without a
        checkpoint root."""
        if not os.environ.get(CKPT_ENV, ""):
            return None
        with self._fleet_lock:
            h = self._runs.get(str(run_id or ""))
            if h is None:
                raise KeyError(f"unknown run {run_id!r}")
        return self._ckpt_sync(h, None, trigger)

    def migrate_commit(self, run_id: str) -> list:
        """Final phase on the SOURCE: retire the migrated-away copy and
        hand back any control flags that arrived while quiesced (the
        coordinator relays them to the new owner). Idempotent: a second
        commit (or a commit after rollback already ran) returns []."""
        with self._fleet_lock:
            h = self._runs.get(str(run_id or ""))
            if h is None or h.migrating is None:
                return []
            flags = []
            while True:
                try:
                    flags.append(h.flags.get_nowait())
                except queue_mod.Empty:
                    break
            h.migrating = None
            # The run lives on elsewhere: flush its final usage
            # accounting, then bookend this segment with migrate_out
            # (not "end") so the stitched lineage reads as a handoff,
            # not a termination.
            self._retire_usage_locked(h)
            self._journal_event(h.run_id, "migrate_out",
                                turn=int(h.turn))
            self._remove_locked(h, journal_end=False)
            self._wake.notify_all()
        return flags

    def migrate_rollback(self, run_id: str) -> dict:
        """Undo a quiesce: restore the run to the state migrate_quiesce
        recorded. Idempotent — a handle that is gone or not migrating
        reports {"restored": False} and changes nothing."""
        with self._fleet_lock:
            h = self._runs.get(str(run_id or ""))
            if h is None or h.migrating is None:
                return {"restored": False}
            prior = h.migrating
            h.migrating = None
            if (prior in ("resident", "queued")
                    and h.state == "parked"
                    and (h.target_turn is None
                         or h.turn < h.target_turn)):
                # frozen reseeds the slot (or the placement queue for a
                # never-placed run) on the next service pass; a drive
                # the quiesce park disarmed re-arms. A run quiesced
                # resident AT its target stays parked — the loop only
                # parks armed drives, so resuming it would free-run it
                # past the target.
                self._resume_locked(h)
                if h.target_turn is not None:
                    h.done.clear()
            self._wake.notify_all()
            rec = h.describe()
        rec["restored"] = True
        return rec

    def import_run(self, run_id: str, board: np.ndarray, turn: int,
                   rule=None, ckpt_every: int = 0,
                   target_turn: Optional[int] = None,
                   activate: bool = True,
                   journal_head: Optional[dict] = None) -> dict:
        """TARGET half of the transfer: stage a migrated-in run. The
        board is admitted and registered parked+hidden ("staged") —
        invisible to list_runs and never auto-resumed — until CommitRun
        activates it. `activate=False` stages "staged-parked": commit
        leaves the run parked, preserving a source run that was itself
        parked (it must not start advancing because it moved)."""
        self._check_alive()
        rid = str(run_id or "")
        if not valid_run_id(rid) or rid == LEGACY_RUN_ID:
            self.admission.reject("run_id")
            raise RuntimeError("admission rejected: run_id")
        run_rule = self._resolve_rule(rule)
        board01 = np.asarray(board)
        h_, w_ = int(board01.shape[-2]), int(board01.shape[-1])
        board01 = self._board01(board01, h_, w_)
        size = choose_bucket_size(h_, w_, self.bucket_sizes)
        if size is None:
            self.admission.reject("shape")
            raise RuntimeError(
                "admission rejected: shape (board sides must divide a "
                f"bucket class {self.bucket_sizes})")
        cost = run_cost(size, size // WORD_BITS)
        handle = RunHandle(rid, run_rule, h_, w_,
                           ckpt_every=int(ckpt_every),
                           target_turn=target_turn,
                           start_turn=int(turn))
        handle.bucket_key = (size, size, run_rule.rulestring,
                             cell_dtype(run_rule))
        handle.frozen = board01
        handle.alive = int(board01.sum())
        handle.alive_turn = handle.turn
        handle.admitted_cost = cost
        handle.state = "parked"
        handle.migrating = "staged" if activate else "staged-parked"
        # A staged run with a target_turn must NOT look drivable to the
        # loop (it would auto-resume before commit) — done is only
        # cleared by a real drive request after activation.
        handle.done.set()
        with self._fleet_lock:
            if rid in self._runs:
                self.admission.reject("run_id")
                raise RuntimeError("admission rejected: run_id")
            ok, reason = self.admission.try_admit(cost)
            if not ok:
                self.admission.reject(reason or "unknown")
                raise RuntimeError(f"admission rejected: {reason}")
            self._runs[rid] = handle
            obs_usage.METER.track(rid)
        obs_log("fleet.import", run_id=rid, turn=handle.turn,
                rule=run_rule.rulestring, board=f"{h_}x{w_}")
        jstamp = journal_head or {}
        self._journal_event(
            rid, "link", turn=int(turn),
            prev_head=jstamp.get("head"), prev_seq=jstamp.get("seq"),
            reason="migrate")
        return handle.describe()

    def activate_imported(self, run_id: str) -> dict:
        """CommitRun on the TARGET: flip a staged import live. "staged"
        queues the run for placement (it resumes stepping where the
        source parked it); "staged-parked" stays parked — readable,
        not advancing — exactly as it was on the source."""
        self._check_alive()
        rid = str(run_id or "")
        with self._fleet_lock:
            h = self._runs.get(rid)
            if h is None:
                raise KeyError(f"unknown run {rid!r}")
            if not (h.migrating or "").startswith("staged"):
                raise RuntimeError(
                    f"run {rid} is not a staged import")
            staged = h.migrating
            h.migrating = None
            if staged == "staged":
                if (h.target_turn is not None
                        and h.turn >= h.target_turn):
                    # The source was resident only transiently (e.g.
                    # quiesced between placement and the loop's
                    # target-park pass). Nothing left to step — and the
                    # loop only parks runs with an ARMED drive, so
                    # queueing this one would free-run it past its
                    # target forever. Activate parked instead.
                    h.state = "parked"
                else:
                    h.state = "queued"
                    self._placeq.append(h)
                    # Re-arm the drive the staging deliberately
                    # disarmed: a run migrated mid-flight resumes
                    # stepping toward its original target.
                    if h.target_turn is not None:
                        h.done.clear()
                    self._wake.notify_all()
            rec = h.describe()
        self._ensure_loop()
        return rec

    def geometry(self) -> dict:
        """Placement stamp for checkpoint manifests / reshard deltas."""
        return {"kind": "fleet", "devices": len(self._devices)}

    def set_rule(self, run_id: str, rule) -> dict:
        """Migrate a fleet run to a new life-like rule WITHOUT dropping
        its board: the run is evicted from its current bucket (an exact
        device readback), its bucket key re-homed under the new
        rulestring, and readmitted through the existing placement queue.
        The admission charge is held across the migration — geometry is
        unchanged, so a migrating run can never lose its capacity to a
        waiter. Returns the run's describe() record (state "queued"
        until the loop re-places it). The legacy run0 is refused: its
        rule is the engine's construction-time rule."""
        self._check_alive()
        rid = str(run_id or "")
        if rule is None or rule == "":
            raise RuntimeError("admission rejected: rule (empty)")
        new_rule = self._resolve_rule(rule)
        with self._fleet_lock:
            if rid in ("", LEGACY_RUN_ID):
                raise PermissionError(
                    f"run {LEGACY_RUN_ID!r} is the legacy engine "
                    "surface; its rule is fixed at construction")
            h = self._runs.get(rid)
            if h is None:
                raise KeyError(f"unknown run {rid!r}")
            if h.rule.rulestring != new_rule.rulestring:
                self._migrate_rule_locked(h, new_rule)
                obs.RUNS_RULE_MIGRATIONS.inc()
                self._journal_event(rid, "rule", turn=int(h.turn),
                                    rule=new_rule.rulestring)
            rec = h.describe()
            self._wake.notify_all()
        self._ensure_loop()
        return rec

    def _migrate_rule_locked(self, h: RunHandle, new_rule) -> None:
        """Move a run between rule-keyed buckets, board intact. The old
        slot frees immediately; placement into the new-rule bucket rides
        the normal queue (same pass ordering as admissions)."""
        if h.slot is not None:
            bucket = self._buckets.get(h.bucket_key)
            if bucket is not None:
                if h.frozen is not None:
                    # Paused/parked: the handle copy is authoritative
                    # and the slot was stepping garbage — free it
                    # without readback.
                    bucket.release(h.slot)
                else:
                    h.frozen = bucket.evict(h.slot, h.h, h.w)
            h.slot = None
            if h.state == "resident":
                h.state = "queued"
                self._placeq.append(h)
            # Parked runs stay parked, slotless: _resume_locked requeues
            # them through placement when a drive resumes them.
        hb, wb = h.bucket_key[:2]
        h.rule = new_rule
        h.bucket_key = (hb, wb, new_rule.rulestring,
                        cell_dtype(new_rule))
        obs_log("fleet.rule_migrated", run_id=h.run_id,
                rule=new_rule.rulestring, turn=h.turn, state=h.state)

    def _resolve_rule(self, rule):
        if rule is None:
            return self._rule
        if isinstance(rule, str):
            from gol_tpu.models import parse_rule

            rule = parse_rule(rule)
        if not isinstance(rule, LifeLikeRule):
            self.admission.reject("rule")
            raise RuntimeError(
                "admission rejected: rule (the fleet batches the packed "
                "life-like stencil; LtL/Lenia runs are served by the "
                "dense engine's conv/FFT tier)")
        return rule

    @staticmethod
    def _board01(board: np.ndarray, h: int, w: int) -> np.ndarray:
        board = np.asarray(board)
        if board.ndim != 2 or board.shape != (h, w):
            raise ValueError(
                f"seed board is {board.shape}, run is {(h, w)}")
        return np.ascontiguousarray((board != 0).astype(np.uint8))

    def _next_run_id(self) -> str:
        with self._fleet_lock:
            while True:
                self._run_seq += 1
                rid = f"run{self._run_seq}"
                if rid not in self._runs:
                    return rid

    def _await_placement(self, handle: RunHandle) -> None:
        deadline = time.monotonic() + _PLACE_TIMEOUT_S
        with self._wake:
            while handle.state == "queued" and handle.pending_seed is None:
                if self._killed:
                    raise EngineKilled("engine has been killed")
                if handle.slot is not None or handle.state != "queued":
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        f"run {handle.run_id} not placed in "
                        f"{_PLACE_TIMEOUT_S:.0f}s")
                self._wake.wait(timeout=min(left, 0.2))

    # ----------------------------------------------------- legacy drive

    def server_distributor(self, params, world, sub_workers=(),
                           start_turn: int = 0,
                           token: Optional[str] = None
                           ) -> Tuple[np.ndarray, int]:
        """The reference blocking run, served from the fleet: the
        submitted world becomes (or reseeds) the legacy run0 handle,
        the loop steps it to start_turn + params.turns, and the final
        board comes back {0,255} — bit-identical to the dense engine
        (same packed stencil, same torus; the bucket tiling is exact)."""
        self._check_alive()
        with self._state_lock:
            if self._running:
                raise EngineBusy("engine already running a board")
            self._running = True
            self._run_token = token
        try:
            handle = self._legacy_handle(world, start_turn)
            return self._drive(handle, np.asarray(world), start_turn,
                               int(params.turns))
        finally:
            with self._state_lock:
                self._running = False
                self._run_token = None

    def _legacy_handle(self, world, start_turn: int) -> RunHandle:
        world = np.asarray(world)
        h, w = world.shape
        with self._fleet_lock:
            handle = self._runs.get(LEGACY_RUN_ID)
            if handle is not None and (handle.h, handle.w) != (h, w):
                # Board-shape change: retire the old legacy run (its
                # bucket slot frees) and re-create at the new geometry.
                self._remove_locked(handle)
                handle = None
            if handle is None:
                handle = RunHandle(LEGACY_RUN_ID, self._rule, h, w,
                                   start_turn=start_turn)
                size = choose_bucket_size(h, w, self.bucket_sizes)
                hb, wb = (size, size) if size else private_shape(h, w)
                handle.bucket_key = (hb, wb, self._rule.rulestring,
                                     cell_dtype(self._rule))
                handle.frozen = self._board01(world, h, w)
                # Legacy runs predate admission: never rejected, never
                # charged (admitted_cost stays 0).
                self._runs[LEGACY_RUN_ID] = handle
                obs_usage.METER.track(LEGACY_RUN_ID)
                # Transfer any flags posted before the run existed.
                try:
                    while True:
                        handle.flags.put(self._flags.get_nowait())
                except queue_mod.Empty:
                    pass
                self._placeq.append(handle)
                self._wake.notify_all()
            self._ensure_loop()
        return handle

    def _drive(self, handle: RunHandle, world: np.ndarray,
               start_turn: int, turns: int) -> Tuple[np.ndarray, int]:
        board01 = self._board01(world, handle.h, handle.w)
        with self._wake:
            if self._driving(handle):
                raise EngineBusy("run already being driven")
            handle.pending_seed = (board01, int(start_turn))
            handle.target_turn = int(start_turn) + int(turns)
            handle.done.clear()
            self._wake.notify_all()
        self._ensure_loop()
        while not handle.done.wait(timeout=0.2):
            if self._killed:
                raise EngineKilled("engine has been killed")
        if self._killed:
            raise EngineKilled("engine has been killed")
        with self._fleet_lock:
            board = handle.frozen
            turn = handle.turn
        if board is None:  # defensive: done implies parked/removed
            board, turn = self._run_board(handle)
        return (board * np.uint8(255)).astype(np.uint8), turn

    def _drive_run(self, handle: RunHandle, params, world,
                   start_turn: int) -> Tuple[np.ndarray, int]:
        """Run-scoped ServerDistributor: drive a FLEET run to a target.
        A submitted world reseeds the run at start_turn first; without
        one the run advances from wherever it is."""
        self._check_alive()
        if world is not None:
            return self._drive(handle, np.asarray(world), start_turn,
                               int(params.turns))
        with self._wake:
            if self._driving(handle):
                raise EngineBusy("run already being driven")
            handle.target_turn = handle.turn + int(params.turns)
            handle.done.clear()
            self._wake.notify_all()
        self._ensure_loop()
        while not handle.done.wait(timeout=0.2):
            if self._killed:
                raise EngineKilled("engine has been killed")
        if self._killed:
            raise EngineKilled("engine has been killed")
        with self._fleet_lock:
            board, turn = handle.frozen, handle.turn
        return (board * np.uint8(255)).astype(np.uint8), turn

    @staticmethod
    def _driving(handle: RunHandle) -> bool:
        return handle.target_turn is not None and not handle.done.is_set()

    # ------------------------------------------- legacy engine surface

    def ping(self) -> int:
        self._check_alive()
        with self._fleet_lock:
            h = self._runs.get(LEGACY_RUN_ID)
            return h.turn if h is not None else 0

    def alive_count(self) -> Tuple[int, int]:
        self._check_alive()
        with self._fleet_lock:
            h = self._runs.get(LEGACY_RUN_ID)
            if h is None:
                return 0, 0
            return h.alive, h.alive_turn

    def get_world(self) -> Tuple[np.ndarray, int]:
        self._check_alive()
        h = self._legacy_or_raise()
        board, turn = self._run_board(h)
        return (board * np.uint8(255)).astype(np.uint8), turn

    def get_world_frame(self, caps) -> Tuple[object, int]:
        from gol_tpu import wire

        px, turn = self.get_world()
        return wire.encode_board(px, frozenset(caps), binary=True), turn

    def get_view(self, max_cells: int):
        self._check_alive()
        h = self._legacy_or_raise()
        return self._view_of(h, max_cells)

    @property
    def turns_per_dispatch(self) -> int:
        """Effective turns one serving quantum advances: the configured
        chunk × the temporal-fusion depth. Every turn-accounting site
        (handle advance, rollback, trim, counters, latency) uses THIS,
        not chunk_turns — a fused fleet's turn ledger stays exact."""
        return self.chunk_turns * self.fuse_k

    def stats(self) -> dict:
        self._check_alive()
        with self._fleet_lock:
            h = self._runs.get(LEGACY_RUN_ID)
            bucket_rows = [
                {"shape": f"{b.hb}x{b.wb}", "cap": b.cap,
                 "occupied": b.occupied, "dispatches": b.dispatches,
                 "placement": b.placement, "devices": b.devices}
                for b in self._buckets.values()]
            doc = {
                "turn": h.turn if h else 0,
                "running": bool(h and self._driving(h)),
                "board": [h.h, h.w] if h else None,
                "alive": h.alive if h else None,
                "alive_turn": h.alive_turn if h else None,
                "packed": True,
                "chunk": self.chunk_turns,
                "turns_per_s": round(self._turns_per_s, 1),
                "chunk_overhead_us": round(self._chunk_overhead_us, 2),
                "rule": self._rule.rulestring,
                "devices": len(self._devices),
                "fleet": {
                    "buckets": bucket_rows,
                    "chunk_turns": self.chunk_turns,
                    "fuse_k": self.fuse_k,
                    "turns_per_dispatch": self.turns_per_dispatch,
                    "mesh": self._mesh_doc_locked(),
                    **self.runs_summary(),
                },
            }
        doc["fleet"]["admission"] = self.admission.summary()
        return doc

    def _mesh_doc_locked(self) -> dict:
        """Placement-mesh stamp for stats()/bench detail records: the
        devices the fleet actually shards over (with the batch-mesh
        geometry when one exists), never a bare jax.device_count()."""
        doc: dict = {"devices": len(self._devices)}
        if self._mesh_batch is not None:
            doc.update(mesh_geometry(self._mesh_batch))
        return doc

    def _legacy_or_raise(self) -> RunHandle:
        with self._fleet_lock:
            h = self._runs.get(LEGACY_RUN_ID)
        if h is None:
            raise RuntimeError("no board loaded")
        return h

    # Flag routing: before run0 exists flags stash on the engine queue
    # (transferred at creation); after, they go to the handle and the
    # loop applies the protocol semantics per run.

    def cf_put(self, flag: int) -> None:
        self._check_alive()
        if flag not in (FLAG_PAUSE, FLAG_QUIT, FLAG_KILL):
            raise ValueError(f"unknown control flag {flag}")
        with self._fleet_lock:
            h = self._runs.get(LEGACY_RUN_ID)
            if h is not None and h.state != "removed":
                h.flags.put(flag)
                self._wake.notify_all()
            else:
                self._flags.put(flag)

    def drain_flags(self, pause_only: bool = False) -> None:
        self._check_alive()
        with self._fleet_lock:
            h = self._runs.get(LEGACY_RUN_ID)
            if h is not None and self._driving(h):
                return  # same contract as the single-run engines
            q = h.flags if h is not None else self._flags
            _drain_queue(q, pause_only)

    def kill_prog(self) -> None:
        self._killed = True
        with self._fleet_lock:
            for h in self._runs.values():
                h.done.set()
            self._wake.notify_all()
            t = self._loop_thread
        # Wait for the loop to drain its in-flight dispatch: a daemon
        # thread still inside an XLA computation at interpreter exit
        # trips the runtime's thread-teardown abort ("terminate called
        # without an active exception"). Bounded — a wedged device
        # must not turn kill into a hang.
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)
        with self._fleet_lock:
            pool, self._ckpt_pool = self._ckpt_pool, None
        if pool is not None:
            try:
                pool.close(timeout=5.0)
            except Exception:
                pass

    # --------------------------------------------------- checkpointing

    def checkpoint_now(self, directory: Optional[str] = None,
                       trigger: str = "manual") -> Tuple[str, int]:
        h = self._legacy_or_raise()
        return self._ckpt_sync(h, directory, trigger)

    def checkpoint_fleet(self, trigger: str = "sigterm") -> int:
        """Durably checkpoint every fleet run that has a board (the
        graceful-drain hook): returns how many runs were written. The
        legacy run is excluded — the SIGTERM handler already writes it
        through the pre-fleet path. Per-run failures are logged and
        skipped; drain must make progress past one bad run."""
        base = os.environ.get(CKPT_ENV, "")
        if not base:
            return 0
        with self._fleet_lock:
            handles = [
                h for h in self._runs.values()
                if h.run_id != LEGACY_RUN_ID
                and (h.state == "resident" or h.frozen is not None)]
        n = 0
        for h in handles:
            try:
                self._ckpt_sync(h, None, trigger)
                n += 1
            except Exception as e:
                obs_exception("fleet.drain_ckpt_failed", e,
                              run_id=h.run_id)
        return n

    def _ckpt_dir(self, run_id: str, base: str) -> str:
        """Per-run checkpoint directory: the legacy run keeps writing
        at the configured root (pre-fleet resume tooling reads there);
        fleet runs get contained run-<id> subdirectories. run_id is
        re-validated here so no request-supplied string ever reaches
        os.path.join unchecked."""
        if run_id == LEGACY_RUN_ID:
            return base
        if not valid_run_id(run_id):
            raise PermissionError(f"invalid run id {run_id!r}")
        return os.path.join(base, f"run-{run_id}")

    def _ckpt_sync(self, handle: RunHandle, directory: Optional[str],
                   trigger: str) -> Tuple[str, int]:
        from gol_tpu import ckpt as ckpt_mod

        base = directory or os.environ.get(CKPT_ENV, "")
        if not base:
            raise RuntimeError(
                "checkpointing not configured: set GOL_CKPT or pass "
                "--checkpoint DIR")
        self._check_alive()
        with self._fleet_lock:
            snap = self._snapshot_locked(handle, trigger)
        writer = ckpt_mod.CheckpointWriter(
            self._ckpt_dir(handle.run_id, base), run_id=handle.run_id,
            keep_last=env_int(ckpt_mod.CKPT_KEEP_ENV,
                              ckpt_mod.CKPT_KEEP_DEFAULT),
            keep_every=env_int(ckpt_mod.CKPT_KEEP_EVERY_ENV, 0,
                               minimum=0))
        return writer.write_sync(snap), snap.turn

    def _snapshot_locked(self, h: RunHandle, trigger: str):
        """A ckpt.Snapshot of one run, word-aligned runs as packed words
        (a device slice for resident runs — the async writer
        materializes it off the loop), others as {0,1} cells."""
        from gol_tpu import ckpt as ckpt_mod

        board_meta = (h.h, h.w)
        rulestring = h.rule.rulestring
        if h.frozen is not None and (h.paused or h.state != "resident"):
            if h.w % WORD_BITS == 0:
                cells = np.ascontiguousarray(board_to_words(h.frozen))
                return ckpt_mod.Snapshot(
                    cells, "packed", 0, h.turn, board_meta, rulestring,
                    trigger=trigger,
                    mesh={"devices": len(self._devices)})
            return ckpt_mod.Snapshot(
                h.frozen.copy(), "u8", 0, h.turn, board_meta,
                rulestring, trigger=trigger,
                mesh={"devices": len(self._devices)})
        bucket = self._buckets[h.bucket_key]
        if h.w % WORD_BITS == 0:
            cells = bucket.slot_words(h.slot)[:, : h.w // WORD_BITS]
            if h.h < bucket.hb:
                cells = cells[: h.h]
            return ckpt_mod.Snapshot(
                cells, "packed", 0, h.turn, board_meta, rulestring,
                trigger=trigger,
                mesh={"devices": len(self._devices)})
        board = bucket.read_board(h.slot, h.h, h.w)
        return ckpt_mod.Snapshot(
            board, "u8", 0, h.turn, board_meta, rulestring,
            trigger=trigger, mesh={"devices": len(self._devices)})

    def _ckpt_cadence_locked(self, h: RunHandle) -> None:
        """Async per-run cadence checkpoint (loop thread, lock held):
        snapshot capture is a pointer copy; the shared writer POOL does
        the rest — a bounded worker set draining runs round-robin, not
        one thread per resident run (PR 11)."""
        from gol_tpu import ckpt as ckpt_mod

        base = os.environ.get(CKPT_ENV, "")
        if not base:
            return
        if self._ckpt_pool is None:
            self._ckpt_pool = ckpt_mod.CheckpointWriterPool()
        self._ckpt_pool.submit(
            self._ckpt_dir(h.run_id, base), h.run_id,
            self._snapshot_locked(h, "periodic"),
            keep_last=env_int(ckpt_mod.CKPT_KEEP_ENV,
                              ckpt_mod.CKPT_KEEP_DEFAULT),
            keep_every=env_int(ckpt_mod.CKPT_KEEP_EVERY_ENV, 0,
                               minimum=0))

    # ------------------------------------------ run journal (PR 17)
    #
    # Every state-mutating input to a fleet run is appended to its
    # hash-chained gol-journal/1 log (gol_tpu/journal.py). Board
    # digests ride the existing bounded checkpoint-writer pool: the
    # CheckpointWriter appends a digest event from the packed payload
    # it already hashes for the manifest, so journaling adds no device
    # readbacks of its own. All hooks are best-effort — observability
    # must never sink a run.

    def _journal_event(self, run_id: str, kind: str, **fields) -> None:
        """Append one event to `run_id`'s journal; never raises."""
        try:
            jw = journal_mod.for_run(run_id)
            if jw is not None:
                jw.append(kind, **fields)
        except Exception:
            pass

    @staticmethod
    def _journal_board_sha(board01: np.ndarray) -> dict:
        """Digest fields for a {0,1} board, using the SAME payload
        representation _snapshot_locked would checkpoint it as (packed
        words when word-aligned, u8 otherwise) so journal digests and
        manifest board_sha256 values agree for the same turn."""
        if board01.shape[-1] % WORD_BITS == 0:
            words = np.ascontiguousarray(board_to_words(board01))
            return {"board_sha256": journal_mod.board_digest(
                words, "packed"), "repr": "packed"}
        return {"board_sha256": journal_mod.board_digest(
            board01, "u8"), "repr": "u8"}

    @staticmethod
    def _journal_seed(board01: np.ndarray, derived: bool) -> dict:
        """Seed provenance for create/reseed events. Derived soups are
        replayable from the run_id alone (seed_kind "soup"); small
        explicit boards ride inline; big ones only by digest."""
        fields = FleetEngine._journal_board_sha(board01)
        if derived:
            fields["seed_kind"] = "soup"
            return fields
        if board01.size <= (1 << 22):
            seed = journal_mod.encode_board(board01)
            if seed is not None:
                fields["seed_kind"] = "inline"
                fields["seed"] = seed
                return fields
        fields["seed_kind"] = "external"
        return fields

    def _journal_create(self, h: RunHandle, board01: np.ndarray,
                        derived: bool) -> None:
        """Journal a run's birth (called under the fleet lock, right
        after registration — the loop can't have submitted any pool
        digest for it yet, so create is always seq-first)."""
        if not journal_mod.enabled():
            return
        try:
            fields = self._journal_seed(board01, derived)
            fields.update(turn=int(h.turn), h=int(h.h), w=int(h.w),
                          rule=h.rule.rulestring)
            if h.target_turn is not None:
                fields["target_turn"] = int(h.target_turn)
            self._journal_event(h.run_id, "create", **fields)
        except Exception:
            pass

    def restore_run(self, path: str, reshard: bool = False) -> int:
        from gol_tpu import ckpt as ckpt_mod

        return ckpt_mod.restore_engine(self, path, reshard=reshard)

    def save_checkpoint(self, path: str) -> None:
        """Legacy .npz autosave of run0 (SIGTERM handler parity)."""
        h = self._legacy_or_raise()
        board, turn = self._run_board(h)
        arrays: dict = {}
        if h.w % WORD_BITS == 0:
            arrays = {"words": np.ascontiguousarray(
                board_to_words(board)), "width": h.w}
        else:
            arrays = {"world": (board * np.uint8(255)).astype(np.uint8)}
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez_compressed(
                    f, turn=turn, rulestring=h.rule.rulestring, **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load_checkpoint(self, path: str) -> int:
        """Restore the LEGACY run from a dense-engine-format checkpoint
        payload — the restore_engine/--resume path works unchanged on a
        fleet server."""
        self._check_alive()
        with np.load(path) as z:
            turn = int(z["turn"])
            if "rulestring" in z.files:
                ckpt_rule = str(z["rulestring"])
                if ckpt_rule != self._rule.rulestring:
                    raise ValueError(
                        f"checkpoint rule {ckpt_rule!r} does not match "
                        f"engine rule {self._rule.rulestring!r}")
            if "world" in z.files:
                board01 = (np.asarray(z["world"]) != 0).astype(np.uint8)
            elif "words" in z.files:
                words = np.asarray(z["words"])
                width = int(z["width"])
                board01 = words_to_board(words, words.shape[-2], width)
            else:
                raise ValueError(
                    "unsupported checkpoint payload for the fleet "
                    f"engine: {sorted(z.files)}")
        h, w = board01.shape
        with self._fleet_lock:
            handle = self._runs.get(LEGACY_RUN_ID)
            if handle is not None and (handle.h, handle.w) != (h, w):
                self._remove_locked(handle)
                handle = None
            if handle is None:
                self._legacy_handle(board01 * np.uint8(255), turn)
                handle = self._runs[LEGACY_RUN_ID]
                handle.turn = turn
                handle.alive = int(board01.sum())
                handle.alive_turn = turn
            else:
                handle.pending_seed = (board01, turn)
                self._wake.notify_all()
        self._ensure_loop()
        self._await_seed(handle)
        with self._state_lock:
            self._turn = turn
            self._alive_pub = (int(board01.sum()), turn)
        return turn

    def _await_seed(self, handle: RunHandle) -> None:
        deadline = time.monotonic() + _PLACE_TIMEOUT_S
        with self._wake:
            while (handle.pending_seed is not None
                   or (handle.state == "queued"
                       and handle in self._placeq)):
                if self._killed:
                    raise EngineKilled("engine has been killed")
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet loop did not apply seed")
                self._wake.wait(timeout=0.2)

    # ----------------------------------------------------- shared reads

    def _run_board(self, h: RunHandle) -> Tuple[np.ndarray, int]:
        """({0,1} board, turn) for any run, coherent: refs are grabbed
        under the scheduling lock, the device sync happens outside it
        (jax arrays are immutable — the loop replacing `bucket.words`
        races nothing)."""
        with self._fleet_lock:
            self._check_alive()
            if h.state == "removed":
                raise RuntimeError(f"run {h.run_id} removed")
            if h.frozen is not None and (h.paused
                                         or h.state != "resident"):
                return h.frozen.copy(), h.turn
            bucket = self._buckets.get(h.bucket_key)
            if bucket is None or h.slot is None:
                if h.frozen is not None:
                    return h.frozen.copy(), h.turn
                raise RuntimeError("no board loaded")
            words_ref = bucket.words
            slot, turn = h.slot, h.turn
            hb, wb = bucket.hb, bucket.wb
        words = np.asarray(words_ref[slot])
        board = words_to_board(words, hb, wb)[: h.h, : h.w]
        return np.ascontiguousarray(board), turn

    def _view_of(self, h: RunHandle, max_cells: int):
        board, turn = self._run_board(h)
        px = (board * np.uint8(255)).astype(np.uint8)
        if max_cells <= 0 or h.h * h.w <= max_cells:
            return px, turn, (1, 1)
        f = view_factor(h.h, h.w, max_cells)
        vh, vw = -(-h.h // f), -(-h.w // f)
        padded = np.zeros((vh * f, vw * f), dtype=np.uint8)
        padded[: h.h, : h.w] = px
        view = padded.reshape(vh, f, vw, f).max(axis=(1, 3))
        return np.ascontiguousarray(view), turn, (f, f)

    # ------------------------------------------------- bench telemetry

    def throughput_counters(self) -> dict:
        """Monotonic retirement counters for the fleet bench: deltas
        over a wall interval give honest (fully-synced) aggregate CUPS."""
        with self._fleet_lock:
            return {"board_turns": self._board_turns,
                    "cell_updates": self._cell_updates,
                    "dispatches": self._dispatches,
                    "chunk_overhead_us": self._chunk_overhead_us}

    def latency_percentiles(self) -> Tuple[float, float]:
        """(p50, p99) per-run turn latency seconds: rotation gap between
        a bucket's consecutive dispatches divided by the quantum."""
        with self._fleet_lock:
            samples = sorted(self._latency_samples)
        if not samples:
            return 0.0, 0.0

        def pct(p: float) -> float:
            return samples[min(len(samples) - 1, int(p * len(samples)))]

        return pct(0.50), pct(0.99)

    def reset_bench_window(self) -> None:
        with self._fleet_lock:
            self._latency_samples.clear()

    # -------------------------------------------------------- the loop

    def _ensure_loop(self) -> None:
        with self._fleet_lock:
            t = self._loop_thread
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._loop, daemon=True,
                                     name="gol-fleet-loop")
                self._loop_thread = t
                t.start()

    def _bucket_for(self, h: RunHandle) -> Bucket:
        key = h.bucket_key
        bucket = self._buckets.get(key)
        if bucket is None:
            hb, wb, _rs, dtype = key
            if dtype != "bit":
                # Defense in depth: admission already rejects non-
                # binary families (the dense engine's conv/FFT tier
                # serves them), but a float board reaching placement
                # must never be bit-packed into a Bucket.
                raise FleetUnsupported(
                    f"no fleet bucket class for cell dtype {dtype!r}; "
                    "float-state (Lenia) runs are served by the dense "
                    "engine's conv/FFT kernel tier")
            ndev = len(self._devices)
            placement = choose_placement(hb, wb, self.slot_base, ndev)
            if placement == "batch":
                mesh = self._mesh_batch
            elif placement == "spatial":
                # Big-board fallback: too few slots per device to keep
                # the batch axis occupied — row-shard each board via
                # the halo machinery instead.
                mesh = make_mesh(ndev, devices=self._devices)
            else:
                mesh = None
            bucket = Bucket(hb, wb, h.rule, slot_base=self.slot_base,
                            mesh=mesh, placement=placement)
            self._buckets[key] = bucket
            self._rr.append(key)
        return bucket

    def _loop(self) -> None:
        """The fleet's single scheduling thread: service, pick the next
        non-empty bucket round-robin, dispatch one quantum, sync, update
        handles. Registry metrics move only at the batched-flush cadence
        (PR 6) — the per-quantum hot path touches plain locals."""
        reporter = obs_timeline.from_env()
        last_end: Dict[tuple, float] = {}
        pend_chunks = 0
        pend_turns = 0
        pend_elapsed: List[float] = []
        # Per-bucket quantum wall latencies since the last flush (PR 8):
        # plain local lists on the hot path, folded into the bounded
        # log-bucket estimators only at flush time.
        pend_quantum: Dict[str, List[float]] = {}
        # Per-dispatch usage attribution tuples (PR 19): plain local
        # appends on the hot path, apportioned by the meter at flush.
        pend_usage: List[tuple] = []
        overhead_accum = 0.0
        overhead_iters = 0
        last_cups = 0.0
        last_rate = 0.0
        last_flush = time.monotonic()

        def _flush(now: float) -> None:
            nonlocal pend_chunks, pend_turns, last_flush
            nonlocal overhead_accum, overhead_iters
            if pend_chunks:
                if self.fuse_k > 1:
                    obs.FUSED_DISPATCHES.labels(tier="fleet").inc(
                        pend_chunks)
                obs.ENGINE_CHUNKS_TOTAL.inc(pend_chunks)
                obs.ENGINE_TURNS_TOTAL.inc(pend_turns)
                obs.ENGINE_CHUNK_SECONDS.observe_batch(pend_elapsed)
                pend_elapsed.clear()
                pend_chunks = pend_turns = 0
            if overhead_iters:
                self._chunk_overhead_us = (
                    overhead_accum / overhead_iters * 1e6)
                obs.ENGINE_CHUNK_OVERHEAD_US.set(self._chunk_overhead_us)
                overhead_accum = 0.0
                overhead_iters = 0
            if last_cups > 0:
                obs.ENGINE_CUPS.set(last_cups)
            if last_rate > 0:
                obs.ENGINE_TURNS_PER_S.set(last_rate)
            with self._state_lock:
                obs.ENGINE_TURN.set(self._turn)
            obs.ENGINE_CHUNK_SIZE.set(self.turns_per_dispatch)
            obs.RUNS_RESIDENT.set(self.runs_summary()["resident"])
            obs.FLEET_MESH_DEVICES.set(len(self._devices))
            for dev, n in enumerate(self._device_resident_locked()):
                obs.FLEET_DEVICE_RESIDENT.labels(device=str(dev)).set(n)
            if self._ckpt_pool is not None:
                obs.CKPT_POOL_DEPTH.set(self._ckpt_pool.depth())
            self._flush_slo_locked(now, pend_quantum)
            self._flush_usage_locked(pend_usage)
            last_flush = now

        while not self._killed:
            t0 = time.monotonic()
            with self._wake:
                self._service_locked()
                picked = self._next_bucket_locked()
                if picked is None:
                    if pend_chunks or overhead_iters:
                        _flush(time.monotonic())
                    self._wake.wait(timeout=0.2)
                    continue
                key, bucket = picked
                chunk = self.turns_per_dispatch
                try:
                    alive_dev = bucket.dispatch(self.chunk_turns,
                                                fuse=self.fuse_k)
                except Exception as e:
                    self._dispatch_failed_locked(bucket, e)
                    continue
                stepped: List[Tuple[int, RunHandle]] = []
                for slot, h in enumerate(bucket.slots):
                    if h is not None and h.active:
                        h.turn += chunk
                        stepped.append((slot, h))
            t_disp = time.monotonic()
            try:
                alive_host = np.asarray(alive_dev)  # the device wait point
            except Exception as e:
                # The dispatch was async; the fault surfaces at the sync.
                with self._wake:
                    self._dispatch_failed_locked(bucket, e, stepped)
                continue
            t_done = time.monotonic()
            with self._wake:
                rotation = t_done - last_end.get(key, t0)
                last_end[key] = t_done
                useful_cells = 0
                run_ids: List[str] = []
                active_usage: List[Tuple[str, int]] = []
                top_turn = 0
                slot_bits = bucket.hb * bucket.wb
                poison_on = bool(os.environ.get("GOL_CHAOS"))
                for slot, h in stepped:
                    if h.state != "resident":
                        continue  # parked/removed while we waited
                    tiles = tiles_for(h.h, h.w, bucket.hb, bucket.wb)
                    raw_alive = int(alive_host[slot])
                    if poison_on:
                        from gol_tpu import chaos

                        if chaos.take_poison(h.run_id, h.turn):
                            raw_alive = -1  # fabricated device fault
                    if not 0 <= raw_alive <= slot_bits:
                        # A popcount outside [0, slot bits] cannot come
                        # from a healthy dispatch: the slot's words are
                        # untrusted. Evict without readback.
                        self._quarantine_locked(bucket, h, "popcount")
                        continue
                    h.alive = crop_alive(raw_alive, tiles)
                    h.alive_turn = h.turn
                    h.advanced_s = t_done
                    useful_cells += h.h * h.w
                    active_usage.append((h.run_id, h.h * h.w))
                    top_turn = max(top_turn, h.turn)
                    if len(run_ids) < 8:
                        run_ids.append(h.run_id)
                    if h.run_id == LEGACY_RUN_ID:
                        with self._state_lock:
                            self._turn = h.turn
                            self._alive_pub = (h.alive, h.turn)
                    if h.ckpt_every and h.turn >= h.next_ckpt_turn:
                        try:
                            self._ckpt_cadence_locked(h)
                        except Exception:
                            pass  # checkpoint trouble never stops serving
                        while h.next_ckpt_turn <= h.turn:
                            h.next_ckpt_turn += h.ckpt_every
                    if (h.target_turn is not None
                            and h.turn >= h.target_turn
                            and not h.done.is_set()):
                        self._park_locked(bucket, h)
                elapsed = t_done - t0
                wait_s = t_done - t_disp
                pend_quantum.setdefault(
                    f"{bucket.hb}x{bucket.wb}", []).append(elapsed)
                self._latency_samples.append(rotation / chunk)
                self._board_turns += chunk * len(stepped)
                self._cell_updates += chunk * useful_cells
                self._dispatches += 1
                pend_chunks += 1
                pend_turns += chunk * len(stepped)
                pend_elapsed.append(elapsed)
                pend_usage.append(
                    (bucket.placement, elapsed, chunk, active_usage))
                overhead_accum += max(0.0, elapsed - wait_s)
                overhead_iters += 1
                if elapsed > 0:
                    last_cups = chunk * useful_cells / elapsed
                    last_rate = chunk / elapsed
                    self._turns_per_s = last_rate
                if reporter is not None and stepped:
                    reporter.emit(
                        "chunk", run_id=f"fleet-{bucket.hb}x{bucket.wb}",
                        turn=top_turn, turns=chunk * len(stepped),
                        chunk_size=chunk, wall_s=round(elapsed, 6),
                        cups=last_cups, turns_per_s=last_rate,
                        token_wait_s=round(wait_s, 6),
                        runs=len(stepped), run_ids=run_ids,
                        alive=int(alive_host.sum()))
                self._wake.notify_all()
            cb = self._bcast_notify
            if cb is not None:
                cb()
            now = time.monotonic()
            if now - last_flush >= METRICS_FLUSH_SECONDS:
                with self._wake:
                    _flush(now)
        # Engine killed: flush the tail and release every waiter.
        with self._wake:
            _flush(time.monotonic())
            for h in self._runs.values():
                h.done.set()
            self._wake.notify_all()

    def _flush_slo_locked(self, now: float,
                          pend_quantum: Dict[str, List[float]]) -> None:
        """Publish the fleet SLO aggregates (fleet lock held, batched
        flush cadence only): per-bucket serving-quantum latency
        percentiles, admission queue depth/wait, per-run turn staleness
        — as bounded-cardinality gauges plus the cached /healthz doc
        (top-K worst runs by staleness, never a per-run label)."""
        qs = (0.50, 0.95, 0.99)
        for blabel, samples in pend_quantum.items():
            est = self._quantum_est.get(blabel)
            if est is None:
                est = self._quantum_est[blabel] = \
                    obs_slo.LogBucketEstimator()
            est.observe_batch(samples)
            for q, v in zip(obs.SLO_QUANTILES, est.percentiles(qs)):
                if v is not None:
                    obs.FLEET_QUANTUM_MS.labels(
                        bucket=blabel, q=q).set(round(v * 1e3, 3))
        pend_quantum.clear()
        waits = self.admission.drain_queue_waits()
        if waits:
            self._queue_wait_est.observe_batch(waits)
            for q, v in zip(obs.SLO_QUANTILES,
                            self._queue_wait_est.percentiles(qs)):
                if v is not None:
                    obs.FLEET_QUEUE_WAIT_MS.labels(q=q).set(
                        round(v * 1e3, 3))
        obs.FLEET_QUEUE_DEPTH.set(len(self._waitq))
        rows: List[Tuple[float, RunHandle]] = []
        for h in self._runs.values():
            if h.state != "resident" or h.paused:
                continue
            rows.append(((now - h.advanced_s) * 1e3, h))
        doc: dict = {"resident_active": len(rows),
                     "queue_depth": len(self._waitq),
                     "flushed_s": round(now, 3)}
        if rows:
            pcts = obs_slo.exact_percentiles([ms for ms, _ in rows], qs)
            for q, v in zip(obs.SLO_QUANTILES, pcts):
                obs.FLEET_STALENESS_MS.labels(q=q).set(round(v, 3))
            doc["staleness_ms"] = {
                q: round(v, 3)
                for q, v in zip(obs.SLO_QUANTILES, pcts)}
            rows.sort(key=lambda r: r[0], reverse=True)
            doc["worst_runs"] = [
                {"run_id": h.run_id, "staleness_ms": round(ms, 1),
                 "turn": h.turn, "state": h.state}
                for ms, h in rows[:5]]
        obs_slo.set_fleet_health(doc)

    def _flush_usage_locked(self, pend_usage: List[tuple]) -> None:
        """Hand the flush window's dispatch tuples to the usage meter
        and refresh the capacity headroom model (PR 19, fleet lock
        held, batched flush cadence only). Headroom per bucket class:
        min(free admission budget // per-run memory charge, free
        slots) runs, converted to CUPS via the class's measured mean
        quantum wall — unmeasured classes project 0 CUPS headroom
        rather than inventing a rate."""
        try:
            obs_usage.METER.ingest_dispatches(pend_usage)
            pend_usage.clear()
            adm = self.admission.summary()
            free = max(0, adm["budget_bytes"] - adm["committed_bytes"])
            slots_free = max(0, adm["max_runs"] - adm["resident"])
            rows: List[dict] = []
            for size in self.bucket_sizes:
                wpb = (size + WORD_BITS - 1) // WORD_BITS
                cost = run_cost(size, wpb)
                projected = min(free // cost, slots_free) if cost else 0
                est = self._quantum_est.get(f"{size}x{size}")
                snap = est.snapshot() if est is not None else None
                mean_s = (snap["sum"] / snap["count"]
                          if snap and snap["count"] else 0.0)
                cups_hr = (projected * size * size
                           * self.turns_per_dispatch / mean_s
                           if mean_s > 0 else 0.0)
                rows.append({
                    "bucket": f"{size}x{size}",
                    "run_cost_bytes": cost,
                    "admissible": int(projected),
                    "quantum_mean_ms": round(mean_s * 1e3, 3),
                    "cups_headroom": round(cups_hr, 1),
                    "free_bytes": free,
                    "slots_free": slots_free,
                })
            obs_usage.METER.publish(capacity=rows)
        except Exception:
            pass  # accounting trouble never stops serving

    def _retire_usage_locked(self, h: RunHandle) -> None:
        """Flush a run's final lifetime accounting into its journal
        before the terminal bookend, so GetJournal can audit a
        destroyed or migrated-away run's resource totals. Idempotent:
        the meter retires a run exactly once."""
        try:
            rec = obs_usage.METER.retire(h.run_id)
        except Exception:
            rec = None
        if rec:
            self._journal_event(h.run_id, "usage", turn=int(h.turn),
                                **rec)

    def _device_resident_locked(self) -> List[int]:
        """Resident-run count per placement-device index. Batch buckets
        place slot s on device s // (cap // devices) — NamedSharding
        splits the slot axis into equal contiguous blocks; spatial
        buckets put every board on every device; single placement lives
        entirely on device 0."""
        counts = [0] * len(self._devices)
        for b in self._buckets.values():
            if b.placement == "batch":
                block = b.cap // b.devices
                for slot, h in enumerate(b.slots):
                    if h is not None and h.state == "resident":
                        counts[slot // block] += 1
            else:
                occupied = sum(
                    1 for h in b.slots
                    if h is not None and h.state == "resident")
                if b.placement == "spatial":
                    counts = [c + occupied for c in counts]
                else:
                    counts[0] += occupied
        return counts

    def _next_bucket_locked(self):
        """Fair rotation: each non-empty bucket gets one quantum per
        cycle regardless of how many buckets exist or how full they
        are — no bucket can starve another."""
        for _ in range(len(self._rr)):
            key = self._rr[0]
            self._rr.rotate(-1)
            bucket = self._buckets.get(key)
            if bucket is not None and bucket.active_count() > 0:
                return key, bucket
        return None

    # ------------------------------------------------- loop service ops

    def _service_locked(self) -> None:
        # Token-scoped legacy abort (ControlFlagProtocol.abort_run).
        if self._abort.is_set():
            h = self._runs.get(LEGACY_RUN_ID)
            if h is not None and h.state == "resident":
                self._park_locked(self._buckets[h.bucket_key], h)
            elif h is not None:
                h.done.set()
            self._abort.clear()
        # Promote capacity-waiters in FIFO order while budget allows.
        while self._waitq:
            h = self._waitq[0]
            ok, _reason = self.admission.try_admit(h.admitted_cost)
            if not ok:
                break
            self._waitq.popleft()
            # Measured promotion wait feeds the queue-wait SLO.
            self.admission.dequeue(
                time.monotonic() - h.enqueued_s
                if h.enqueued_s is not None else None)
            self._placeq.append(h)
        # Placements.
        while self._placeq:
            h = self._placeq.pop(0)
            if h.state != "queued":
                continue
            bucket = self._bucket_for(h)
            board = h.frozen if h.frozen is not None \
                else _soup(h.run_id, h.h, h.w)
            h.slot = bucket.place(h, board)
            # A PAUSED handle's frozen board stays authoritative (the
            # slot steps garbage until resume restamps it) — clearing
            # it here would corrupt a paused run placed after a rule
            # migration or checkpoint restore.
            if not h.paused:
                h.frozen = None
            h.state = "resident"
            h.advanced_s = time.monotonic()
        # Per-run: quarantine restores, seeds, flags, resumes, trims.
        for h in list(self._runs.values()):
            if h.state == "removed":
                continue
            if ((h.migrating or "").startswith("staged")
                    and time.time() - h.created_s
                    > env_float(IMPORT_STALE_ENV,
                                IMPORT_STALE_DEFAULT_S)):
                # Orphaned import: its migration coordinator never came
                # back to commit or roll back. The source (or adopter)
                # copy is authoritative; this hidden copy only holds
                # budget — expire it.
                obs_log("fleet.import_expired", level="warning",
                        run_id=h.run_id, turn=h.turn)
                self._remove_locked(h)
                continue
            if h.state == "quarantined":
                self._service_quarantined_locked(h)
            if h.pending_seed is not None:
                try:
                    self._apply_seed_locked(h)
                except Exception as e:
                    # A seed/restore that cannot be installed leaves the
                    # run with no trustworthy board: quarantine it (the
                    # auto-restore path retries from its checkpoint).
                    h.pending_seed = None
                    obs_exception("fleet.seed_failed", e, run_id=h.run_id)
                    self._quarantine_locked(
                        self._buckets.get(h.bucket_key), h, "restore")
            if not h.flags.empty():
                self._service_flags_locked(h)
            if h.abort.is_set():
                h.abort.clear()
                if h.state == "resident":
                    self._park_locked(self._buckets[h.bucket_key], h)
                else:
                    h.done.set()
            if self._driving(h):
                if h.state == "parked":
                    self._resume_locked(h)
                if h.state == "resident" and not h.paused:
                    rem = h.target_turn - h.turn
                    if rem <= 0:
                        self._park_locked(self._buckets[h.bucket_key], h)
                    elif rem < self.turns_per_dispatch:
                        self._trim_locked(h, rem)
        self._wake.notify_all()

    def _apply_seed_locked(self, h: RunHandle) -> None:
        board01, turn = h.pending_seed
        h.pending_seed = None
        h.turn = int(turn)
        h.alive = int(board01.sum())
        h.alive_turn = h.turn
        if h.state == "resident" and not h.paused:
            self._buckets[h.bucket_key].stamp(h.slot, board01)
        else:
            h.frozen = board01
            if h.state == "quarantined":
                # An explicit reseed is operator recovery: requeue the
                # run for placement with fresh quarantine bookkeeping.
                h.state = "queued"
                h.quarantine_next_s = 0.0
                self._placeq.append(h)
        if h.run_id == LEGACY_RUN_ID:
            with self._state_lock:
                self._turn = h.turn
                self._alive_pub = (h.alive, h.turn)
        if journal_mod.enabled():
            try:
                fields = self._journal_seed(board01, False)
                fields["turn"] = h.turn
                self._journal_event(h.run_id, "reseed", **fields)
            except Exception:
                pass

    def _service_flags_locked(self, h: RunHandle) -> None:
        # Flags arriving mid-migration are DEFERRED, not dropped: the
        # coordinator drains them at commit and relays them to the new
        # owner (or rollback leaves them queued for local service).
        if h.migrating is not None:
            return
        while True:
            try:
                flag = h.flags.get_nowait()
            except queue_mod.Empty:
                return
            if flag == FLAG_PAUSE:
                self._toggle_pause_locked(h)
            elif flag == FLAG_QUIT:
                if h.state == "resident":
                    self._park_locked(self._buckets[h.bucket_key], h)
                else:
                    self._remove_locked(h)
            elif flag == FLAG_KILL:
                self._remove_locked(h)

    def _toggle_pause_locked(self, h: RunHandle) -> None:
        if h.state == "resident":
            if not h.paused:
                bucket = self._buckets[h.bucket_key]
                h.frozen = bucket.read_board(h.slot, h.h, h.w)
                h.paused = True
            else:
                bucket = self._buckets[h.bucket_key]
                bucket.stamp(h.slot, h.frozen)
                h.frozen = None
                h.paused = False
        else:
            h.paused = not h.paused
        self._journal_event(h.run_id,
                            "pause" if h.paused else "resume",
                            turn=int(h.turn))

    def _park_locked(self, bucket: Bucket, h: RunHandle) -> None:
        """Freeze a resident run: its board copies to the handle, the
        slot keeps its place (stepping garbage) for a cheap resume."""
        if h.frozen is None:
            h.frozen = bucket.read_board(h.slot, h.h, h.w)
        h.alive = int(h.frozen.sum())
        h.alive_turn = h.turn
        h.state = "parked"
        if h.run_id == LEGACY_RUN_ID:
            with self._state_lock:
                self._turn = h.turn
                self._alive_pub = (h.alive, h.turn)
        h.done.set()

    def _resume_locked(self, h: RunHandle) -> None:
        if h.slot is None:
            # Slotless park (rule migration freed the old-bucket slot):
            # resume goes back through placement — frozen reseeds the
            # new bucket on the next service pass.
            h.state = "queued"
            if h not in self._placeq:
                self._placeq.append(h)
            return
        bucket = self._buckets[h.bucket_key]
        if not h.paused and h.frozen is not None:
            bucket.stamp(h.slot, h.frozen)
            h.frozen = None
        h.state = "resident"

    # ------------------------------------------------------- quarantine

    def _quarantine_locked(self, bucket: Optional[Bucket], h: RunHandle,
                           reason: str) -> None:
        """Evict a faulted run from the shared batch: its slot frees
        WITHOUT readback (the contents are untrusted by definition) and
        its host copy is discarded. The admission charge stays held —
        auto-restore re-places the run without re-admission, so a
        transient fault cannot lose the run its capacity to a waiter."""
        reason = obs.quarantine_label(reason)
        if h.slot is not None and bucket is not None:
            bucket.release(h.slot)
        h.slot = None
        h.frozen = None
        h.paused = False
        h.state = "quarantined"
        h.quarantine_reason = reason
        h.quarantine_tries = 0
        h.quarantine_next_s = 0.0  # first restore attempt: immediately
        obs.RUNS_QUARANTINED.labels(reason=reason).inc()
        obs_log("fleet.quarantine", level="error", run_id=h.run_id,
                reason=reason, turn=h.turn)
        # Fleet audit (PR 16): rides the next heartbeat snapshot into
        # the registry tier's durable gol-fleet-audit/1 log.
        obs_audit.note("quarantine", run_id=h.run_id, reason=reason,
                       turn=h.turn)
        # NOTE: h.done is NOT set — a driven run stays driven; the
        # restore path re-queues it and the drive completes normally.
        # Only exhausted restores (below) release waiting drivers.

    def _dispatch_failed_locked(self, bucket: Bucket, exc: Exception,
                                stepped: Optional[List[Tuple[int,
                                                   RunHandle]]] = None,
                                ) -> None:
        """A batched dispatch (or its device sync) raised: no slot of
        this bucket produced a trustworthy result. Quarantine every run
        that was being stepped and rebuild the device array from the
        host copies of the survivors (paused/parked residents)."""
        obs_exception("fleet.dispatch_failed", exc,
                      bucket=f"{bucket.hb}x{bucket.wb}")
        if stepped is None:  # dispatch itself raised: turns never moved
            victims = [(s, h) for s, h in enumerate(bucket.slots)
                       if h is not None and h.active]
        else:  # sync raised: roll the optimistic turn advance back
            victims = stepped
            for _slot, h in victims:
                if h.state == "resident":
                    h.turn -= self.turns_per_dispatch
        for _slot, h in victims:
            if h.state == "resident":
                self._quarantine_locked(bucket, h, "step")
        try:
            bucket.rebuild()
        except Exception as e:  # device truly wedged; keep serving rest
            obs_exception("fleet.bucket_rebuild_failed", e,
                          bucket=f"{bucket.hb}x{bucket.wb}")

    def _service_quarantined_locked(self, h: RunHandle) -> None:
        """Capped, backed-off auto-restore: re-seed a quarantined run
        from its newest durable per-run checkpoint and requeue it for
        placement. Past the try cap the run is left quarantined and any
        waiting driver is released (it will surface the failure)."""
        now = time.monotonic()
        if now < h.quarantine_next_s:
            return
        max_tries = env_int(QUARANTINE_TRIES_ENV,
                            QUARANTINE_TRIES_DEFAULT, minimum=0)
        if h.quarantine_tries >= max_tries:
            return
        h.quarantine_tries += 1
        try:
            board01, turn = self._load_run_ckpt(h)
        except Exception as e:
            obs.RUNS_QUARANTINE_RESTORES.labels(status="error").inc()
            backoff = env_float(QUARANTINE_BACKOFF_ENV,
                                QUARANTINE_BACKOFF_DEFAULT_S)
            h.quarantine_next_s = now + backoff * (
                2 ** (h.quarantine_tries - 1))
            obs_log("fleet.quarantine_restore_failed", level="error",
                    run_id=h.run_id, attempt=h.quarantine_tries,
                    error=f"{type(e).__name__}: {e}")
            if h.quarantine_tries >= max_tries:
                obs_log("fleet.quarantine_terminal", level="error",
                        run_id=h.run_id, tries=h.quarantine_tries)
                if h.adopted:
                    h.adopted = False
                    obs.FED_ADOPTED_RUNS.labels(status="error").inc()
                h.done.set()  # drivers must not wait on a dead run
            return
        h.frozen = board01
        h.turn = int(turn)
        h.alive = int(board01.sum())
        h.alive_turn = h.turn
        if h.ckpt_every:
            h.next_ckpt_turn = h.turn + h.ckpt_every
        h.state = "queued"
        self._placeq.append(h)
        obs.RUNS_QUARANTINE_RESTORES.labels(status="ok").inc()
        if h.adopted:
            h.adopted = False
            obs.FED_ADOPTED_RUNS.labels(status="ok").inc()
        obs_log("fleet.quarantine_restored", run_id=h.run_id,
                turn=h.turn, attempt=h.quarantine_tries,
                reason=h.quarantine_reason)
        if journal_mod.enabled():
            try:
                fields = self._journal_board_sha(board01)
                fields.update(turn=h.turn, reason=h.quarantine_reason,
                              attempt=h.quarantine_tries)
                self._journal_event(h.run_id, "restore", **fields)
            except Exception:
                pass

    def _load_run_ckpt(self, h: RunHandle) -> Tuple[np.ndarray, int]:
        """(board01, turn) from the run's newest durable checkpoint,
        verified end-to-end before any byte is trusted."""
        from gol_tpu.ckpt import manifest as mf

        base = os.environ.get(CKPT_ENV, "")
        if not base:
            raise RuntimeError(
                "checkpointing not configured (set GOL_CKPT)")
        directory = self._ckpt_dir(h.run_id, base)
        latest = mf.latest_checkpoint(directory)
        if latest is None:
            raise FileNotFoundError(
                f"no durable checkpoint for run {h.run_id} "
                f"in {directory}")
        target = latest[1]
        m = mf.verify_manifest(target)
        payload = mf.payload_path(target, m)
        # Canonical decode (ckpt/reshard.py) instead of a members
        # switch: any representation family an engine can write —
        # packed words, raw pixels, sparse windows — restores here.
        from gol_tpu.ckpt import reshard as reshard_mod

        can = reshard_mod.load_canonical(payload)
        board01 = reshard_mod.board01_of(can)
        turn = int(can.turn)
        if board01.shape != (h.h, h.w):
            raise ValueError(
                f"checkpoint board {board01.shape} does not match "
                f"run {(h.h, h.w)}")
        return np.ascontiguousarray(board01), turn

    def _remove_locked(self, h: RunHandle,
                       journal_end: bool = True) -> None:
        """Terminal: free the slot, return the admission charge, drop
        the handle from the registry. The final board stays on
        `h.frozen` so an in-flight _drive can still return it.
        `journal_end=False` suppresses the journal's "end" bookend —
        migrate_commit writes "migrate_out" instead (the run lives on
        elsewhere)."""
        if h in self._placeq:
            self._placeq.remove(h)
        if h in self._waitq:
            self._waitq.remove(h)
            self.admission.dequeue()
        elif h.slot is not None:
            bucket = self._buckets.get(h.bucket_key)
            if bucket is not None:
                h.frozen = bucket.evict(h.slot, h.h, h.w)
            h.slot = None
            if h.admitted_cost:
                self.admission.release(h.admitted_cost)
        elif (h.state in ("queued", "parked", "quarantined")
              and h.admitted_cost):
            self.admission.release(h.admitted_cost)
        h.state = "removed"
        if self._ckpt_pool is not None:
            # Pending snapshots drain (same flush-then-close semantics
            # the per-run writer had); only the directory core is
            # dropped so the pool's map cannot grow unboundedly.
            self._ckpt_pool.forget(h.run_id)
        self._retire_usage_locked(h)
        if journal_end:
            self._journal_event(h.run_id, "end", turn=int(h.turn))
        journal_mod.forget(h.run_id)
        self._runs.pop(h.run_id, None)
        h.done.set()

    def _trim_locked(self, h: RunHandle, rem: int) -> None:
        """Exact-target remainder: the slot's FULL bucket torus advances
        `rem` turns as a single-board scan (same torus as the batch —
        bit-identical), then the run parks at its target."""
        bucket = self._buckets[h.bucket_key]
        obs_devstats.note_signature(
            ("fleet-trim", bucket.hb, bucket.wpb, rem,
             h.rule.rulestring))
        out = packed_run_turns(bucket.slot_words(h.slot), rem, h.rule)
        board = words_to_board(np.asarray(out), bucket.hb, bucket.wb)
        h.turn += rem
        h.frozen = np.ascontiguousarray(board[: h.h, : h.w])
        self._board_turns += rem
        self._cell_updates += rem * h.h * h.w
        # Host-side remainder turns: no dispatch wall to apportion,
        # but the advancement itself is attributable work.
        obs_usage.METER.charge_turns(h.run_id, rem, rem * h.h * h.w)
        self._park_locked(bucket, h)


def _drain_queue(q: "queue_mod.Queue[int]", pause_only: bool) -> None:
    kept = []
    try:
        while True:
            flag = q.get_nowait()
            if pause_only and flag != FLAG_PAUSE:
                kept.append(flag)
    except queue_mod.Empty:
        pass
    for flag in kept:
        q.put(flag)


class RunView:
    """The per-run engine surface of one fleet run: what the server
    dispatches run-scoped wire methods against. Implements the same
    duck-typed contract the single-run engines expose, so every
    existing handler (Stats/Alivecount/GetWorld/GetView/CFput/
    DrainFlags/Checkpoint/ServerDistributor) works per run unchanged."""

    frames_diffable = True
    binary_pixels = True

    def __init__(self, engine: FleetEngine, handle: RunHandle) -> None:
        self._engine = engine
        self._handle = handle
        self.run_id = handle.run_id

    def describe_run(self) -> dict:
        with self._engine._fleet_lock:
            return self._handle.describe()

    def ping(self) -> int:
        self._engine._check_alive()
        return self._handle.turn

    def alive_count(self) -> Tuple[int, int]:
        self._engine._check_alive()
        with self._engine._fleet_lock:
            return self._handle.alive, self._handle.alive_turn

    def get_world(self) -> Tuple[np.ndarray, int]:
        self._engine._check_alive()
        board, turn = self._engine._run_board(self._handle)
        return (board * np.uint8(255)).astype(np.uint8), turn

    def get_world_frame(self, caps) -> Tuple[object, int]:
        from gol_tpu import wire

        px, turn = self.get_world()
        return wire.encode_board(px, frozenset(caps), binary=True), turn

    def get_view(self, max_cells: int):
        self._engine._check_alive()
        return self._engine._view_of(self._handle, max_cells)

    def stats(self) -> dict:
        self._engine._check_alive()
        e, h = self._engine, self._handle
        with e._fleet_lock:
            return {
                "turn": h.turn,
                "running": FleetEngine._driving(h),
                "board": [h.h, h.w],
                "alive": h.alive,
                "alive_turn": h.alive_turn,
                "packed": True,
                "chunk": e.chunk_turns,
                "turns_per_s": round(e._turns_per_s, 1),
                "chunk_overhead_us": round(e._chunk_overhead_us, 2),
                "rule": h.rule.rulestring,
                "devices": len(e._devices),
                "run_id": h.run_id,
                "state": h.state,
            }

    def cf_put(self, flag: int) -> None:
        self._engine._check_alive()
        if flag not in (FLAG_PAUSE, FLAG_QUIT, FLAG_KILL):
            raise ValueError(f"unknown control flag {flag}")
        with self._engine._wake:
            self._handle.flags.put(flag)
            self._engine._wake.notify_all()

    def drain_flags(self, pause_only: bool = False) -> None:
        self._engine._check_alive()
        with self._engine._fleet_lock:
            if FleetEngine._driving(self._handle):
                return
            _drain_queue(self._handle.flags, pause_only)

    def checkpoint_now(self, directory: Optional[str] = None,
                       trigger: str = "manual") -> Tuple[str, int]:
        return self._engine._ckpt_sync(self._handle, directory, trigger)

    def server_distributor(self, params, world, sub_workers=(),
                           start_turn: int = 0,
                           token: Optional[str] = None):
        return self._engine._drive_run(self._handle, params, world,
                                       start_turn)

    def subscribe_view(self, vkey: str) -> None:
        """Record a live-view subscription (observational today; the
        set sizes ListRuns' viewer column and is the fan-out list a
        future push-snapshot path would use)."""
        with self._engine._fleet_lock:
            self._handle.viewers.add(vkey)
