"""Run handles: the per-run identity every engine flavour serves.

PR 7 splits "an engine" from "a run". The dense `Engine` and
`SparseEngine` each own exactly one run (the reference broker's
globals); the fleet engine owns thousands. Server/wire/obs/ckpt code
must stop reaching into one global run, so every engine now answers the
same three questions:

    resolve_run(run_id) -> a per-run ENGINE SURFACE (alive_count,
                           get_world/get_world_frame/get_view, cf_put,
                           drain_flags, checkpoint_now, stats,
                           describe_run) for that run.  run_id None/""
                           means the legacy default run — which is how
                           capability-less peers that never send a
                           run_id keep working bit-identically.
    list_runs()         -> [describe_run() dicts]
    runs_summary()      -> {"resident", "queued", ...} for /healthz,
                           ListRuns, and the fleet bench.

For the single-run engines the per-run surface IS the engine itself
(`SingleRunSurface` mixin below); the fleet engine returns a
`fleet.engine.RunView` bound to one `RunHandle`.

`RunHandle` is the fleet's per-run record: rule, turn, control-flag
queue, viewer subscriptions, checkpoint cadence, and the (bucket, slot)
placement of the run's board inside a batched device array. Handles are
mutated only by the fleet loop thread (placement, turn, alive) and by
request threads through the flag queue — the same discipline
`ControlFlagProtocol` uses, so the subtle flag semantics cannot drift.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional, Tuple

import numpy as np

from gol_tpu.wire import valid_run_id  # noqa: F401  (re-exported)

# The implicit run every pre-fleet client is talking to when it sends
# no run_id. One constant, shared by the engines' mixin, the fleet
# engine's legacy path, the server's routing, and the tests.
LEGACY_RUN_ID = "run0"

# RunHandle.state values (ListRuns/AttachRun expose them verbatim):
#   queued      - admitted to the wait queue, no device placement yet
#   resident    - placed in a bucket slot; stepped while active
#   parked      - resident but frozen (target reached, FLAG_QUIT on an
#                 active run, or pause): board durable on the handle,
#                 readable, not stepped
#   quarantined - slot faulted (implausible popcount, step exception,
#                 restore failure): evicted from its bucket with the
#                 board DISCARDED as untrusted, admission charge held;
#                 the fleet loop auto-restores from the run's last
#                 per-run checkpoint under capped backoff
#                 (GOL_QUARANTINE_TRIES), re-queueing it for placement
#   removed     - slot released, capacity returned; terminal
RUN_STATES = ("queued", "resident", "parked", "quarantined", "removed")


class FleetUnsupported(RuntimeError):
    """A fleet-only operation (CreateRun on a single-run engine)."""


class RunHandle:
    """One resident (or queued) run of the fleet engine."""

    __slots__ = (
        "run_id", "rule", "h", "w", "bucket_key", "slot", "turn",
        "alive", "alive_turn", "state", "paused", "frozen", "flags",
        "viewers", "ckpt_every", "next_ckpt_turn", "target_turn",
        "done", "created_s", "pending_seed", "abort",
        "admitted_cost", "enqueued_s", "advanced_s",
        "quarantine_reason", "quarantine_tries", "quarantine_next_s",
        "adopted", "migrating",
    )

    def __init__(self, run_id: str, rule, h: int, w: int,
                 ckpt_every: int = 0,
                 target_turn: Optional[int] = None,
                 start_turn: int = 0) -> None:
        self.run_id = run_id
        self.rule = rule
        self.h = int(h)
        self.w = int(w)
        self.bucket_key: Optional[tuple] = None
        self.slot: Optional[int] = None
        self.turn = int(start_turn)
        self.alive = 0
        self.alive_turn = int(start_turn)
        self.state = "queued"
        self.paused = False
        # Host {0,1} board while parked/paused (the authoritative state
        # whenever the slot isn't being stepped on the run's behalf);
        # also the queued run's seed until placed.
        self.frozen: Optional[np.ndarray] = None
        # (board01, turn) a request thread wants installed — applied by
        # the fleet loop before the next quantum (reseed/restore path).
        self.pending_seed: Optional[tuple] = None
        # Bytes charged to the admission controller (0 for the legacy
        # run, which predates admission and is never rejected).
        self.admitted_cost = 0
        self.flags: "queue.Queue[int]" = queue.Queue()
        # vkeys of live-view subscribers (GetView with run_id): purely
        # observational today — lets ListRuns show which runs anyone is
        # actually watching, and gives a later PR the subscription set
        # push-snapshots would fan out to.
        self.viewers: set = set()
        self.ckpt_every = int(ckpt_every)
        self.next_ckpt_turn = (self.turn + self.ckpt_every
                               if self.ckpt_every else 0)
        # None = free-running (serve until FLAG_QUIT); else park at it.
        self.target_turn = target_turn
        self.done = threading.Event()
        self.abort = threading.Event()
        self.created_s = time.time()
        # SLO telemetry (PR 8), monotonic clock: when the run entered
        # the admission wait queue (None = never queued), and when its
        # board last advanced — placement stamps it, each stepped
        # quantum restamps it, and the fleet flush derives the per-run
        # TURN STALENESS signal (now - advanced_s) from it.
        self.enqueued_s: Optional[float] = None
        self.advanced_s = time.monotonic()
        # Quarantine bookkeeping (PR 10): why the run left residency,
        # restore attempts so far, and the monotonic time before which
        # the fleet loop must not retry (exponential backoff).
        self.quarantine_reason: Optional[str] = None
        self.quarantine_tries = 0
        self.quarantine_next_s = 0.0
        # Federation (PR 12): True while this handle is an adopted run
        # whose first restore hasn't resolved yet — the quarantine
        # service meters its outcome under gol_fed_adopted_runs_total.
        self.adopted = False
        # Live migration (PR 15). On the SOURCE member: the run's state
        # before migrate_quiesce ("resident"/"queued"/"parked"), so
        # rollback restores exactly that; control flags are deferred
        # while set. On the TARGET member: "staged"/"staged-parked"
        # until CommitRun activates the import — staged handles are
        # hidden from list_runs (exactly one listed copy fleet-wide)
        # and never auto-resumed. None = not migrating.
        self.migrating: Optional[str] = None

    @property
    def active(self) -> bool:
        """Stepped by the fleet loop this quantum?"""
        return self.state == "resident" and not self.paused

    def describe(self) -> dict:
        """The ListRuns/AttachRun record for this run."""
        rec = {
            "run_id": self.run_id,
            "state": self.state,
            "board": [self.h, self.w],
            "rule": self.rule.rulestring,
            "turn": self.turn,
            "alive": self.alive,
            "alive_turn": self.alive_turn,
            "paused": self.paused,
            "bucket": ("%dx%d" % self.bucket_key[:2]
                       if self.bucket_key else None),
            "viewers": len(self.viewers),
            "ckpt_every": self.ckpt_every,
            "target_turn": self.target_turn,
        }
        # Only quarantined (or once-quarantined) runs carry the key, so
        # the record shape is unchanged for every healthy run.
        if self.quarantine_reason is not None:
            rec["quarantine_reason"] = self.quarantine_reason
            rec["quarantine_tries"] = self.quarantine_tries
        if self.migrating is not None:
            rec["migrating"] = self.migrating
        return rec


class SingleRunSurface:
    """Run-handle contract for the single-run engines.

    Mixed into `Engine` and `SparseEngine`: the per-run surface of the
    one run IS the engine object, so `resolve_run` hands the engine
    back and every existing call site keeps working. Relies only on
    the duck-typed engine surface (`stats`, `alive_count`) both
    engines already implement."""

    run_id = LEGACY_RUN_ID

    def resolve_run(self, run_id: Optional[str] = None):
        if run_id in (None, "", self.run_id):
            return self
        raise KeyError(f"unknown run {run_id!r}")

    def describe_run(self) -> dict:
        s = self.stats()
        board = s.get("board")
        return {
            "run_id": self.run_id,
            "state": "resident",
            "board": list(board) if board else None,
            "rule": s.get("rule"),
            "turn": s.get("turn"),
            "alive": s.get("alive"),
            "alive_turn": s.get("alive_turn"),
            "paused": False,
            "bucket": None,
            "viewers": 0,
            "ckpt_every": 0,
            "target_turn": None,
            "running": s.get("running"),
        }

    def list_runs(self) -> list:
        return [self.describe_run()]

    def runs_summary(self) -> dict:
        return {"resident": 1, "queued": 0,
                "engine": type(self).__name__}

    def create_run(self, *a, **kw):
        raise FleetUnsupported(
            f"{type(self).__name__} serves a single run; start the "
            "server with --fleet for CreateRun")

    def destroy_run(self, *a, **kw):
        raise FleetUnsupported(
            f"{type(self).__name__} serves a single run; start the "
            "server with --fleet for DestroyRun")


def tiles_for(h: int, w: int, hb: int, wb: int) -> int:
    """How many copies of an (h, w) board tile an (hb, wb) bucket."""
    return (hb // h) * (wb // w)


def fits_bucket(h: int, w: int, hb: int, wb: int) -> bool:
    """The tiling contract: a board occupies a bucket by PERIODIC
    tiling, so the bucket torus evolution restricted to any board-sized
    window is bit-identical to the board's own torus evolution (GoL
    commutes with translations; a periodic state stays periodic). That
    requires the board to divide the bucket exactly in both axes."""
    return (0 < h <= hb and 0 < w <= wb
            and hb % h == 0 and wb % w == 0)


def tile_board(board01: np.ndarray, hb: int, wb: int) -> np.ndarray:
    """(h, w) {0,1} board -> (hb, wb) periodic tiling."""
    h, w = board01.shape
    return np.tile(board01, (hb // h, wb // w))


def crop_alive(bucket_alive: int, tiles: int) -> int:
    """Per-run alive count from the tiled bucket popcount (exact: the
    bucket holds `tiles` identical copies of the board)."""
    return int(bucket_alive) // max(1, int(tiles))
