"""Typed event API — the observability contract of the framework.

Re-design of the reference event surface (`Local/gol/event.go:9-131`):
the `Event` interface (a Stringer plus `GetCompletedTurns`) and six concrete
events. Events flow over a `queue.Queue` from the distributor to the
SDL/ASCII view and to tests; the channel-close of the Go version is modelled
by the `CLOSE` sentinel pushed after the final event
(`Local/gol/distributor.go:226`).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Tuple


class State(enum.Enum):
    """Reference `State` enum (`Local/gol/event.go:70-90`)."""

    PAUSED = "Paused"
    EXECUTING = "Executing"
    QUITTING = "Quitting"

    def __str__(self) -> str:  # matches Go String()
        return self.value


@dataclasses.dataclass(frozen=True)
class Event:
    """Base event; `completed_turns` mirrors GetCompletedTurns()."""

    completed_turns: int

    def __str__(self) -> str:
        return ""


@dataclasses.dataclass(frozen=True)
class AliveCellsCount(Event):
    """Emitted every 2 s by the telemetry ticker
    (reference `Local/gol/distributor.go:154-167`)."""

    cells_count: int = 0

    def __str__(self) -> str:
        return f"{self.cells_count} Alive Cells"


@dataclasses.dataclass(frozen=True)
class ImageOutputComplete(Event):
    """A PGM snapshot hit disk (`Local/gol/event.go:33-45`)."""

    filename: str = ""

    def __str__(self) -> str:
        return f"File {self.filename} output complete"


@dataclasses.dataclass(frozen=True)
class StateChange(Event):
    """Executing / Paused / Quitting transition (`event.go:47-68`)."""

    new_state: State = State.EXECUTING

    def __str__(self) -> str:
        return f"State change to {self.new_state}"


@dataclasses.dataclass(frozen=True)
class CellFlipped(Event):
    """A single cell changed value; feeds the live view
    (`event.go:92-100`; defined-but-unemitted in the reference — we emit it
    when a live view is attached)."""

    cell: Tuple[int, int] = (0, 0)  # (x, y)


@dataclasses.dataclass(frozen=True)
class CellsFlipped(Event):
    """Batched CellFlipped — one event per turn instead of one per cell,
    so the live view costs one host transfer per rendered frame."""

    cells: Tuple[Tuple[int, int], ...] = ()


@dataclasses.dataclass(frozen=True)
class TurnComplete(Event):
    """End-of-turn marker for the live view (`event.go:102-110`)."""


@dataclasses.dataclass(frozen=True)
class FinalTurnComplete(Event):
    """Terminal event carrying the alive-cell set; the test-harness hook
    (`event.go:112-124`, consumed at `Local/gol_test.go:32-37`).

    `alive_count` is always populated. For boards beyond
    GOL_MAX_EVENT_CELLS total cells (default 2^24) the `alive` tuple is
    left EMPTY — materialising ~10^9 coordinate tuples for a 65536²
    board would exhaust controller memory; at every reference scale the
    full list is present and `alive_count == len(alive)`."""

    alive: Tuple[Tuple[int, int], ...] = ()  # (x, y) pairs
    alive_count: int = -1  # -1 only for hand-built legacy instances

    def count(self) -> int:
        return self.alive_count if self.alive_count >= 0 else len(self.alive)


@dataclasses.dataclass(frozen=True)
class EngineLost(Event):
    """The controller lost its remote engine mid-run (connection failure
    or missed heartbeats). Framework extension beyond the reference —
    its only failure story is `log.Fatal` on dial errors
    (`Local/gol/distributor.go:96-98`); here the controller announces the
    loss and tries to reattach (see `GOL_RECONNECT`)."""

    def __str__(self) -> str:
        return "Engine connection lost"


@dataclasses.dataclass(frozen=True)
class EngineReattached(Event):
    """The controller reattached to a recovered engine and resumed the run
    from the engine's authoritative (world, turn) — the automated version
    of the reference's manual CONT=yes reattach."""

    def __str__(self) -> str:
        return "Engine connection restored"


class _Close:
    """Sentinel marking the end of the event stream (Go channel close)."""

    def __repr__(self) -> str:
        return "<events closed>"


CLOSE = _Close()


def drain(events_queue) -> List[Event]:
    """Collect every event until CLOSE. Test helper mirroring the
    `for event := range events` pattern (`Local/gol_test.go:32`)."""
    out: List[Event] = []
    while True:
        ev = events_queue.get()
        if ev is CLOSE:
            return out
        out.append(ev)
