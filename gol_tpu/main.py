"""CLI entry — counterpart of reference `Local/main.go:12-51`.

Flags mirror the Go CLI: `-t` threads (default 8), `-w` width (512),
`-h` height (512), `--turns` (default 10_000_000_000 ≙ "run until
keypress", `Local/main.go:37`). Env mirrors the reference too: `SER`
(engine address — empty means in-process engine), `SUB` (worker list →
shard-count request), `CONT=yes` (reattach).

    python -m gol_tpu.main -w 512 -h 512 --turns 100
    python -m gol_tpu.main --headless --live   # ANSI live view off/on
"""

from __future__ import annotations

import argparse
import queue
import sys

from gol_tpu.gol import run
from gol_tpu.params import Params
from gol_tpu.sdl.loop import start as view_start


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        description="TPU-native distributed Game of Life", add_help=False
    )
    ap.add_argument("--help", action="help")
    ap.add_argument("-t", "--threads", type=int, default=8,
                    help="worker shard hint (reference thread count)")
    ap.add_argument("-w", "--width", type=int, default=512)
    ap.add_argument("-h", "--height", type=int, default=512)
    ap.add_argument("--turns", type=int, default=10_000_000_000)
    ap.add_argument("--headless", action="store_true",
                    help="no window / terminal rendering, events printed")
    ap.add_argument("--live", action="store_true",
                    help="enable the live board view (polls snapshots)")
    ap.add_argument("--trace", metavar="DIR", default="",
                    help="dump one jax.profiler chunk trace to DIR")
    ap.add_argument("--rule", metavar="B.../S...", default="",
                    help="life-like rulestring for the in-process engine "
                         "(e.g. B36/S23 = HighLife; default Conway). With "
                         "SER set, the remote engine's own rule governs.")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.trace:
        import os

        from gol_tpu.engine import TRACE_ENV

        os.environ[TRACE_ENV] = args.trace
    rule = None
    if args.rule:
        from gol_tpu.models.lifelike import LifeLikeRule

        rule = LifeLikeRule(args.rule)  # fail fast on a malformed string
    p = Params(
        threads=args.threads,
        image_width=args.width,
        image_height=args.height,
        turns=args.turns,
    )
    events_q: "queue.Queue" = queue.Queue(maxsize=10000)
    key_presses: "queue.Queue" = queue.Queue(maxsize=10)
    run(p, events_q, key_presses, live_view=args.live, rule=rule)
    view_start(p, events_q, key_presses, headless=args.headless)
    return 0


if __name__ == "__main__":
    sys.exit(main())
