"""CLI entry — counterpart of reference `Local/main.go:12-51`.

Flags mirror the Go CLI: `-t` threads (default 8), `-w` width (512),
`-h` height (512), `--turns` (default 10_000_000_000 ≙ "run until
keypress", `Local/main.go:37`). Env mirrors the reference too: `SER`
(engine address — empty means in-process engine), `SUB` (worker list →
shard-count request), `CONT=yes` (reattach).

    python -m gol_tpu.main -w 512 -h 512 --turns 100
    python -m gol_tpu.main --headless --live   # ANSI live view off/on
"""

from __future__ import annotations

import argparse
import queue
import sys

from gol_tpu.gol import run
from gol_tpu.params import Params
from gol_tpu.sdl.loop import start as view_start


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        description="TPU-native distributed Game of Life", add_help=False
    )
    ap.add_argument("--help", action="help")
    ap.add_argument("-t", "--threads", type=int, default=8,
                    help="worker shard hint (reference thread count)")
    ap.add_argument("-w", "--width", type=int, default=512)
    ap.add_argument("-h", "--height", type=int, default=512)
    ap.add_argument("--turns", type=int, default=10_000_000_000)
    ap.add_argument("--headless", action="store_true",
                    help="no window / terminal rendering, events printed")
    ap.add_argument("--live", action="store_true",
                    help="enable the live board view (polls snapshots)")
    ap.add_argument("--trace", metavar="DIR", default="",
                    help="dump one jax.profiler chunk trace to DIR")
    ap.add_argument("--profile-dir", metavar="DIR", default="",
                    help="capture a jax.profiler trace of the run's "
                         "first --profile-turns turns into DIR "
                         "(XPlane + Perfetto-loadable trace.json.gz); "
                         "equivalent to GOL_PROFILE_DIR=DIR")
    ap.add_argument("--profile-turns", type=int, default=0,
                    metavar="N",
                    help="turns per profiler capture (sets "
                         "GOL_PROFILE_TURNS; default 256)")
    ap.add_argument("--run-report", metavar="PATH", default="",
                    help="append a JSON-lines chunk-timeline run report "
                         "(schema gol-run-report/1) to PATH; equivalent "
                         "to GOL_RUN_REPORT=PATH")
    ap.add_argument("--journal", metavar="DIR", default="",
                    help="append every state-mutating run input to a "
                         "hash-chained gol-journal/1 log under DIR "
                         "(replay with tools/replay_audit.py); "
                         "equivalent to GOL_JOURNAL=DIR")
    ap.add_argument("--journal-digest-every", type=int, default=0,
                    metavar="TURNS",
                    help="journal a canonical board digest every TURNS "
                         "turns at chunk boundaries (sets "
                         "GOL_JOURNAL_DIGEST_EVERY; default 512; "
                         "requires --journal)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve Prometheus text on "
                         "http://127.0.0.1:PORT/metrics (0 = ephemeral "
                         "port; unset = no endpoint)")
    ap.add_argument("--trace-spans", metavar="PATH", default="",
                    help="export controller/RPC/engine spans as Chrome "
                         "trace-event JSON (Perfetto-loadable) to PATH "
                         "when the run ends; equivalent to "
                         "GOL_TRACE_SPANS=PATH (a directory gets one "
                         "file per pid)")
    ap.add_argument("--rule", metavar="RULE", default="",
                    help="rulestring for the in-process engine: life-like"
                         " 'B36/S23' (HighLife) or Generations "
                         "'survival/birth/states' ('/2/3' = Brian's "
                         "Brain); default Conway. With SER set, the "
                         "remote engine's own rule governs evolution and "
                         "this (or GOL_RULE) only sets controller-side "
                         "io semantics — match the server's --rule.")
    ap.add_argument("--rle", metavar="NAME|FILE", default="",
                    help="seed the board from an RLE pattern instead of "
                         "images/WxH.pgm: a library name (glider, lwss, "
                         "rpentomino, gosper-gun, blinker) or a .rle file, "
                         "stamped centred on an empty WxH torus")
    ap.add_argument("--checkpoint", metavar="DIR", default="",
                    help="checkpoint directory (sets GOL_CKPT): the "
                         "engine writes gol-ckpt/1 manifest checkpoints "
                         "here when --ckpt-every is set, plus the legacy "
                         "time-based autosave")
    ap.add_argument("--ckpt-every", metavar="TURNS", type=int, default=0,
                    help="manifest checkpoint cadence in TURNS (sets "
                         "GOL_CKPT_EVERY_TURNS; 0 = off; requires "
                         "--checkpoint)")
    ap.add_argument("--ckpt-keep", metavar="N", type=int, default=0,
                    help="retention: keep the newest N checkpoints "
                         "(sets GOL_CKPT_KEEP; default 3; "
                         "GOL_CKPT_KEEP_EVERY additionally pins every "
                         "K-th turn)")
    ap.add_argument("--resume", metavar="DIR|MANIFEST|NPZ", nargs="?",
                    const="", default=None,
                    help="resume from a checkpoint before running: a "
                         "directory (newest durable manifest wins), a "
                         "ckpt-*.json manifest (payload SHA-256 "
                         "verified), or a legacy .npz; bare --resume "
                         "uses --checkpoint / GOL_CKPT. With SER set "
                         "the SERVER adopts the checkpoint from its own "
                         "configured directory (RestoreRun)")
    ap.add_argument("--sparse", action="store_true",
                    help="run on the sparse-torus engine: -w/-h give the "
                         "TORUS size (equal, multiple of 32 — e.g. "
                         "1048576 = 2^20) and --rle gives the seed; only "
                         "the live window is ever materialised, snapshots "
                         "and the final PGM are that window")
    return ap.parse_args(argv)


def _parse_rle_arg(name_or_path: str):
    """(cells, pw, ph, rle_declared_rule_or_None) from a library pattern
    name or a .rle file path."""
    from gol_tpu.io.rle import parse_rle, read_rle
    from gol_tpu.models.patterns import PATTERNS

    if name_or_path in PATTERNS:
        return parse_rle(PATTERNS[name_or_path])
    return read_rle(name_or_path)


def _stage_tmp_pgm(board, filename: str, prefix: str) -> str:
    """Write `board` as `<fresh tempdir>/<filename>` (cleaned up at
    exit); returns the tempdir."""
    import atexit
    import os
    import shutil
    import tempfile

    from gol_tpu.io.pgm import write_pgm

    d = tempfile.mkdtemp(prefix=prefix)
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    write_pgm(os.path.join(d, filename), board)
    return d


def _stage_rle_board(name_or_path: str, width: int, height: int):
    """Stamp an RLE pattern (library name or file path) centred on an
    empty width x height board and write it as `WxH.pgm` in a fresh temp
    images dir. Returns (images_dir, rle_declared_rule_or_None)."""
    import numpy as np

    cells, pw, ph, rle_rule = _parse_rle_arg(name_or_path)
    if pw > width or ph > height:
        raise ValueError(
            f"pattern extent {pw}x{ph} exceeds board {width}x{height}")
    board = np.zeros((height, width), dtype=np.uint8)
    ox, oy = (width - pw) // 2, (height - ph) // 2
    for x, y in cells:
        board[oy + y, ox + x] = 255
    return (_stage_tmp_pgm(board, f"{width}x{height}.pgm", "gol_rle_"),
            rle_rule)


def _stage_sparse_seed(name_or_path: str):
    """Write an RLE pattern as `<tempdir>/seed.pgm` at its own bounding-
    box dims — the sparse distributor's board source (live cells are
    stamped centred on the torus engine-side). Returns (images_dir,
    rle_declared_rule_or_None)."""
    import numpy as np

    cells, pw, ph, rle_rule = _parse_rle_arg(name_or_path)
    board = np.zeros((ph, pw), dtype=np.uint8)
    for x, y in cells:
        board[y, x] = 255
    return _stage_tmp_pgm(board, "seed.pgm", "gol_sparse_"), rle_rule


def main(argv=None) -> int:
    args = parse_args(argv)
    import os

    import gol_tpu

    gol_tpu.maybe_enable_default_compile_cache()
    if args.trace:
        from gol_tpu.engine import TRACE_ENV

        os.environ[TRACE_ENV] = args.trace
    # Same env-var contract: the engine arms one capture per run start
    # while GOL_PROFILE_DIR is set (obs/prof.arm_from_env).
    if args.profile_dir:
        from gol_tpu.obs.prof import PROFILE_DIR_ENV

        os.environ[PROFILE_DIR_ENV] = args.profile_dir
    if args.profile_turns:
        from gol_tpu.obs.prof import PROFILE_TURNS_ENV

        os.environ[PROFILE_TURNS_ENV] = str(args.profile_turns)
    if args.run_report:
        # Same env-var contract as --trace: the engine reads it at run
        # time, so remote/forked engines inherit it too.
        from gol_tpu.obs.timeline import RUN_REPORT_ENV

        os.environ[RUN_REPORT_ENV] = args.run_report
    if args.journal:
        from gol_tpu import journal as journal_mod

        os.environ[journal_mod.JOURNAL_ENV] = args.journal
    if args.journal_digest_every:
        from gol_tpu import journal as journal_mod

        os.environ[journal_mod.DIGEST_EVERY_ENV] = str(
            args.journal_digest_every)
    # Checkpoint knobs travel as env too — the engine reads them at run
    # start (gol_tpu/ckpt package docstring has the full table).
    if args.checkpoint:
        os.environ["GOL_CKPT"] = args.checkpoint
    if args.ckpt_every:
        os.environ["GOL_CKPT_EVERY_TURNS"] = str(args.ckpt_every)
    if args.ckpt_keep:
        os.environ["GOL_CKPT_KEEP"] = str(args.ckpt_keep)
    from gol_tpu.obs import trace as obs_trace

    if args.trace_spans:
        os.environ[obs_trace.TRACE_SPANS_ENV] = args.trace_spans
    obs_trace.set_process_name("gol-controller")
    if args.metrics_port is not None:
        from gol_tpu.obs.http import start_metrics_server
        from gol_tpu.obs.log import log as obs_log

        msrv = start_metrics_server(args.metrics_port)
        obs_log("metrics.serving", url=msrv.url, port=msrv.port)
    rule = None
    if args.rule:
        from gol_tpu.models import parse_rule

        rule = parse_rule(args.rule)  # fail fast on a malformed string
        if os.environ.get("SER"):
            import warnings

            warnings.warn(
                f"--rule {rule.rulestring} has no effect with SER set: "
                "the REMOTE engine's own rule governs the run — start "
                "the server with --rule to match")
    if args.resume is not None:
        from gol_tpu.distributor import (
            _resolve_engine,
            _resolve_sparse_engine,
        )

        if os.environ.get("SER"):
            # The SERVER adopts the checkpoint from its own configured
            # directory (RestoreRun): the reference names a checkpoint
            # there, or "" for its newest durable one.
            turn = _resolve_engine(rule).restore_run(args.resume)
        else:
            ref = args.resume or os.environ.get("GOL_CKPT", "")
            if not ref:
                print("--resume needs DIR|MANIFEST|NPZ (or --checkpoint"
                      " / GOL_CKPT to name the directory)",
                      file=sys.stderr)
                return 2
            from gol_tpu import ckpt as ckpt_mod

            kind, target = ckpt_mod.resolve(ref)
            if kind == "manifest":
                m = ckpt_mod.read_manifest(target)
                if rule is None:
                    # The manifest knows the run's rule — resuming must
                    # not require re-stating it.
                    from gol_tpu.models import parse_rule

                    rule = parse_rule(m["rule"])
                if (args.sparse and m["repr"] == "sparse"
                        and m.get("board")):
                    # A sparse manifest knows its torus size too.
                    args.width = args.height = int(m["board"]["w"])
                elif not args.sparse and m.get("board"):
                    # Dense: adopt the checkpoint's board dims so the
                    # out/WxHxT.pgm filename contract and the live-view
                    # window describe the RESTORED board, not the -w/-h
                    # defaults the user didn't type.
                    args.width = int(m["board"]["w"])
                    args.height = int(m["board"]["h"])
            eng = (_resolve_sparse_engine(args.width, rule)
                   if args.sparse else _resolve_engine(rule))
            turn = eng.restore_run(target if kind == "manifest" else ref)
        # Reattach to the restored engine-held state — the CONT=yes
        # contract — instead of seeding a fresh board from images/.
        os.environ["CONT"] = "yes"
        print(f"resuming at turn {turn}", flush=True)
    p = Params(
        threads=args.threads,
        image_width=args.width,
        image_height=args.height,
        turns=args.turns,
    )
    images_dir = None
    if args.sparse:
        if not args.rle and os.environ.get("CONT", "") != "yes":
            print("--sparse needs --rle (the seed pattern) unless "
                  "CONT=yes resumes an engine-held run", file=sys.stderr)
            return 2
        if args.width != args.height or args.width % 32:
            print(f"--sparse torus must be square and a multiple of 32, "
                  f"got {args.width}x{args.height}", file=sys.stderr)
            return 2
        if args.rle:
            images_dir, rle_rule = _stage_sparse_seed(args.rle)
            if rule is None:
                rule = rle_rule
    elif args.rle:
        # Materialise the pattern as the WxH.pgm the distributor expects
        # (in a temp images dir) — the PGM board-source contract stays the
        # single entry path. An RLE-declared rule applies unless --rule
        # overrode it.
        images_dir, rle_rule = _stage_rle_board(
            args.rle, args.width, args.height)
        if rule is None:
            rule = rle_rule
            if rle_rule is not None and os.environ.get("SER"):
                import warnings

                warnings.warn(
                    f"--rle declares rule {rle_rule.rulestring}, but with "
                    "SER set the REMOTE engine's own rule governs the "
                    "run — start the server with --rule to match")
    events_q: "queue.Queue" = queue.Queue(maxsize=10000)
    key_presses: "queue.Queue" = queue.Queue(maxsize=10)
    t = run(p, events_q, key_presses, live_view=args.live, rule=rule,
            images_dir=images_dir, sparse=args.sparse)
    view_start(p, events_q, key_presses, headless=args.headless)
    t.join(30)
    # Export whatever spans the run recorded (no-op without
    # --trace-spans / GOL_TRACE_SPANS; never raises).
    obs_trace.export_from_env()
    if t.exception is not None:
        # The run failed (bad rule, missing image, engine error): the
        # thread printed its traceback; the CLI must exit non-zero
        # (reference parity: the Go controller log.Fatal's).
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
