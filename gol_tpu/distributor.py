"""The controller distributor: orchestrates one full run.

Re-design of reference `Local/gol/distributor.go:55-226`. Same observable
contract — load `images/WxH.pgm`, drive the engine, emit the event stream,
honour s/p/q/k keypresses, tick alive counts every 2 s, write
`out/WxHxT.pgm`, support detach (`q`) / reattach (`CONT=yes`) — but the
engine link is either an in-process `Engine` (default; the "no real
cluster" test story) or a remote engine over the TCP control plane when
`SER` is set, mirroring the reference env config
(`Local/gol/distributor.go:90-105`).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import List, Optional

import numpy as np

from gol_tpu import events as ev
from gol_tpu.engine import (
    Engine,
    EngineKilled,
    FLAG_KILL,
    FLAG_PAUSE,
    FLAG_QUIT,
)
from gol_tpu.io.pgm import input_path, output_path, read_pgm, write_pgm
from gol_tpu.params import Params
from gol_tpu.utils.cell import alive_cells_from_board

ALIVE_POLL_SECONDS = 2.0  # reference ticker (`Local/gol/distributor.go:58`)

# Process-local default engine. A module global on purpose: it outlives
# individual `run` calls, which is what makes in-process detach/reattach
# (`q` then `CONT=yes`) work — the reference equivalent is the broker
# process staying up holding `world`/`turn` (`Server:29-30`).
_default_engine: Optional[Engine] = None
_default_engine_lock = threading.Lock()


def _resolve_engine():
    ser = os.environ.get("SER", "")
    if ser:
        from gol_tpu.client import RemoteEngine

        return RemoteEngine(ser)
    global _default_engine
    with _default_engine_lock:
        if _default_engine is None or _default_engine._killed:
            _default_engine = Engine()
        return _default_engine


def _sub_workers() -> List[str]:
    """Worker list from SUB (comma separated); its *length* is the shard
    count request (`Local/gol/distributor.go:100-105` defaults to four
    workers on ports 8030-8060)."""
    sub = os.environ.get("SUB", "")
    if not sub:
        return []
    return [a for a in sub.split(",") if a]


def distributor(
    p: Params,
    events_q: "queue.Queue",
    key_presses: Optional["queue.Queue"] = None,
    engine=None,
    images_dir: Optional[str] = None,
    out_dir: Optional[str] = None,
    live_view: bool = False,
) -> None:
    images_dir = images_dir or os.environ.get("GOL_IMAGES", "images")
    out_dir = out_dir or os.environ.get("GOL_OUT", "out")
    engine = engine if engine is not None else _resolve_engine()

    width, height = p.image_width, p.image_height
    done = threading.Event()
    kp_state = {"k": False}

    # Attach: discard control flags left by a previous controller session
    # BEFORE this session's keypress thread starts posting its own.
    try:
        engine.drain_flags()
    except (EngineKilled, ConnectionError, OSError, AttributeError):
        pass

    # -- keypress goroutine (`Local/gol/distributor.go:107-152`) ----------
    def keypress_loop() -> None:
        paused = False
        while not done.is_set():
            try:
                key = key_presses.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                if key == "s":
                    world, turn = engine.get_world()
                    fname = output_path(width, height, turn, out_dir)
                    write_pgm(fname, world)
                    events_q.put(
                        ev.ImageOutputComplete(turn, os.path.basename(fname))
                    )
                elif key == "p":
                    engine.cf_put(FLAG_PAUSE)
                    _, turn = engine.alive_count()
                    paused = not paused
                    if paused:
                        events_q.put(ev.StateChange(turn, ev.State.PAUSED))
                    else:
                        print("Continuing")
                        events_q.put(
                            ev.StateChange(turn, ev.State.EXECUTING)
                        )
                elif key == "q":
                    engine.cf_put(FLAG_QUIT)
                elif key == "k":
                    kp_state["k"] = True
                    engine.cf_put(FLAG_KILL)
            except (EngineKilled, ConnectionError, OSError):
                return
            except RuntimeError:
                # Transient engine state (e.g. snapshot requested before the
                # board is loaded) — drop this keypress, keep serving.
                continue

    # -- 2 s alive ticker (`Local/gol/distributor.go:154-167`) ------------
    def ticker_loop() -> None:
        while not done.wait(ALIVE_POLL_SECONDS):
            try:
                alive, turn = engine.alive_count()
            except (EngineKilled, ConnectionError, OSError):
                return
            events_q.put(ev.AliveCellsCount(turn, alive))

    # -- live view feed: CellsFlipped diffs + TurnComplete ----------------
    # The reference defines CellFlipped/TurnComplete but never emits them
    # (`Local/gol/event.go:92-110`, SURVEY §7 step 5); here the live view
    # polls board snapshots at frame rate and emits batched diffs, so view
    # cost is one device→host transfer per rendered frame, not per cell.
    def live_loop() -> None:
        prev = None
        prev_turn = -1
        while not done.wait(0.1):
            try:
                world, turn = engine.get_world()
            except (EngineKilled, ConnectionError, OSError, RuntimeError):
                continue
            if turn == prev_turn:
                continue
            cur = world != 0
            if prev is None:
                ys, xs = np.nonzero(cur)
            else:
                ys, xs = np.nonzero(cur != prev)
            if len(xs):
                events_q.put(ev.CellsFlipped(
                    turn, tuple(zip(xs.tolist(), ys.tolist()))
                ))
            events_q.put(ev.TurnComplete(turn))
            prev, prev_turn = cur, turn

    if key_presses is not None:
        threading.Thread(target=keypress_loop, daemon=True).start()
    threading.Thread(target=ticker_loop, daemon=True).start()
    if live_view:
        threading.Thread(target=live_loop, daemon=True).start()

    try:
        # -- board source: fresh from PGM, or reattach (`:171-178`) -------
        start_turn = 0
        if os.environ.get("CONT", "") == "yes":
            world, start_turn = engine.get_world()
            turns_left = max(p.turns - start_turn, 0)
        else:
            world = read_pgm(input_path(width, height, images_dir))
            turns_left = p.turns

        events_q.put(ev.StateChange(start_turn, ev.State.EXECUTING))

        # -- blocking run (`:182`) ----------------------------------------
        run_params = Params(
            threads=p.threads,
            image_width=width,
            image_height=height,
            turns=turns_left,
        )
        try:
            final_world, final_turn = engine.server_distributor(
                run_params, world, _sub_workers(), start_turn=start_turn
            )
        except EngineKilled:
            final_world, final_turn = world, start_turn

        # -- finalize (`:187-226`) ----------------------------------------
        alive_cells = alive_cells_from_board(final_world)
        events_q.put(
            ev.FinalTurnComplete(
                final_turn, tuple((c.x, c.y) for c in alive_cells)
            )
        )
        fname = output_path(width, height, final_turn, out_dir)
        write_pgm(fname, final_world)
        events_q.put(
            ev.ImageOutputComplete(final_turn, os.path.basename(fname))
        )
        if kp_state["k"]:
            try:
                engine.kill_prog()
            except (EngineKilled, ConnectionError, OSError):
                pass
        events_q.put(ev.StateChange(final_turn, ev.State.QUITTING))
    finally:
        done.set()
        events_q.put(ev.CLOSE)
