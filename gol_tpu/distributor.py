"""The controller distributor: orchestrates one full run.

Re-design of reference `Local/gol/distributor.go:55-226`. Same observable
contract — load `images/WxH.pgm`, drive the engine, emit the event stream,
honour s/p/q/k keypresses (plus 'c': durable checkpoint on demand),
tick alive counts every 2 s, write
`out/WxHxT.pgm`, support detach (`q`) / reattach (`CONT=yes`) — but the
engine link is either an in-process `Engine` (default; the "no real
cluster" test story) or a remote engine over the TCP control plane when
`SER` is set, mirroring the reference env config
(`Local/gol/distributor.go:90-105`).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import List, Optional

import numpy as np

from gol_tpu import events as ev
from gol_tpu.engine import (
    Engine,
    EngineBusy,
    EngineKilled,
    FLAG_KILL,
    FLAG_PAUSE,
    FLAG_QUIT,
)
from gol_tpu.io.pgm import input_path, output_path, read_pgm, write_pgm
from gol_tpu.obs import trace as obs_trace
from gol_tpu.obs.log import log as obs_log
from gol_tpu.params import Params
from gol_tpu.utils.cell import alive_cells_from_board
from gol_tpu.utils.envcfg import env_float, env_int

ALIVE_POLL_SECONDS = 2.0  # reference ticker (`Local/gol/distributor.go:58`)

# GOL_LIVE_MAX_CELLS: largest frame (in cells) the live view moves per
# poll. Boards above it stream a DOWNSAMPLED view via `engine.get_view`
# — O(cap) per frame instead of the full board (a 65536² frame through
# get_world would be 4.3 GB per poll; r5, VERDICT r4 #3) — with a
# one-time warning that coordinates are in view space. 0 disables the
# guard (always full frames).
LIVE_MAX_CELLS_ENV = "GOL_LIVE_MAX_CELLS"
LIVE_MAX_CELLS_DEFAULT = 1 << 21

# GOL_RECONNECT=<seconds>: how long a controller keeps trying to reattach
# to a lost REMOTE engine before giving up (0 disables). Beyond-reference
# failure recovery (its controller does `log.Fatal` on dial errors,
# `Local/gol/distributor.go:96-98`): on connection loss mid-run the
# controller emits EngineLost, polls ping until the engine answers, then
# resumes from the engine's authoritative (world, turn) — or resubmits its
# own last-known board when the engine came back empty (fresh restart
# without a checkpoint).
RECONNECT_ENV = "GOL_RECONNECT"
RECONNECT_DEFAULT = 10.0

# Process-local default engine. A module global on purpose: it outlives
# individual `run` calls, which is what makes in-process detach/reattach
# (`q` then `CONT=yes`) work — the reference equivalent is the broker
# process staying up holding `world`/`turn` (`Server:29-30`).
_default_engine: Optional[Engine] = None
_default_engine_lock = threading.Lock()


def _resolve_rule(rule=None):
    """The rule for in-process engines AND controller-side io semantics:
    an explicit argument wins, else GOL_RULE env — life-like ('B36/S23'
    HighLife) or Generations ('/2/3' Brian's Brain) — default Conway.
    A malformed rulestring raises — silently defaulting would corrupt a
    run. Beyond-reference: the Go kernel hardcodes Conway
    (`SubServer/distributor.go:179-201`)."""
    from gol_tpu.models import parse_rule
    from gol_tpu.models.lifelike import CONWAY

    if rule is not None:
        return rule
    s = os.environ.get("GOL_RULE", "")
    return parse_rule(s) if s else CONWAY


# Process-local sparse engine (one, keyed by (size, rulestring)): the
# sparse analog of `_default_engine` — it outlives `run` calls so the
# in-process detach/reattach contract (`q` then CONT=yes) holds for
# sparse runs too.
_default_sparse: dict = {}


def _resolve_sparse_engine(size: int, rule=None):
    from gol_tpu.sparse_engine import SparseEngine

    ser = os.environ.get("SER", "")
    if ser:
        from gol_tpu.client import RemoteEngine

        return RemoteEngine(ser)
    rule = _resolve_rule(rule)
    key = (size, rule.rulestring)
    with _default_engine_lock:
        eng = _default_sparse.get(key)
        if eng is None or eng._killed:
            _default_sparse.clear()  # one held sparse state at a time
            eng = SparseEngine(size, rule=rule)
            _default_sparse[key] = eng
        return eng


def _resolve_engine(rule=None):
    ser = os.environ.get("SER", "")
    if ser:
        from gol_tpu.client import RemoteEngine

        return RemoteEngine(ser)
    rule = _resolve_rule(rule)
    global _default_engine
    with _default_engine_lock:
        if _default_engine is None or _default_engine._killed:
            _default_engine = Engine(rule=rule)
        elif _default_engine._rule != rule:
            if _default_engine._cells is not None:
                # The engine holds detached (world, turn) state — the
                # CONT=yes contract. Its own rule stays authoritative
                # (same stance as the checkpoint rule guard); a rebuild
                # here would silently discard the board.
                import warnings

                warnings.warn(
                    f"engine holds a detached board under rule "
                    f"{_default_engine._rule.rulestring}; ignoring "
                    f"requested rule {rule.rulestring}")
            else:
                _default_engine = Engine(rule=rule)
        return _default_engine


def _sub_workers() -> List[str]:
    """Worker list from SUB (comma separated); its *length* is the shard
    count request (`Local/gol/distributor.go:100-105` defaults to four
    workers on ports 8030-8060)."""
    sub = os.environ.get("SUB", "")
    if not sub:
        return []
    return [a for a in sub.split(",") if a]


def _await_engine(engine, budget_s: float) -> None:
    """Poll `engine.ping()` with a short backoff until it answers or the
    budget runs out (re-raising the last connection error). An EngineKilled
    answer propagates — a deliberately killed engine is not 'lost'."""
    deadline = time.monotonic() + budget_s
    while True:
        try:
            engine.ping()
            return
        except (ConnectionError, OSError):
            if time.monotonic() >= deadline:
                raise
            time.sleep(min(0.5, budget_s / 10))


def distributor(
    p: Params,
    events_q: "queue.Queue",
    key_presses: Optional["queue.Queue"] = None,
    engine=None,
    images_dir: Optional[str] = None,
    out_dir: Optional[str] = None,
    live_view: bool = False,
    rule=None,
    sparse: bool = False,
) -> None:
    """`sparse`: run on the sparse-torus engine — Params' width/height
    give the TORUS size, the board source is the small seed pattern
    `<images_dir>/seed.pgm` (its live cells stamped centred), snapshots
    and the final PGM are the live WINDOW (named by window dims), and
    FinalTurnComplete carries torus-coordinate cells."""
    images_dir = images_dir or os.environ.get("GOL_IMAGES", "images")
    out_dir = out_dir or os.environ.get("GOL_OUT", "out")

    if sparse and live_view:
        # The live view renders into a Params-sized display (a 2^20
        # torus would be a 1 TiB buffer) with window-LOCAL coordinates —
        # neither is meaningful for a sparse run yet. Snapshots ('s')
        # are the sparse visualisation path.
        import warnings

        warnings.warn("live view is not supported for sparse runs; "
                      "disabled (use 's' snapshots instead)")
        live_view = False

    width, height = p.image_width, p.image_height
    # The trace root for the whole run: it rides this thread's context
    # stack (so the submit below — local engine.run or remote
    # rpc.ServerDistributor — parents under it) and its captured context
    # is handed to the helper threads, whose keypress/ticker spans would
    # otherwise each start an unrelated trace.
    run_span = obs_trace.start(
        "controller.run",
        attrs={"w": width, "h": height, "turns": p.turns,
               "sparse": bool(sparse)})
    obs_trace.TRACER.push(run_span)
    root_ctx = run_span.context()
    done = threading.Event()
    helper_threads: list = []
    kp_state = {"k": False}
    # Shared pause state (keypress thread toggles, recovery loop reads
    # and resets): a controller-local bool could silently invert against
    # the engine across a loss/reattach cycle.
    pause_requested = threading.Event()
    # Set for the span of a loss episode (EngineLost .. reattach reset):
    # 'p' presses inside it are dropped — a pause flag posted to an
    # engine whose run is being torn down/resubmitted pairs with nothing
    # and would invert controller-vs-engine pause state.
    in_recovery = threading.Event()

    # Engine resolution can fail (backend init, bad GOL_RULE, …) — it
    # must happen under the finally that delivers CLOSE, or every
    # consumer of the events queue hangs forever on a failed startup.
    try:
        if engine is None:
            engine = (_resolve_sparse_engine(width, rule) if sparse
                      else _resolve_engine(rule))
        # Controller-side io semantics for the rule FAMILY: PGM value
        # levels and the firing-cell pixel mask. For remote engines the
        # server's --rule governs evolution; GOL_RULE here mirrors it
        # for io (documented on both --rule flags). Multi-state boards
        # use the gray encoding of `models/generations.gray_levels`;
        # "alive" is the firing state's 255 pixel — which for life-like
        # boards is exactly the reference's 255-cell contract.
        io_rule = _resolve_rule(rule)
        from gol_tpu.models.generations import GenerationsRule, gray_levels

        pgm_levels = (tuple(gray_levels(io_rule).tolist())
                      if isinstance(io_rule, GenerationsRule) else None)
    except BaseException as e:
        done.set()
        # The run span was already pushed: unwind it or it stays on this
        # thread's context stack forever and every later send_msg from
        # this thread would inherit a dead trace context.
        obs_trace.TRACER.pop(run_span)
        obs_trace.finish(run_span,
                         error=e if isinstance(e, Exception) else None)
        events_q.put(ev.CLOSE)
        raise

    # -- keypress goroutine (`Local/gol/distributor.go:107-152`) ----------
    def keypress_loop() -> None:
        while not done.is_set():
            try:
                key = key_presses.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                # One span per handled keypress, parented to the run
                # root (this thread's own stack is empty). Names clamp
                # to the known keys — span names must stay bounded.
                kname = (key if key in ("s", "p", "q", "k", "c")
                         else "other")
                with obs_trace.span(f"controller.key.{kname}",
                                    parent=root_ctx):
                    if key == "s":
                        if sparse:
                            win, _, turn = engine.get_window()
                            fname = output_path(
                                win.shape[1], win.shape[0], turn, out_dir)
                            write_pgm(fname, win)
                        else:
                            world, turn = engine.get_world()
                            fname = output_path(
                                width, height, turn, out_dir)
                            write_pgm(fname, world, levels=pgm_levels)
                        events_q.put(
                            ev.ImageOutputComplete(
                                turn, os.path.basename(fname))
                        )
                    elif key == "p":
                        if in_recovery.is_set():
                            continue  # see in_recovery above
                        engine.cf_put(FLAG_PAUSE)
                        # The flag is committed: toggle shared state
                        # BEFORE the (fallible) turn poll, or a transient
                        # failure there would leave controller and engine
                        # pause-inverted for the rest of the run.
                        paused = not pause_requested.is_set()
                        if paused:
                            pause_requested.set()
                        else:
                            pause_requested.clear()
                        try:
                            _, turn = engine.alive_count()
                        except (ConnectionError, OSError, RuntimeError):
                            turn = 0
                        if paused:
                            events_q.put(
                                ev.StateChange(turn, ev.State.PAUSED))
                        else:
                            print("Continuing")
                            events_q.put(
                                ev.StateChange(turn, ev.State.EXECUTING)
                            )
                    elif key == "c":
                        # Durable manifest checkpoint on demand —
                        # in-process engines write to GOL_CKPT, remote
                        # ones via the Checkpoint wire method into the
                        # server's configured directory.
                        name, turn = engine.checkpoint_now(
                            trigger="manual")
                        print(f"checkpointed turn {turn} "
                              f"({os.path.basename(name)})")
                    elif key == "q":
                        engine.cf_put(FLAG_QUIT)
                    elif key == "k":
                        kp_state["k"] = True
                        engine.cf_put(FLAG_KILL)
            except EngineKilled:
                return
            except (ConnectionError, OSError):
                # Engine outage: drop this keypress but keep serving — the
                # run loop may reattach (GOL_RECONNECT) and later keys must
                # still work.
                continue
            except RuntimeError:
                # Transient engine state (e.g. snapshot requested before the
                # board is loaded) — drop this keypress, keep serving.
                continue
            except ValueError:
                # A snapshot write can reject the payload (e.g. a remote
                # multi-state engine's gray pixels against this
                # controller's {0,255}/levels expectation when GOL_RULE
                # doesn't mirror the server's --rule). Losing one 's' is
                # recoverable; losing the keypress THREAD would strand
                # q/k/p for the rest of the run.
                continue

    # -- 2 s alive ticker (`Local/gol/distributor.go:154-167`) ------------
    def ticker_loop() -> None:
        while not done.wait(ALIVE_POLL_SECONDS):
            try:
                with obs_trace.span("controller.tick", parent=root_ctx):
                    alive, turn = engine.alive_count()
            except EngineKilled:
                return
            except (ConnectionError, OSError, RuntimeError):
                # Outage or a transient wrapped server error: keep the
                # telemetry thread alive for the rest of the run.
                continue
            events_q.put(ev.AliveCellsCount(turn, alive))

    # -- live view feed: CellsFlipped diffs + TurnComplete ----------------
    # The reference defines CellFlipped/TurnComplete but never emits them
    # (`Local/gol/event.go:92-110`, SURVEY §7 step 5); here the live view
    # polls board snapshots at frame rate and emits batched diffs, so view
    # cost is one device→host transfer per rendered frame, not per cell.
    def live_loop() -> None:
        prev = None
        prev_turn = -1
        cap = env_int(LIVE_MAX_CELLS_ENV, LIVE_MAX_CELLS_DEFAULT,
                      minimum=0)
        use_view = (cap > 0 and width * height > cap
                    and hasattr(engine, "get_view"))
        if use_view:
            import warnings

            warnings.warn(
                f"live view: board {width}x{height} exceeds "
                f"GOL_LIVE_MAX_CELLS={cap}; streaming a downsampled "
                f"view (O(viewport) bytes/frame, coordinates in view "
                f"space)")
        while not done.wait(0.1):
            try:
                if use_view:
                    world, turn, _f = engine.get_view(cap)
                else:
                    world, turn = engine.get_world()
            except RuntimeError as e:
                if use_view and "unknown method" in str(e):
                    # Pre-0.5 remote server without GetView: better a
                    # slow full-frame view than a silently blank one.
                    import warnings

                    warnings.warn(
                        "engine does not serve GetView (older server); "
                        "live view falls back to FULL board frames — "
                        "expect heavy transfers at this board size")
                    use_view = False
                continue
            except (EngineKilled, ConnectionError, OSError):
                continue
            if turn == prev_turn:
                continue
            cur = world != 0
            if prev is not None and prev.shape != cur.shape:
                prev = None  # window regrew (sparse engine): full repaint
            if prev is None:
                ys, xs = np.nonzero(cur)
            else:
                ys, xs = np.nonzero(cur != prev)
            if len(xs):
                events_q.put(ev.CellsFlipped(
                    turn, tuple(zip(xs.tolist(), ys.tolist()))
                ))
            events_q.put(ev.TurnComplete(turn))
            prev, prev_turn = cur, turn

    try:
        # Attach: discard control flags left by a previous controller
        # session BEFORE this session's keypress thread starts posting its
        # own. Inside the CLOSE-delivering try — ANY attach-time failure
        # (incl. RuntimeError from a client-wrapped server error,
        # `client.py:40-47`) must still close the events queue, or every
        # consumer hangs forever (round-3 regression, VERDICT weak #2).
        try:
            engine.drain_flags()
        except (EngineKilled, ConnectionError, OSError, AttributeError,
                RuntimeError):
            pass

        if sparse:
            # A REMOTE sparse engine's torus size is server state
            # (`--sparse SIZE`); a mismatched controller would silently
            # wrap final torus coordinates by the wrong modulus. Fail
            # fast on a definite mismatch; transport errors here are
            # not a verdict and fall through to the run itself.
            try:
                board = (engine.stats() or {}).get("board")
            except (EngineKilled, ConnectionError, OSError,
                    RuntimeError, AttributeError):
                board = None
            if board is not None and tuple(board) != (height, width):
                raise ValueError(
                    f"engine torus is {board[1]}x{board[0]} but Params "
                    f"say {width}x{height} — match the server's "
                    f"--sparse SIZE")

        if key_presses is not None:
            helper_threads.append(threading.Thread(
                target=keypress_loop, daemon=True))
        helper_threads.append(threading.Thread(
            target=ticker_loop, daemon=True))
        if live_view:
            helper_threads.append(threading.Thread(
                target=live_loop, daemon=True))
        for t in helper_threads:
            t.start()

        # -- board source: fresh from PGM, or reattach (`:171-178`) -------
        start_turn = 0
        if sparse:
            if os.environ.get("CONT", "") == "yes":
                # The engine's held window IS the state; only the resume
                # arithmetic travels.
                world = None
                start_turn = engine.ping()
                turns_left = max(p.turns - start_turn, 0)
            else:
                world = read_pgm(os.path.join(images_dir, "seed.pgm"))
                turns_left = p.turns
        elif os.environ.get("CONT", "") == "yes":
            world, start_turn = engine.get_world()
            turns_left = max(p.turns - start_turn, 0)
        else:
            src = input_path(width, height, images_dir)
            world = read_pgm(src, levels=pgm_levels)
            if world.shape != (height, width):
                # A mislabeled file would silently evolve the wrong
                # geometry under correctly-named outputs — fail here.
                raise ValueError(
                    f"{src}: image is {world.shape[1]}x{world.shape[0]} "
                    f"but Params say {width}x{height}")
            turns_left = p.turns

        if getattr(engine, "recoverable", False):
            # Attach probe: one ping teaches the client the server's
            # wire caps BEFORE the seed board is uploaded, so even the
            # first upload rides the negotiated codec path (packed
            # boards put 8× fewer bytes up). Failures fall through to
            # the submit loop's own retry/recovery story.
            try:
                engine.ping()
                caps = getattr(engine, "peer_caps", None)
                if caps:
                    obs_log("wire.caps", caps=sorted(caps))
            except (ConnectionError, OSError, EngineKilled,
                    RuntimeError):
                pass

        events_q.put(ev.StateChange(start_turn, ev.State.EXECUTING))

        # -- blocking run (`:182`), with reattach-on-loss -----------------
        # Recovery only for engines whose ConnectionError/OSError means
        # the NETWORK/peer (RemoteEngine sets `recoverable`): an in-process
        # engine's OSError (e.g. full disk during checkpointing) must
        # propagate, not be mistaken for a lost connection.
        reconnect_budget = env_float(RECONNECT_ENV, RECONNECT_DEFAULT)
        recoverable = (
            reconnect_budget > 0 and getattr(engine, "recoverable", False))
        lost_pending = False       # a loss episode awaits its Reattached
        recovery_deadline = None   # bound on one recovery episode
        recovering = False         # a loss has happened on this run

        def _close_recovery(turn: int) -> None:
            """A pause cannot survive engine loss (see the reattach
            drain): reset the shared pause state and tell consumers the
            run resumes executing. Shared by BOTH reattach paths."""
            if pause_requested.is_set():
                pause_requested.clear()
                events_q.put(ev.StateChange(turn, ev.State.EXECUTING))

        while True:
            run_params = Params(
                threads=p.threads,
                image_width=width,
                image_height=height,
                turns=turns_left,
            )
            submit_t = time.monotonic()
            try:
                final_world, final_turn = engine.server_distributor(
                    run_params, world, _sub_workers(), start_turn=start_turn
                )
                if lost_pending:
                    # The resubmit itself proved contact (a reattach whose
                    # get_world still failed): close the Lost episode so
                    # consumers always see paired Lost/Reattached events.
                    events_q.put(ev.EngineReattached(final_turn))
                    lost_pending = False
                    _close_recovery(final_turn)
                in_recovery.clear()
                break
            except EngineKilled:
                final_world, final_turn = world, start_turn
                break
            except (ConnectionError, OSError):
                if not recoverable:
                    raise
                recovering = True
                now = time.monotonic()
                if now - submit_t > reconnect_budget:
                    # The failed submission outlived a WHOLE budget of
                    # wall clock before dying: a NEW outage, not the old
                    # episode still flapping — grant a fresh budget. Wall
                    # clock (not observed turn progress) is deliberately
                    # the only refresh signal: get_world is not
                    # token-scoped, so an advancing turn could be a
                    # FOREIGN controller's run (or a deterministic
                    # restore-and-recrash loop) and refreshing on it
                    # would unbound the give-up deadline. The cost is
                    # that a run losing its engine after less than one
                    # budget of compute inherits the previous episode's
                    # remaining budget — bounded unfairness, preferred
                    # over unbounded retries.
                    recovery_deadline = None
                if recovery_deadline is None:
                    recovery_deadline = now + reconnect_budget
                elif now >= recovery_deadline:
                    raise  # episode budget exhausted — stop flapping
                else:
                    time.sleep(0.1)  # damp a flapping link's retry spin
                in_recovery.set()
                if not lost_pending:
                    events_q.put(ev.EngineLost(start_turn))
                    lost_pending = True
                try:
                    _await_engine(
                        engine, max(recovery_deadline - now, 0.0))
                except EngineKilled:
                    final_world, final_turn = world, start_turn
                    break
            except EngineBusy:
                # After a TRANSIENT partition the server never saw the
                # dead socket, so this run's pre-partition orphan still
                # occupies the engine. abort_run is token-scoped — it
                # stops OUR orphan and is a no-op on a foreign
                # controller's run, which then keeps failing the resubmit
                # until the episode deadline re-raises here.
                # `recovering` marks an active recovery episode (every
                # path that sets it also arms the deadline): EngineBusy
                # on a FIRST submission is a foreign-run conflict and
                # propagates.
                if not (recovering and hasattr(engine, "abort_run")):
                    raise
                in_recovery.set()  # busy retries are recovery too
                if time.monotonic() >= recovery_deadline:
                    raise
                try:
                    engine.abort_run()
                except EngineKilled:
                    final_world, final_turn = world, start_turn
                    break
                except (ConnectionError, OSError, RuntimeError):
                    pass
                time.sleep(0.3)

            # -- reattach: refresh state, then resubmit ------------------
            contacted = True
            try:
                # Engine is back with authoritative state (it survived, or
                # was restarted from a checkpoint): resume from it. Sparse
                # runs resume the ENGINE-HELD window (world stays None —
                # a window snapshot re-stamped as a seed would lose its
                # torus origin); an engine that came back empty fails the
                # resubmit with "no sparse state to resume".
                if sparse:
                    start_turn = engine.ping()
                    world = None
                else:
                    world, start_turn = engine.get_world()
            except EngineKilled:
                final_world, final_turn = world, start_turn
                break
            except RuntimeError:
                # Engine answered but restarted empty: resubmit the
                # last-known board from the last-known turn —
                # deterministic re-evolution.
                pass
            except (ConnectionError, OSError):
                # Flapped again between ping and snapshot: contact is NOT
                # restored — no Reattached event; the resubmit below will
                # fail back into the recovery branch.
                contacted = False
            turns_left = max(p.turns - start_turn, 0)
            if lost_pending and contacted:
                events_q.put(ev.EngineReattached(start_turn))
                lost_pending = False
                _close_recovery(start_turn)
            if contacted:
                try:
                    # Wipe PAUSE flags stranded by the pre-loss session
                    # (a SURVIVED engine's queue may hold a pause posted
                    # at the instant of loss that the aborted orphan
                    # never consumed) so the resubmitted run really does
                    # start unpaused. pause_only: a stranded quit/kill
                    # is an idempotent order the resubmitted run SHOULD
                    # honour. Runs on EVERY recovery cycle because it is
                    # a no-op while our orphan still occupies the engine
                    # — only after the EngineBusy cycle aborts the
                    # orphan (engine parked) does it actually fire.
                    # Deliberately ordered AFTER _close_recovery's pause
                    # reset: a delayed in-flight cf_put(PAUSE) that lands
                    # before this drain is wiped here, after controller
                    # pause state was already cleared. A microsecond-scale
                    # inversion window remains — a pause landing between
                    # this drain and the resubmit strands on the engine
                    # while the controller reports EXECUTING (control RPCs
                    # carry a 10 s timeout, so the straddle is rare and
                    # bounded, not impossible).
                    engine.drain_flags(pause_only=True)
                except EngineKilled:
                    final_world, final_turn = world, start_turn
                    break
                except (ConnectionError, OSError, RuntimeError,
                        AttributeError, TypeError):
                    pass
            # 'p' presses may flow into the resubmission (ordered after
            # the pause reset — both happen on this thread); a pre-run
            # pause posted now is consumed by the next run and pairs
            # with the keypress thread's toggle, which is consistent.
            in_recovery.clear()

        # -- finalize (`:187-226`) ----------------------------------------
        # Reference contract: the final event carries the alive-cell set
        # (`Local/gol_test.go:32-37`). Beyond GOL_MAX_EVENT_CELLS total
        # cells, only the count travels — a 65536² board's ~10^9
        # coordinate tuples would exhaust controller memory.
        max_event_cells = env_int(
            "GOL_MAX_EVENT_CELLS", 1 << 24, minimum=0)
        # The firing mask (pixel == 255): identical to != 0 for life-like
        # {0,255} boards; for multi-state boards it selects state-1 cells,
        # matching the engine's AliveCellsCount semantics.
        if sparse:
            # Final cells in TORUS coordinates: window nonzeros offset by
            # the window's torus origin (engine parked — state intact).
            origin = None
            try:
                win, origin, _ = engine.get_window()
            except (EngineKilled, ConnectionError, OSError, RuntimeError):
                # State unreachable (engine killed/lost): fall back to
                # the run's last-known pixels — the torus ORIGIN is lost
                # with the engine, so only the count travels, never
                # wrongly-wrapped coordinates.
                win = final_world
            if win is None:
                # Killed before any state on a resume-only run: nothing
                # to report or write.
                events_q.put(ev.FinalTurnComplete(final_turn, (), 0))
                fname = None
            else:
                ys, xs = np.nonzero(win)
                if origin is not None and len(xs) <= max_event_cells:
                    ox, oy = origin
                    alive = tuple(
                        (int((x + ox) % width), int((y + oy) % height))
                        for x, y in zip(xs, ys))
                    count = len(alive)
                else:
                    alive = ()
                    count = int(len(xs))
                events_q.put(ev.FinalTurnComplete(final_turn, alive, count))
                fname = output_path(
                    win.shape[1], win.shape[0], final_turn, out_dir)
                write_pgm(fname, win)
        else:
            if final_world.size <= max_event_cells:
                alive_cells = alive_cells_from_board(final_world == 255)
                alive = tuple((c.x, c.y) for c in alive_cells)
                count = len(alive)
            else:
                alive = ()
                count = int((final_world == 255).sum())
            events_q.put(ev.FinalTurnComplete(final_turn, alive, count))
            fname = output_path(width, height, final_turn, out_dir)
            write_pgm(fname, final_world, levels=pgm_levels)
        if fname is not None:
            events_q.put(
                ev.ImageOutputComplete(final_turn, os.path.basename(fname))
            )
        if kp_state["k"]:
            try:
                engine.kill_prog()
            except (EngineKilled, ConnectionError, OSError):
                pass
        events_q.put(ev.StateChange(final_turn, ev.State.QUITTING))
    finally:
        done.set()
        obs_trace.TRACER.pop(run_span)
        obs_trace.finish(run_span)
        events_q.put(ev.CLOSE)
        # Bounded join of the helper threads AFTER CLOSE is delivered:
        # a daemon ticker still inside a device fetch when the process
        # begins interpreter finalization aborts the native runtime
        # ("terminate called ..." at exit). done is set, so each loop
        # exits as soon as its in-flight poll completes; the timeout
        # keeps a wedged engine from blocking the caller forever.
        for t in helper_threads:
            t.join(timeout=5.0)
