# Convenience targets; everything also works as plain commands.

.PHONY: test bench obs-smoke ckpt-smoke wire-smoke perf-smoke fleet-smoke load-smoke broadcast-smoke mesh-smoke fleet-mesh-smoke chaos-smoke federation-smoke migrate-smoke fuse-smoke fleet-obs-smoke journal-smoke usage-smoke conv-smoke smoke perf-gate native fixtures clean

test:
	python -m pytest tests/ -q

bench:
	python bench.py

# End-to-end observability check, CPU-only: tiny board with
# --run-report + --metrics-port 0, validates the run report schema and
# the Prometheus /metrics output (tools/obs_smoke.py).
obs-smoke:
	JAX_PLATFORMS=cpu python tools/obs_smoke.py

# End-to-end checkpoint/restore check, CPU-only: SIGKILL a real server
# mid-run, --resume a replacement bit-identically, refuse corrupted
# payloads, prove retention safety (tools/ckpt_smoke.py).
ckpt-smoke:
	JAX_PLATFORMS=cpu python tools/ckpt_smoke.py

# End-to-end wire data-plane check, CPU-only: loopback server/client
# capability handshake, packed + zlib + xrle codec round-trips, raw-u8
# old-peer fallback, host/device bitpack parity (tools/wire_smoke.py).
wire-smoke:
	JAX_PLATFORMS=cpu python tools/wire_smoke.py

# Headless chunk-loop overhead check, CPU-only: run the bench.py
# --overhead matrix (512²/1024² × no viewer / 1 viewer / viewer+ckpt),
# then gate the measured chunk_overhead_us legs against the committed
# BASELINE.json ceilings — this also runs the baseline-integrity audit,
# so an unwaivered lowered anchor fails here too.
perf-smoke:
	mkdir -p out
	JAX_PLATFORMS=cpu python bench.py --overhead --turns 2048 \
		| tee out/perf_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/perf_smoke.jsonl

# Fleet aggregate-throughput check, CPU-only: bench.py --fleet runs
# 1/64/512 resident 512² boards in one FleetEngine against a
# wire-driven single run, then gates the aggregate cups, the >=10x
# speedup, and the fleet chunk_overhead_us ceiling against
# BASELINE.json (same metric names as the full-window bench; the
# short window only shrinks the measurement, not the topology).
fleet-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --fleet \
		--fleet-window 1.0 | tee out/fleet_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/fleet_smoke.jsonl

# Serving-SLO load check, CPU-only: bench.py --load drives concurrent
# create/attach/view/flag/destroy clients against an in-process fleet
# server (tools/load_smoke.py) and gates the client-observed
# per-method rpc p50/p99 ms lines against the committed BASELINE.json
# ceilings (lower is better).
load-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --load \
		| tee out/load_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/load_smoke.jsonl

# Broadcast fan-out check, CPU-only: bench.py --broadcast parks 1000
# Subscribe spectators (2 tracked decoders + a ViewerPool draining
# bytes) on one advancing run behind the selectors gateway; the
# encode_calls_per_published_frame witness must be exactly 1.0 and the
# viewer_fanout_p99_ms ceiling gates via BASELINE.json.
# tools/broadcast_smoke.py then proves encode-once, shared-byte
# parity, the slow-subscriber skip-to-keyframe policy, DestroyRun
# view-cache eviction + end sentinel, socket options, and the
# gol_bcast_*/gol_gateway_* families end to end.
broadcast-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --broadcast \
		| tee out/broadcast_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/broadcast_smoke.jsonl
	JAX_PLATFORMS=cpu python tools/broadcast_smoke.py

# Chaos-hardening check, CPU-only: bench.py --chaos drives the same
# seed twice over loopback TCP (clean, then under the seeded GOL_CHAOS
# fault spec) and must end bit-identical with faults actually injected;
# the availability_pct floor and rpc_retries_per_call ceiling gate via
# BASELINE.json (tight flags — the artifact only overlaps the two chaos
# metrics). tools/chaos_smoke.py then exercises SIGTERM graceful drain,
# SIGKILL-then-restart (quarantines nothing), and a poisoned fleet run
# (quarantined exactly once, auto-restored bit-identically).
chaos-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --chaos \
		| tee out/chaos_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/chaos_smoke.jsonl \
		--noise-floor 0.2 --max-regression 1
	JAX_PLATFORMS=cpu python tools/chaos_smoke.py

# Multi-device scaling telemetry check, CPU-only with 8 forced host
# devices: one 4-way bench.py --mesh leg in-process, validating the
# gol_mesh_*/gol_halo_*/imbalance families, the /healthz mesh stamp,
# and the BASELINE.json scaling_efficiency_pct / halo_overlap_pct
# floors (higher is better) via tools/perf_compare.py
# (tools/mesh_smoke.py).
mesh-smoke:
	JAX_PLATFORMS=cpu python tools/mesh_smoke.py

# Mesh-sharded fleet check, CPU-only with 8 forced host devices: a
# reduced bench.py --fleet --mesh matrix (1-way baseline + one 4-way
# batch-sharded leg, 64 resident 512² runs) in-process — the 4-way
# board must be bit-identical to the 1-device fleet, zero new step
# signatures inside the window, the placement mesh stamped in detail
# and in gol_fleet_mesh_devices / gol_fleet_device_resident_runs, and
# the per-device cups + fleet_scaling_efficiency_pct floors gate via
# BASELINE.json (tools/fleet_mesh_smoke.py).
fleet-mesh-smoke:
	JAX_PLATFORMS=cpu python tools/fleet_mesh_smoke.py

# Federation failover check, CPU-only: bench.py --federation runs 3
# real --fleet --federate servers behind an in-process router, lets
# the GOL_CHAOS kill_member hook SIGKILL the member owning run 0
# mid-traffic, and must stay bit-identical to an unkilled control
# fleet; the availability_pct floor and the failover_downtime_p99_ms /
# router_overhead_p99_ms ceilings gate via BASELINE.json.
# tools/federation_smoke.py then proves membership, HRW placement,
# adoption, viewer re-route, and the gol_fed_* families end to end.
federation-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --federation \
		| tee out/federation_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/federation_smoke.jsonl
	JAX_PLATFORMS=cpu python tools/federation_smoke.py

# Live-migration check, CPU-only: bench.py --migrate runs 3 real
# --fleet --federate members behind an in-process router, live-
# migrates runs between members under routed read traffic (one
# migrate_fail rollback sub-leg, one kill_member@migrating SIGKILL
# sub-leg — every injected failure must end rolled back with exactly
# one authoritative copy), and must stay bit-identical to an
# unmigrated control fleet AND the device torus replay; the
# migration_downtime_p99_ms ceiling and availability_pct floor gate
# via BASELINE.json. tools/migrate_smoke.py then proves the Rescale
# cutover, the retryable "moved:" straggler answer, and rollback
# re-migratability end to end.
migrate-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --migrate \
		| tee out/migrate_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/migrate_smoke.jsonl
	JAX_PLATFORMS=cpu python tools/migrate_smoke.py

# Temporal-fusion check, CPU-only: a reduced bench.py --fuse matrix
# (k ∈ {1,4}, 512² dense + one 2-way mesh leg) run in-process, every
# leg parity-gated bit-identical vs the k=1 torus replay, the analytic
# per-turn halo observables checked against the physics (exchange
# rounds/turn = 1/k, bytes/turn CONSERVED), registry families
# validated, and the captured lines round-tripped through the
# perf_compare gate (tools/fuse_smoke.py).
fuse-smoke:
	JAX_PLATFORMS=cpu python tools/fuse_smoke.py

# Fleet telemetry-plane check, CPU-only: bench.py --fleet-obs runs two
# sequential 3-member federated fleets (heartbeat snapshots on vs off)
# under the same routed Stats window plus one SIGKILL; the
# telemetry_overhead_pct / heartbeat_payload_p99_bytes /
# alert_detection_p99_ms ceilings gate via BASELINE.json.
# tools/fleet_obs_smoke.py then proves the plane end to end: exact
# fed_agg rollups, in-budget snapshot payloads, GetTelemetry/GetAudit
# over the wire, a headless fleet_top frame, and SIGKILL ->
# member-death alert + gol-fleet-audit/1 records on disk.
fleet-obs-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --fleet-obs \
		| tee out/fleet_obs_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/fleet_obs_smoke.jsonl
	JAX_PLATFORMS=cpu python tools/fleet_obs_smoke.py

# Event-sourced run journal (PR 17): bench.py --journal measures the
# hash-chained black box's steady-state cost (journal on vs off, same
# board) and gates journal_overhead_pct <= 2% via BASELINE.json.
# tools/journal_smoke.py then proves the journal end to end: a
# federated 1000-turn run through a mid-flight SetRule and one SIGKILL
# failover, chain-verified and deterministically replayed by
# tools/replay_audit.py with bit-identical digests at every digest
# event (exit nonzero on divergence).
journal-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --journal \
		| tee out/journal_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/journal_smoke.jsonl
	JAX_PLATFORMS=cpu python tools/journal_smoke.py

# Per-run usage metering & capacity attribution (PR 19): bench.py
# --usage gates usage_overhead_pct <= 2% (meter wall share of the
# dispatch window) and usage_attribution_error_pct <= 1% (sum of
# per-run device-time shares vs measured dispatch wall), then checks
# the capacity headroom forecast against an admit-to-rejection count
# (+-10%). tools/usage_smoke.py proves the plane end to end: GetUsage
# over a real socket, /healthz usage doc, top-talker ranking,
# fleet_top --usage rendering, and the final journal usage record on
# DestroyRun.
usage-smoke:
	mkdir -p out
	set -e; JAX_PLATFORMS=cpu python bench.py --usage \
		| tee out/usage_smoke.jsonl
	python tools/perf_compare.py BASELINE.json out/usage_smoke.jsonl
	JAX_PLATFORMS=cpu python tools/usage_smoke.py

# Conv/FFT kernel-tier check, CPU-only: small-board parity only (no
# timing — `bench.py --conv` owns the gated 4096² crossover): conv and
# fft tiers bit-identical to the numpy oracle for LtL rules, the Lenia
# float32 step within 1e-4 of the float64 oracle on both tiers, real
# Engine runs for both families (incl. a lossless f32 frame to a
# CAP_F32 peer), the select_tier policy surface, and the
# gol_conv_dispatches_total / gol_kernel_tier families
# (tools/conv_smoke.py).
conv-smoke:
	JAX_PLATFORMS=cpu python tools/conv_smoke.py

# Every end-to-end smoke in one chain (CPU-only, no artifacts needed).
smoke: obs-smoke ckpt-smoke wire-smoke perf-smoke fleet-smoke load-smoke broadcast-smoke mesh-smoke fleet-mesh-smoke chaos-smoke federation-smoke migrate-smoke fuse-smoke fleet-obs-smoke journal-smoke usage-smoke conv-smoke

# Perf-regression gate: compare the latest BENCH_r*.json artifact (or
# PERF_CANDIDATE=<file>) against the committed BASELINE.json published
# metrics; exits nonzero on a >10% regression of a gated throughput
# metric (tools/perf_compare.py). Highest round number wins — mtimes
# are meaningless after a fresh checkout.
perf-gate:
	python tools/perf_compare.py BASELINE.json \
		$${PERF_CANDIDATE:-$$(ls BENCH_r*.json | sort | tail -1)}

native:
	$(MAKE) -C csrc

# Regenerate golden fixtures from the independent numpy oracle
# (check/images, check/alive; see tests/make_fixtures.py).
fixtures:
	python tests/make_fixtures.py

clean:
	$(MAKE) -C csrc clean 2>/dev/null || true
	rm -rf out .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
