"""ckpt-inspect: operator tool for gol-ckpt/1 checkpoint directories.

    python tools/ckpt_inspect.py list   DIR [--strict]
    python tools/ckpt_inspect.py verify DIR|MANIFEST
    python tools/ckpt_inspect.py diff   A B

`list` tabulates every durable checkpoint (run, turn, trigger, repr,
rule, board, alive, payload bytes, file) — malformed manifests are
skipped unless --strict. Fleet directories (PR 7) nest per-run
checkpoints in `run-<id>/` subdirectories; `list` walks those too,
labelling each row with its run id ("-" = the legacy root run). `verify` re-parses every manifest AND recomputes the
payload SHA-256 from disk (the same refusal gate `--resume` runs);
exit 1 if anything fails. `diff` compares two checkpoints (manifest
paths, or directories meaning their newest durable checkpoint) and
reports the number of differing cells — exact for every representation
via XOR-popcount on the packed families, no decode needed.

Pure stdlib + numpy: usable on a machine with no jax at all (a restore
host inspecting checkpoints written by a TPU pod)."""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gol_tpu.ckpt import manifest as mf  # noqa: E402


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n}B"


def _fleet_run_dirs(base: str) -> list:
    """Fleet layout (PR 7): per-run checkpoints live in contained
    `run-<id>` subdirectories under the configured root (the legacy
    run keeps writing at the root itself). Returns sorted
    (run_id, dir) pairs — empty for pre-fleet layouts."""
    out = []
    try:
        names = sorted(os.listdir(base))
    except OSError:
        return out
    for nm in names:
        full = os.path.join(base, nm)
        if nm.startswith("run-") and os.path.isdir(full):
            out.append((nm[len("run-"):], full))
    return out


def cmd_list(args) -> int:
    rows = [("RUN", "TURN", "TRIGGER", "REPR", "RULE", "BOARD", "ALIVE",
             "BYTES", "FILE")]
    n = 0
    scan = [("-", args.dir)] + _fleet_run_dirs(args.dir)
    for run_label, directory in scan:
        for turn, path, m in mf.list_checkpoints(directory,
                                                 strict=args.strict):
            board = m.get("board") or {}
            rows.append((
                run_label, str(turn), str(m.get("trigger", "?")),
                m["repr"], m["rule"],
                f"{board.get('h', '?')}x{board.get('w', '?')}",
                str(m.get("alive", "?")), _fmt_bytes(m["payload_bytes"]),
                os.path.basename(path)))
            n += 1
    if n == 0:
        print(f"{args.dir}: no durable checkpoints")
        return 1
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return 0


def cmd_verify(args) -> int:
    if os.path.isdir(args.path):
        targets = [(p, m) for _, p, m in
                   mf.list_checkpoints(args.path, strict=False)]
        # strict re-read below catches what the lenient listing skipped
        names = [nm for nm in sorted(os.listdir(args.path))
                 if nm.startswith(mf.CKPT_PREFIX)
                 and nm.endswith(mf.MANIFEST_SUFFIX)]
        listed = {os.path.basename(p) for p, _ in targets}
        bad_parse = [nm for nm in names if nm not in listed]
    else:
        targets, bad_parse = [(args.path, None)], []
    failures = len(bad_parse)
    for nm in bad_parse:
        print(f"FAIL  {nm}: manifest does not parse/validate")
    for path, _ in targets:
        try:
            m = mf.verify_manifest(path)
            print(f"ok    {os.path.basename(path)}: turn {m['turn']}, "
                  f"payload sha256 {m['payload_sha256'][:12]}… verified")
        except mf.CheckpointIntegrityError as e:
            failures += 1
            print(f"FAIL  {os.path.basename(path)}: {e}")
    if not targets and not bad_parse:
        print(f"{args.path}: nothing to verify")
        return 1
    print(f"{len(targets) + len(bad_parse) - failures} ok, "
          f"{failures} failed")
    return 1 if failures else 0


def _resolve_one(ref: str):
    """manifest path or directory (newest durable) -> (path, manifest)."""
    if os.path.isdir(ref):
        latest = mf.latest_checkpoint(ref)
        if latest is None:
            raise SystemExit(f"{ref}: no durable checkpoint")
        return latest[1], latest[2]
    return ref, mf.read_manifest(ref)


_POP8 = np.array([bin(i).count("1") for i in range(256)], np.uint16)


def _payload_cells(path: str, m: dict):
    """(array, kind): the payload's state array plus how to count cell
    diffs on it — 'bits' (uint32 words, XOR+popcount) or 'cells'
    (elementwise uint8 compare)."""
    with np.load(mf.payload_path(path, m)) as z:
        for key, kind in (("words", "bits"), ("gen_planes", "bits"),
                          ("sparse_words", "bits"),
                          ("gen_state", "cells"), ("world", "cells")):
            if key in z.files:
                return z[key], kind
    raise SystemExit(f"{path}: unrecognized payload members")


def cmd_diff(args) -> int:
    pa, ma = _resolve_one(args.a)
    pb, mb = _resolve_one(args.b)
    same_meta = True
    for field in ("turn", "rule", "repr", "board"):
        va, vb = ma.get(field), mb.get(field)
        marker = "" if va == vb else "   <-- differs"
        same_meta &= va == vb
        print(f"{field:6} {va!r:>24} | {vb!r}{marker}")
    print(f"{'alive':6} {ma.get('alive')!r:>24} | {mb.get('alive')!r}")
    if ma["board_sha256"] == mb["board_sha256"]:
        print("boards: IDENTICAL (board_sha256 match)")
        return 0
    if ma["repr"] != mb["repr"] or ma.get("board") != mb.get("board"):
        print("boards: DIFFER (shape/representation mismatch — "
              "cell diff unavailable)")
        return 1
    a, ka = _payload_cells(pa, ma)
    b, _ = _payload_cells(pb, mb)
    if a.shape != b.shape:
        print(f"boards: DIFFER (payload shapes {a.shape} vs {b.shape})")
        return 1
    if ka == "bits":
        xor = (a ^ b).view(np.uint8)
        ndiff = int(_POP8[xor].sum(dtype=np.int64))
    else:
        ndiff = int((a != b).sum(dtype=np.int64))
    print(f"boards: DIFFER in {ndiff} cell(s)")
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ckpt_inspect",
        description="list / verify / diff gol-ckpt/1 checkpoints")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("list", help="tabulate durable checkpoints")
    p.add_argument("dir")
    p.add_argument("--strict", action="store_true",
                   help="fail on malformed manifests instead of skipping")
    p.set_defaults(fn=cmd_list)
    p = sub.add_parser("verify",
                       help="recompute payload hashes, exit 1 on mismatch")
    p.add_argument("path", help="checkpoint directory or manifest")
    p.set_defaults(fn=cmd_verify)
    p = sub.add_parser("diff", help="compare two checkpoints")
    p.add_argument("a", help="manifest path or directory (newest)")
    p.add_argument("b", help="manifest path or directory (newest)")
    p.set_defaults(fn=cmd_diff)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
