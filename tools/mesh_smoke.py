"""mesh-smoke: prove the multi-device scaling telemetry end to end.

Runs one 4-way `bench.py --mesh` leg IN-PROCESS on CPU (8 forced host
devices), then validates every surface the leg is supposed to light up:

  * the emitted bench lines parse, are parity-clean, and carry true
    mesh geometry plus the analytic halo traffic in their detail;
  * the gol_mesh_* / gol_halo_* / gol_shard_imbalance_ratio families
    hold non-zero samples in the registry after the run (the halo
    histogram actually observed the measured walls);
  * `devstats.healthz_fields()` carries the stamped `mesh` geometry —
    the same dict `/healthz` serves;
  * tools/perf_compare.py gates the captured lines against the
    committed BASELINE.json floors (scaling_efficiency_pct /
    halo_overlap_pct, higher is better).

Exit 0 = pass.

    make mesh-smoke     # part of the `make smoke` chain
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
from contextlib import redirect_stdout

# Runnable as `python tools/mesh_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The leg needs devices; force 8 virtual host devices strictly before
# any jax backend initialisation (same guard as bench.py --mesh).
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

MESH_SMOKE_WAYS = (4,)
MESH_SMOKE_TURNS = 512


def main() -> int:
    import bench

    problems = []
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.bench_mesh(ways=MESH_SMOKE_WAYS,
                              turns=MESH_SMOKE_TURNS)
    captured = buf.getvalue()
    sys.stdout.write(captured)
    if rc != 0:
        problems.append(f"bench_mesh rc={rc} (parity gate failed?)")

    # ---- bench lines ---------------------------------------------------
    recs = []
    for line in captured.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            recs.append(json.loads(line))
        except ValueError:
            problems.append(f"unparseable bench line: {line[:80]!r}")
    names = {r.get("metric", "") for r in recs}
    for needed in ("scaling_efficiency_pct (strong, 4-way, 1024x1024)",
                   "halo_overlap_pct (strong, 4-way, 1024x1024)",
                   "scaling_efficiency_pct (weak, 4-way, 256x1024/dev)",
                   "halo_overlap_pct (weak, 4-way, 256x1024/dev)"):
        if needed not in names:
            problems.append(f"missing bench line {needed!r}")
    for r in recs:
        d = r.get("detail", {})
        if d.get("alive_parity") is not True:
            problems.append(f"parity not clean on {r.get('metric')!r}")
        mesh = d.get("mesh") or {}
        if mesh.get("devices") != 4 or mesh.get("shards") != 4 \
                or mesh.get("axes") != {"rows": 4}:
            problems.append(f"bad mesh geometry in detail: {mesh!r}")
        rows = (d.get("halo_traffic") or {}).get("rows") or {}
        if not rows.get("rounds") or not rows.get("bytes"):
            problems.append(f"no halo traffic in detail of "
                            f"{r.get('metric')!r}: {rows!r}")

    # ---- registry families hold real samples ---------------------------
    from gol_tpu.obs.metrics import REGISTRY

    samples = {}
    for line in REGISTRY.render_prometheus().splitlines():
        if line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            samples[key] = float(val)
        except ValueError:
            pass
    for key in ("gol_mesh_devices", "gol_mesh_shards",
                'gol_mesh_axis_size{axis="rows"}',
                'gol_halo_exchanges_total{axis="rows"}',
                'gol_halo_bytes_total{axis="rows"}',
                "gol_halo_exchange_seconds_count",
                "gol_shard_imbalance_ratio"):
        if samples.get(key, 0) <= 0:
            problems.append(f"registry sample not populated: {key!r} "
                            f"= {samples.get(key)}")

    # ---- /healthz mesh stamp -------------------------------------------
    from gol_tpu.obs import devstats

    mesh_f = devstats.healthz_fields().get("mesh") or {}
    if mesh_f.get("devices") != 4 or mesh_f.get("shards") != 4:
        problems.append(f"healthz mesh geometry: {mesh_f!r}")

    # ---- perf_compare gate round-trip ----------------------------------
    import perf_compare

    tmpdir = tempfile.mkdtemp(prefix="gol_mesh_smoke_")
    out_path = os.path.join(tmpdir, "mesh.jsonl")
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(captured)
    if perf_compare.main([os.path.join(_ROOT, "BASELINE.json"),
                          out_path]) != 0:
        problems.append("perf_compare gate failed on the mesh legs")

    if problems:
        for p in problems:
            print(f"mesh-smoke: FAIL: {p}", file=sys.stderr)
        return 1
    rows_bytes = int(samples.get('gol_halo_bytes_total{axis="rows"}', 0))
    hist_n = int(samples.get("gol_halo_exchange_seconds_count", 0))
    print(f"mesh-smoke: OK — {len(recs)} gated mesh line(s), "
          f"{rows_bytes} halo bytes counted on the rows axis, "
          f"{hist_n} exchange-latency sample(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
