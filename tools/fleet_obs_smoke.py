"""fleet-obs-smoke: prove the fleet telemetry plane end to end on CPU.

One acceptance scenario (PR 16), real member processes behind a real
in-process router:

  * three `--fleet --federate` servers heartbeat to a FederationRouter
    with telemetry snapshots riding the beats; runs created THROUGH
    the router are HRW-placed and parked at a target turn;
  * the router's rollup must agree EXACTLY with the per-member states
    it ingested at the same sweep — gol_fed_agg_runs_resident equals
    both the sum of the member table rows and the number of runs the
    fleet actually holds — and every heartbeat payload the registry
    saw must be within the GOL_FED_SNAPSHOT_MAX byte budget;
  * GetTelemetry / GetAudit answer over the wire (fleet doc, tsdb
    series points, monotonic gol-fleet-audit/1 join records), the
    router's /healthz carries the telemetry doc, and
    tools/fleet_top.py renders one dashboard frame headless;
  * one member is SIGKILLed: the member-death alert must FIRE within
    the detection budget, flip gol_alerts_active{rule="member-death"}
    to 1, and land both a member_death and an alert_fired record in
    the durable audit log on disk.

Exit 0 = pass.

    make fleet-obs-smoke    # bench.py --fleet-obs + gate, then this
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import signal
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.federation_smoke import (  # noqa: E402
    FED_ENV, spawn_member, wait_live, wait_member, wait_runs_at)

# SIGKILL -> firing alert: death verdict (GOL_FED_DEAD_AFTER 1.2 s)
# + one sweep (0.1 s) + alert evaluation (for_s=0 for member-death),
# with CI slack on a loaded CPU host.
DETECT_BUDGET_S = 15.0


def fail(msg: str) -> int:
    print(f"fleet-obs-smoke: FAIL — {msg}", flush=True)
    return 1


def read_audit_files(audit_dir: str) -> list:
    """Every gol-fleet-audit/1 record on disk, oldest first."""
    recs = []
    for name in sorted(os.listdir(audit_dir), reverse=True):
        if not name.startswith("audit.jsonl"):
            continue
        with open(os.path.join(audit_dir, name), encoding="utf-8") as f:
            recs.extend(json.loads(line) for line in f if line.strip())
    recs.sort(key=lambda r: r.get("seq", 0))
    return recs


def wait_telemetry(router, n_members: int, n_runs: int,
                   timeout: float = 60.0):
    """The telemetry doc once every member reports and the rollup
    holds all runs, or None."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = router.telemetry.doc()
        fleet = doc.get("fleet", {})
        if fleet.get("members_reporting") == n_members \
                and fleet.get("runs_resident") == n_runs:
            return doc
        time.sleep(0.2)
    return None


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("GOL_CHAOS", None)
    os.environ.update(FED_ENV)

    from gol_tpu.client import RemoteEngine
    from gol_tpu.federation.router import FederationRouter
    from gol_tpu.obs import catalog as obs
    from gol_tpu.obs.export import snapshot_budget
    from gol_tpu.obs.http import healthz_doc
    from tools import fleet_top

    tmpdir = tempfile.mkdtemp(prefix="gol_fleet_obs_smoke_")
    ckpt_root = os.path.join(tmpdir, "ck")
    audit_dir = os.path.join(tmpdir, "audit")
    # Runs get NO target turn: a parked run leaves the `resident`
    # state, and this smoke pins the resident-sum rollup — so the
    # boards keep stepping for the whole scenario.
    n_members, n_runs, placed_turn = 3, 5, 4

    router = FederationRouter(port=0,
                              audit_dir=audit_dir).start_background()
    procs = [spawn_member(tmpdir, ckpt_root, router.port)
             for _ in range(n_members)]
    members = {}
    try:
        for p in procs:
            addr = wait_member(p)
            if addr is None:
                return fail("a member never announced its port")
            members[addr] = p
        if not wait_live(router, n_members):
            return fail("registry never reached 3 live members")

        cli = RemoteEngine(f"127.0.0.1:{router.port}", timeout=60.0)
        rng = np.random.default_rng(16)
        run_ids = []
        for i in range(n_runs):
            rid = f"obs{i}"
            board = (rng.random((64, 64)) < 0.3).astype(np.uint8)
            cli.create_run(64, 64, board=board, run_id=rid,
                           ckpt_every=4)
            run_ids.append(rid)
        owners = wait_runs_at(cli, run_ids, placed_turn)
        if owners is None:
            return fail("runs never started stepping on their members")

        # ---- rollup exactness at one sweep -------------------------
        doc = wait_telemetry(router, n_members, n_runs)
        if doc is None:
            return fail(f"rollup never converged: "
                        f"{router.telemetry.doc().get('fleet')}")
        fleet = doc["fleet"]
        member_sum = sum(r["resident"] for r in doc["members"].values())
        if fleet["runs_resident"] != member_sum:
            return fail(f"rollup {fleet['runs_resident']} != member "
                        f"table sum {member_sum}")
        if fleet["runs_resident"] != n_runs:
            return fail(f"rollup {fleet['runs_resident']} != "
                        f"{n_runs} created runs")
        if obs.FED_AGG_RUNS_RESIDENT.value != n_runs:
            return fail("gol_fed_agg_runs_resident gauge disagrees")
        if fleet["cups"] < 0 or fleet["imbalance_ratio"] < 1.0:
            return fail(f"implausible rollup: {fleet}")
        print(f"fleet-obs-smoke: rollup exact — {n_runs} resident "
              f"across {n_members} members, imbalance "
              f"{fleet['imbalance_ratio']}", flush=True)

        # ---- every ingested heartbeat within the byte budget -------
        budget = snapshot_budget()
        p99 = obs.FED_AGG_PAYLOAD_BYTES.labels(q="p99").value
        if not p99 or p99 > budget:
            return fail(f"snapshot payload p99 {p99} outside "
                        f"(0, {budget}]")

        # ---- wire surface + dashboard ------------------------------
        tdoc = cli.get_telemetry(series="fleet.runs_resident")
        if tdoc.get("fleet", {}).get("runs_resident") != n_runs:
            return fail(f"GetTelemetry fleet doc wrong: "
                        f"{tdoc.get('fleet')}")
        if not tdoc.get("series", {}).get("points"):
            return fail("GetTelemetry returned no tsdb points for "
                        "fleet.runs_resident")
        joins = [r for r in cli.get_audit(limit=200)
                 if r["kind"] == "member_join"]
        if len(joins) != n_members:
            return fail(f"{len(joins)} member_join audit records, "
                        f"want {n_members}")
        seqs = [r["seq"] for r in joins]
        if seqs != sorted(seqs):
            return fail(f"audit seqs not monotonic: {seqs}")
        hz = healthz_doc().get("telemetry")
        if not hz or hz.get("fleet", {}).get("members_reporting") \
                != n_members:
            return fail(f"/healthz telemetry doc wrong: {hz}")
        frame_out = io.StringIO()
        with contextlib.redirect_stdout(frame_out):
            rc = fleet_top.main(
                ["--router", f"127.0.0.1:{router.port}", "--once"])
        frame = frame_out.getvalue()
        if rc != 0 or "fleet " not in frame or "MEMBER" not in frame:
            return fail(f"fleet_top --once rc={rc}, frame:\n{frame}")
        print("fleet-obs-smoke: wire + /healthz + fleet_top frame ok",
              flush=True)

        # ---- SIGKILL -> member-death alert + audit record ----------
        victim = owners[run_ids[0]]
        os.kill(members[victim].pid, signal.SIGKILL)
        members[victim].wait(10)
        t_kill = time.monotonic()
        while time.monotonic() - t_kill < DETECT_BUDGET_S:
            if "member-death" in router.telemetry.alerts.active():
                break
            time.sleep(0.05)
        detect_s = time.monotonic() - t_kill
        if "member-death" not in router.telemetry.alerts.active():
            return fail(f"member-death alert did not fire within "
                        f"{DETECT_BUDGET_S}s of SIGKILL")
        if obs.ALERTS_ACTIVE.labels(rule="member-death").value != 1:
            return fail("gol_alerts_active{rule=member-death} != 1")
        # The alert shows active the instant the sweep promotes it;
        # the audit append trails by a beat — poll the files.
        deadline = time.monotonic() + 10.0
        recs, kinds = [], []
        while time.monotonic() < deadline:
            recs = read_audit_files(audit_dir)
            kinds = [r["kind"] for r in recs]
            if "member_death" in kinds and "alert_fired" in kinds:
                break
            time.sleep(0.1)
        if "member_death" not in kinds:
            return fail(f"no member_death audit record on disk: "
                        f"{kinds}")
        death = next(r for r in recs if r["kind"] == "member_death")
        if death.get("member") != victim:
            return fail(f"member_death names {death.get('member')}, "
                        f"killed {victim}")
        fired = [r for r in recs if r["kind"] == "alert_fired"
                 and r.get("rule") == "member-death"]
        if not fired:
            return fail("no alert_fired audit record for member-death")
        if any(r.get("schema") != "gol-fleet-audit/1" for r in recs):
            return fail("audit records missing gol-fleet-audit/1 "
                        "schema stamp")
        print(f"fleet-obs-smoke: member-death fired {detect_s:.2f}s "
              f"after SIGKILL, audit log has {len(recs)} records",
              flush=True)
        print("fleet-obs-smoke: PASS", flush=True)
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(10)
        router.shutdown()


if __name__ == "__main__":
    sys.exit(main())
