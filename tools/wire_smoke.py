"""wire-smoke: prove the negotiated wire data plane end to end on CPU.

Boots a real EngineServer on a loopback TCP socket and drives it with
RemoteEngine clients, validating the full codec stack:

  * the capability handshake: a fresh client learns the server's caps
    from its first reply and the upload/snapshot path goes packed;
  * a packed snapshot decodes bit-identically to the uploaded board
    AND to a raw-u8 fetch by a capability-less client (GOL_WIRE_CAPS=""
    — the old-peer interop contract);
  * host bitpack parity: ops/bitpack.pack_np bytes == the device pack's
    little-endian word bytes, so client decode and device layout agree;
  * zlib framing round-trips and shrinks a sparse board;
  * the SDL live-view path: the second GetView poll of an unchanged
    board ships an xrle delta frame of 0 payload bytes;
  * the gol_wire_* metric families record frames, payload bytes, and
    bytes saved.

Runs IN-PROCESS (no subprocess) so counters are directly readable and
the run stays inside the tier-1 time budget. Exit 0 = pass.

    make wire-smoke     # JAX_PLATFORMS=cpu python tools/wire_smoke.py
"""

from __future__ import annotations

import os
import sys
import time

# Runnable as `python tools/wire_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("GOL_SERVER_EXIT_ON_KILL", "0")


def main() -> int:
    import numpy as np

    from gol_tpu import wire
    from gol_tpu.client import RemoteEngine
    from gol_tpu.engine import Engine
    from gol_tpu.obs import catalog as obs_cat
    from gol_tpu.ops.bitpack import pack, pack_np, words_bytes_np
    from gol_tpu.params import Params
    from gol_tpu.server import EngineServer

    problems = []
    rng = np.random.default_rng(0)

    # ---- host/device bitpack parity -----------------------------------
    import jax.numpy as jnp

    cells = (rng.random((37, 96)) < 0.4).astype(np.uint8)
    host_bytes = pack_np(cells * 255).tobytes()
    dev_bytes = words_bytes_np(
        np.asarray(pack(jnp.asarray(cells)))).tobytes()
    if host_bytes != dev_bytes:
        problems.append("pack_np bytes != device pack word bytes")

    # ---- negotiated server/client round-trip --------------------------
    n = 96
    world = (rng.random((n, n)) < 0.25).astype(np.uint8) * 255
    p = Params(threads=1, image_width=n, image_height=n, turns=0)
    srv = EngineServer(port=0, host="127.0.0.1", engine=Engine())
    srv.start_background()
    try:
        cli = RemoteEngine(f"127.0.0.1:{srv.port}")
        cli.ping()
        if cli.peer_caps != wire.SUPPORTED_CAPS:
            problems.append(f"handshake learned {sorted(cli.peer_caps)}, "
                            f"want {sorted(wire.SUPPORTED_CAPS)}")
        sent0 = obs_cat.WIRE_BYTES.labels(direction="sent").value
        out, _ = cli.server_distributor(p, world)
        got, _ = cli.get_world()
        packed_sent = (obs_cat.WIRE_BYTES.labels(direction="sent").value
                       - sent0)
        if not np.array_equal(out, world):
            problems.append("upload round-trip not bit-identical")
        if not np.array_equal(got, world):
            problems.append("packed snapshot not bit-identical")
        # upload + echo + snapshot, all framed: far below 3 raw boards
        if packed_sent >= 3 * n * n:
            problems.append(f"negotiated transfer moved {packed_sent} "
                            f"bytes — codecs not engaged?")

        os.environ["GOL_WIRE_CAPS"] = ""
        try:
            raw_cli = RemoteEngine(f"127.0.0.1:{srv.port}")
            raw, _ = raw_cli.get_world()
        finally:
            del os.environ["GOL_WIRE_CAPS"]
        if not np.array_equal(raw, world):
            problems.append("raw-u8 fallback fetch not bit-identical")

        # ---- zlib framing ---------------------------------------------
        frame = wire.encode_board(world, frozenset({wire.CAP_ZLIB}))
        if frame.codec != wire.CODEC_U8_ZLIB:
            problems.append(f"sparse board framed as {frame.codec}, "
                            "want u8+zlib")
        elif frame.nbytes >= n * n:
            problems.append("zlib frame did not shrink a sparse board")

        # ---- live-view xrle path --------------------------------------
        cli.get_view(n * n)
        before = obs_cat.WIRE_FRAMES.labels(codec="xrle").value
        v2, _, _ = cli.get_view(n * n)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and obs_cat.WIRE_FRAMES.labels(codec="xrle").value \
                <= before:
            time.sleep(0.01)
        if obs_cat.WIRE_FRAMES.labels(codec="xrle").value != before + 1:
            problems.append("second GetView poll did not ship xrle")
        if not np.array_equal(v2, world):
            problems.append("xrle view not bit-identical")
    finally:
        srv.shutdown()

    # ---- metric families ----------------------------------------------
    frames_total = sum(
        child.value
        for child in obs_cat.WIRE_FRAMES.children().values())
    if frames_total < 3:
        problems.append(f"gol_wire_frames_total = {frames_total}, "
                        "expected the run to meter frames")
    if obs_cat.WIRE_BYTES_SAVED.value <= 0:
        problems.append("gol_wire_bytes_saved_total never incremented")

    if problems:
        for msg in problems:
            print(f"wire-smoke: FAIL: {msg}", file=sys.stderr)
        return 1
    print(f"wire-smoke: OK — packed snapshot moved {int(packed_sent)} "
          f"bytes for a {n * n}-byte board (upload+echo+snapshot), "
          f"{int(frames_total)} codec frames metered, "
          f"{int(obs_cat.WIRE_BYTES_SAVED.value)} bytes saved")
    return 0


if __name__ == "__main__":
    sys.exit(main())
