"""obs-smoke: prove the observability plumbing end to end on CPU.

Runs a tiny board through the real CLI with `--run-report`,
`--metrics-port 0`, and `--trace-spans`, then validates ALL outputs:

  * the run report parses as schema gol-run-report/1 and contains at
    least one chunk record with wall/turns/CUPS populated, bracketed by
    run_start/run_end;
  * the `/metrics` endpoint serves parseable Prometheus text including
    the engine turn/CUPS gauges and the wire/server counter families;
  * the span export is a valid Chrome trace-event document whose
    controller.run / engine.run / engine.chunk spans share one trace id
    with correct parent links;
  * every metric family in the registry matches the Prometheus naming
    regex and carries the gol_ prefix.

Runs IN-PROCESS (main() is called, not subprocessed) so the ephemeral
metrics port is discoverable without output scraping, and stays inside
the tier-1 time budget. Exit 0 = pass.

    make obs-smoke      # JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile
import urllib.request

# Runnable as `python tools/obs_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def main() -> int:
    tmpdir = tempfile.mkdtemp(prefix="gol_obs_smoke_")
    report = os.path.join(tmpdir, "run.jsonl")
    spans_path = os.path.join(tmpdir, "spans.json")

    from gol_tpu.main import main as gol_main

    rc = gol_main(["-w", "64", "-h", "64", "--turns", "64",
                   "--rle", "rpentomino", "--headless", "-t", "1",
                   "--run-report", report, "--metrics-port", "0",
                   "--trace-spans", spans_path])
    if rc != 0:
        print(f"obs-smoke: CLI run failed rc={rc}", file=sys.stderr)
        return 1

    # ---- run report ----------------------------------------------------
    from gol_tpu.obs.timeline import read_report

    recs = list(read_report(report))  # raises on any schema violation
    events = [r["event"] for r in recs]
    chunks = [r for r in recs if r["event"] == "chunk"]
    problems = []
    if events[0] != "run_start" or events[-1] != "run_end":
        problems.append(f"bad bookends: {events[:1]} ... {events[-1:]}")
    if not chunks:
        problems.append("no chunk records")
    for c in chunks:
        if c["turns"] <= 0 or c["wall_s"] < 0 or c["cups"] < 0:
            problems.append(f"bad chunk record: {c}")
    if recs and recs[-1]["event"] == "run_end" and recs[-1]["turn"] != 64:
        problems.append(f"run_end turn {recs[-1]['turn']} != 64")

    # ---- /metrics ------------------------------------------------------
    from gol_tpu.obs.http import last_server

    srv = last_server()
    if srv is None:
        problems.append("metrics server did not start")
    else:
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        for needle in ("# TYPE gol_engine_turn gauge",
                       "# TYPE gol_engine_cups gauge",
                       "# TYPE gol_server_requests_total counter",
                       "# TYPE gol_wire_bytes_total counter",
                       "gol_engine_chunk_seconds_bucket"):
            if needle not in body:
                problems.append(f"/metrics missing {needle!r}")
        for line in body.splitlines():
            if line.startswith("gol_engine_turn "):
                if float(line.split()[-1]) != 64:
                    problems.append(f"engine turn gauge: {line!r}")
                break
        else:
            problems.append("no gol_engine_turn sample")
        srv.close()

    # ---- span export ---------------------------------------------------
    from gol_tpu.obs import trace

    n_span_events = 0
    if not os.path.exists(spans_path):
        problems.append("span export was not written")
    else:
        try:
            with open(spans_path, encoding="utf-8") as f:
                doc = json.load(f)
            trace.validate_chrome(doc)
            by_name = {}
            for evd in doc["traceEvents"]:
                if evd["ph"] in ("X", "B"):
                    n_span_events += 1
                    by_name.setdefault(evd["name"], []).append(evd["args"])
            for needed in ("controller.run", "engine.run", "engine.chunk"):
                if needed not in by_name:
                    problems.append(f"span export missing {needed!r}")
            if not problems:
                ctrl = by_name["controller.run"][0]
                erun = by_name["engine.run"][0]
                if erun["trace_id"] != ctrl["trace_id"] \
                        or erun.get("parent_id") != ctrl["span_id"]:
                    problems.append("engine.run not parented under "
                                    "controller.run")
                for ch in by_name["engine.chunk"]:
                    if ch["trace_id"] != ctrl["trace_id"] \
                            or ch.get("parent_id") != erun["span_id"]:
                        problems.append("engine.chunk not parented "
                                        "under engine.run")
                        break
        except (ValueError, KeyError) as e:
            problems.append(f"span export invalid: {e}")

    # ---- catalog naming ------------------------------------------------
    from gol_tpu.obs.metrics import REGISTRY

    for name in REGISTRY.families():
        if not PROM_NAME_RE.match(name):
            problems.append(f"metric name violates Prometheus regex: "
                            f"{name!r}")
        if not name.startswith("gol_"):
            problems.append(f"metric name missing gol_ prefix: {name!r}")

    if problems:
        for p in problems:
            print(f"obs-smoke: FAIL: {p}", file=sys.stderr)
        return 1
    print(f"obs-smoke: OK — {len(chunks)} chunk record(s), "
          f"/metrics served {len(body)} bytes, "
          f"{n_span_events} span event(s), "
          f"{len(REGISTRY.families())} metric families named cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
